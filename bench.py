"""Benchmark harness. Prints exactly ONE JSON line on stdout, always.

Headline metric (BASELINE.md §1): MNIST-CNN training samples/sec/chip —
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

Extra keys on the same object (diagnostics + secondary benches):
    platform      — backend actually used ("tpu" or "cpu" fallback)
    init_error    — TPU init failure that forced the CPU fallback, if any
    lm            — TransformerLM train-step bench (tokens/sec + MFU) at
                    2k and 8k tokens, flash attention, TPU only
    attn          — flash-vs-dense attention kernel microbench (fwd+bwd
                    ms/step and speedup) at 2k and 8k tokens, TPU only
    error         — fatal failure note; value stays 0.0 but the line still
                    parses (round-1 failure mode was rc=1 with NO output)

``vs_baseline``: the reference publishes no benchmark numbers (BASELINE.md
— "none recoverable"), so the ratio is against the recorded best of THIS
repo (bench_baseline.json).  First run: 1.0.

Data content doesn't affect throughput, so MNIST-shaped synthetic tensors
stand in for the real dataset in offline environments.
"""

from __future__ import annotations

import json
import os
import time
import traceback

# bf16 peak FLOPs/sec by device_kind prefix (public spec sheets)
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def _peak_flops(device_kind: str):
    for prefix, peak in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(prefix):
            return peak
    return None


def _init_backend(retries: int = 3, wait_s: float = 10.0):
    """Bring up whatever accelerator is visible; never raise.

    Round-1 failure mode (VERDICT weak #2): one transient 'Unable to
    initialize backend axon' aborted the whole bench with rc=1 and zero
    output.  Retry the default platform; if it never comes up, pin the CPU
    platform so the bench still emits a comparable (if slow) number.
    Returns (platform, init_error_or_None).
    """
    import jax

    last = None
    for attempt in range(retries):
        try:
            jax.devices()
            return jax.default_backend(), None
        except RuntimeError as e:  # backend init failure; not a bug in us
            last = e
            if attempt + 1 < retries:
                time.sleep(wait_s)
    from distkeras_tpu.platform import pin_cpu_devices

    pin_cpu_devices(1)
    return jax.default_backend(), f"{type(last).__name__}: {last}"


# v5e sweet spot from the 2026-07-30 in-program sweep (see _bench_mnist_cnn);
# the single source for both the bench config and the reported metadata
_MNIST_BATCH = 1024

# bump whenever the headline measurement itself changes (batch size, dispatch
# structure, ...); vs_baseline is only computed against a matching tag
_METHODOLOGY = "in-program-multi-epoch-v2"


def _bench_mnist_cnn(batch_size: int = _MNIST_BATCH, num_batches: int = 200, reps: int = 3,
                     repeat: int = 3):
    """Headline number: MNIST-CNN scan-epoch training throughput.

    All ``reps`` epochs run inside ONE compiled program (outer lax.scan over
    the inner per-batch scan): on the relayed axon platform each dispatch
    costs ~50-100ms of RPC latency, and the round-1 bench (one dispatch per
    epoch, host sync between) measured that latency, not the chip — moving
    the loop in-program took the same model from ~400k to ~1M samples/sec.
    batch 1024 is the measured v5e sweet spot (sweep 2026-07-30, in-program:
    512->765k, 1024->999k, 2048->565k, 4096->520k samples/sec; bf16 compute
    measured SLOWER than f32 here — the convs are too small to feed the
    MXU, so the layout conversions dominate).  Median of ``repeat`` timed
    runs so one contended run doesn't set the record."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.parallel.engine import make_minibatch_step

    spec = mnist_cnn_spec()
    model = Model.init(spec, seed=0)
    optimizer = optax.sgd(0.01, momentum=0.9)
    mini = make_minibatch_step(spec.apply_fn(), get_loss("categorical_crossentropy"), optimizer)

    @jax.jit
    def multi_epoch(params, opt_state, xs, ys):
        def epoch(carry, _):
            carry, losses = lax.scan(mini, carry, (xs, ys))
            return carry, losses[-1]

        (params, opt_state), last = lax.scan(
            epoch, (params, opt_state), None, length=reps)
        return params, opt_state, last

    rng = np.random.default_rng(0)
    xs_d = jnp.asarray(rng.normal(size=(num_batches, batch_size, 28, 28, 1)).astype(np.float32))
    ys_d = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=(num_batches, batch_size))])

    params = jax.tree.map(jnp.array, model.params)
    opt_state = optimizer.init(params)

    # warmup (compile + one full pass); host readback is the only reliable
    # completion barrier on relayed/remote platforms, where
    # block_until_ready can return before execution finishes
    _, _, last = multi_epoch(params, opt_state, xs_d, ys_d)
    np.asarray(last)

    samples = reps * num_batches * batch_size
    rates = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        _, _, last = multi_epoch(params, opt_state, xs_d, ys_d)
        np.asarray(last)
        rates.append(samples / (time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2] / jax.device_count()


def _bench_lm(seq_len: int, batch: int, *, model_dim: int = 512, num_heads: int = 8,
              num_layers: int = 8, vocab: int = 8192, steps: int = 10,
              remat: bool = False):
    """TransformerLM fwd+bwd train step: tokens/sec + MFU (flash attention).

    The loss path is the framework's fused unembed+CE
    (``ops.losses.unembed_cross_entropy``, same as ``make_lm_train_step``):
    the unembed matmul runs in bf16 at MXU rate and the [B, L, V] f32
    logits tensor is never materialized — on v5e this moved the 2k-token
    step from 0.28 to ~0.4 MFU by itself (round-3 sweep).

    MFU counts the matmul FLOPs the model *requires*: 6·T·P_matmul for the
    dense projections + unembed (fwd 2·T·P, bwd 2x) plus the causal
    attention term 6·n_layers·B·L²·E (4·B·L²·E fwd halved by causality,
    times 3 for fwd+bwd) — the standard PaLM-style accounting.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.ops.losses import lm_token_cross_entropy
    from distkeras_tpu.parallel.lm import shift_targets

    spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim, num_heads=num_heads,
                         num_layers=num_layers, max_seq_len=seq_len, remat=remat)
    model = Model.init(spec, seed=0)
    module = spec.build()
    opt = optax.sgd(0.01)

    def loss_fn(params, tok, tgt):
        ce = lm_token_cross_entropy(module, params, tok, tgt)
        return ce[:, :-1].mean()

    # the step loop lives INSIDE the compiled program: per-dispatch host
    # round trips (~100ms on the relayed axon platform) would otherwise
    # dominate and the bench would measure RPC latency, not the chip
    @jax.jit
    def run(params, opt_state, tok, tgt):
        def body(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=steps)
        return params, opt_state, losses

    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)), dtype=jnp.int32)
    tgt = jnp.asarray(shift_targets(np.asarray(tok)))
    params = jax.tree.map(jnp.array, model.params)
    opt_state = opt.init(params)

    params, opt_state, losses = run(params, opt_state, tok, tgt)  # compile
    np.asarray(losses)
    t0 = time.perf_counter()
    params, opt_state, losses = run(params, opt_state, tok, tgt)
    np.asarray(losses)
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq_len
    e = model_dim
    p_matmul = 12 * e * e * num_layers + e * vocab
    flops_per_step = (6 * tokens_per_step * p_matmul
                      + 6 * num_layers * batch * seq_len * seq_len * e)
    sec_per_step = dt / steps
    peak = _peak_flops(jax.devices()[0].device_kind)
    return {
        "seq_len": seq_len,
        "batch": batch,
        "tokens_per_sec": round(tokens_per_step / sec_per_step, 1),
        "ms_per_step": round(sec_per_step * 1e3, 2),
        "mfu": round(flops_per_step / sec_per_step / peak, 4) if peak else None,
    }


def _bench_attn(seq_len: int, *, batch: int = 2, heads: int = 8, head_dim: int = 64,
                steps: int = 50):
    """Kernel microbench: Pallas flash vs XLA dense attention, fwd+bwd.

    ``steps`` must be large enough to amortize the one-dispatch RPC cost of
    the relayed axon platform (~50-100ms): at steps=5 the 2k-token per-step
    figure read ~25ms when the kernel actually takes ~3.3ms."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.ops.attention import dense_attention
    from distkeras_tpu.ops.flash_attention import flash_attention

    from jax import lax

    rng = np.random.default_rng(0)
    shape = (batch, seq_len, heads, head_dim)
    q, k, v = (jnp.asarray(rng.normal(size=shape) * 0.1, dtype=jnp.bfloat16)
               for _ in range(3))

    def timed(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True).astype(jnp.float32))

        grad_fn = jax.grad(loss, argnums=(0, 1, 2))

        # loop inside the program (see _bench_lm); feeding each step's grad
        # back into q keeps the body loop-variant so XLA cannot hoist it
        @jax.jit
        def run(q, k, v):
            def body(q, _):
                gq, gk, gv = grad_fn(q, k, v)
                # all three grads must stay live or XLA DCEs the dv matmul
                # out of the dense backward (the fused flash VJP can't be
                # partially eliminated, which would skew the comparison)
                return q + 1e-6 * gq, (jnp.sum(gk) + jnp.sum(gv)).astype(jnp.float32)

            q, sums = lax.scan(body, q, None, length=steps)
            return sums

        np.asarray(run(q, k, v))  # compile
        t0 = time.perf_counter()
        np.asarray(run(q, k, v))
        return (time.perf_counter() - t0) / steps * 1e3  # ms

    flash_ms = timed(flash_attention)
    dense_ms = timed(dense_attention)
    return {
        "seq_len": seq_len,
        "flash_ms": round(flash_ms, 2),
        "dense_ms": round(dense_ms, 2),
        "flash_speedup": round(dense_ms / flash_ms, 2),
    }


def _bench_decode(*, batch: int = 8, prompt_len: int = 128, new_tokens: int = 256,
                  model_dim: int = 512, num_heads: int = 8, num_layers: int = 8,
                  vocab: int = 8192):
    """KV-cache autoregressive decode throughput (greedy), tokens/sec —
    three modes on the same model family: fp (bf16 activations, f32
    weights), int8 (weight-only quantized params), and speculative (a
    2-layer draft proposing k=4 tokens per target verification).

    The whole generation (prefill + ``new_tokens`` scanned single-token
    steps) is one compiled program, so the relay dispatch cost amortizes
    over the full sequence.  Speculative runs batch 1 (its decode path is
    single-sequence); its tokens/sec is NOT comparable to the batched fp
    number — compare via ``ms_per_token`` against a batch-1 fp run, which
    is also reported."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.decode import make_generate_fn
    from distkeras_tpu.models.speculative import make_speculative_generate_fn
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.ops.quantize import quantize_params

    spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim, num_heads=num_heads,
                         num_layers=num_layers, max_seq_len=prompt_len + new_tokens + 8)
    model = Model.init(spec, seed=0)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, vocab, (batch, prompt_len)), jnp.int32)
    key = jax.random.PRNGKey(0)

    def timed(fn, *args, reps: int = 2):
        np.asarray(fn(*args))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    out = {"batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens}

    fn = make_generate_fn(spec, new_tokens)
    dt = timed(fn, model.params, prompt, key)
    out["fp"] = {"tokens_per_sec": round(batch * new_tokens / dt, 1),
                 "ms_per_token": round(dt / new_tokens * 1e3, 3)}

    qparams = quantize_params(model.params)
    dt = timed(fn, qparams, prompt, key)
    out["int8"] = {"tokens_per_sec": round(batch * new_tokens / dt, 1),
                   "ms_per_token": round(dt / new_tokens * 1e3, 3)}

    # batch-1 legs: fp reference + speculative (draft = 2-layer same-width)
    dt = timed(fn, model.params, prompt[:1], key)
    out["fp_b1"] = {"tokens_per_sec": round(new_tokens / dt, 1),
                    "ms_per_token": round(dt / new_tokens * 1e3, 3)}
    draft_spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim,
                               num_heads=num_heads, num_layers=2,
                               max_seq_len=prompt_len + new_tokens + 8)
    draft = Model.init(draft_spec, seed=1)
    sfn = make_speculative_generate_fn(spec, draft_spec, new_tokens, k=4)
    dt = timed(sfn, model.params, draft.params, prompt[:1])
    out["speculative_b1"] = {"tokens_per_sec": round(new_tokens / dt, 1),
                             "ms_per_token": round(dt / new_tokens * 1e3, 3),
                             "draft_layers": 2, "k": 4}
    return out


# (seq_len, batch, model_dim, num_layers, steps) for the LM train legs.
# The 1024-dim/16-layer leg exists to show WHERE MFU saturates: the
# 512-dim legs are attention-VPU-bound at head_dim 64, the 1024-dim model
# (head_dim 128) has 4x the matmul work per attention score.  steps are
# sized so the ~100ms relay dispatch stays ~1-2% of the reported step.
# 32k HBM watch-out: in round 2 a 6-step 32k run inside the full bench
# (after the earlier legs' HBM pressure) once degraded ~25x to 24s/step;
# the fused backward's smaller footprint made 8 steps measure sane
# (692ms/step, round-3 full-bench run), but if the 32k leg ever reports a
# wildly slow step again, suspect HBM pressure from the preceding legs
# first and drop its step count back down.
_LM_LEGS = (
    (2048, 8, 512, 8, 100),
    (8192, 2, 512, 8, 50),
    (32768, 1, 512, 8, 8),
    (2048, 4, 1024, 16, 30),
)


def _leg_ratio(current: float, base: float):
    """current/base rounded, or None when either side is missing/zero."""
    if not current or not base:
        return None
    return round(current / base, 4)


def _apply_leg_baselines(out: dict, baseline: dict) -> None:
    """Attach per-leg ``vs_baseline`` ratios (throughput ratios, > 1 means
    faster than the recorded best) so an MFU/decode regression trips
    visibly.  Legs are matched by config key; a methodology or config
    change simply finds no match and reports no ratio."""
    for leg in out.get("lm", ()):
        key = f"lm:{leg.get('seq_len')}x{leg.get('batch')}:d{leg.get('model_dim', 512)}"
        base = baseline.get("legs", {}).get(key, {})
        r = _leg_ratio(leg.get("tokens_per_sec"), base.get("tokens_per_sec"))
        if r is not None:
            leg["vs_baseline"] = r
    for leg in out.get("attn", ()):
        key = f"attn:{leg.get('seq_len')}"
        base = baseline.get("legs", {}).get(key, {})
        # ms ratio inverted so > 1 still means "faster than baseline"
        r = _leg_ratio(base.get("flash_ms"), leg.get("flash_ms"))
        if r is not None:
            leg["vs_baseline"] = r
    dec = out.get("decode", {})
    for mode in ("fp", "int8", "fp_b1", "speculative_b1"):
        sub = dec.get(mode)
        base = baseline.get("legs", {}).get(f"decode:{mode}", {})
        if isinstance(sub, dict):
            r = _leg_ratio(sub.get("tokens_per_sec"), base.get("tokens_per_sec"))
            if r is not None:
                sub["vs_baseline"] = r


def main() -> None:
    out = {
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "value": 0.0,
        "unit": "samples/sec/chip",
        "vs_baseline": 0.0,
    }
    try:
        platform, init_error = _init_backend()
        out["platform"] = platform
        if init_error:
            out["init_error"] = init_error

        sps_per_chip = _bench_mnist_cnn()
        out["value"] = round(sps_per_chip, 1)
        out["batch_size"] = _MNIST_BATCH
        out["methodology"] = _METHODOLOGY

        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
        baseline = {}
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                baseline = json.load(f)
        vs = 1.0
        base = baseline.get("value")
        base_method = baseline.get("methodology")
        if base and baseline.get("platform", "tpu") != platform:
            # CPU-fallback throughput vs a TPU baseline is meaningless;
            # skip the ratio (keep 1.0) and flag why
            out["vs_baseline_note"] = (
                f"baseline recorded on {baseline.get('platform', 'tpu')}; "
                f"this run on {platform} — ratio not computed")
        elif base and base_method != _METHODOLOGY:
            # a ratio across bench-methodology changes measures the
            # measurement, not the chip (the round-2 dispatch-overhead
            # fix alone moved the same model 539k -> 934k)
            out["vs_baseline_note"] = (
                f"baseline methodology {base_method!r} != {_METHODOLOGY!r}"
                " — ratio not computed")
        elif base:
            vs = sps_per_chip / base
        out["vs_baseline"] = round(vs, 6)

        if platform == "tpu":
            import gc

            # secondary benches are TPU-only (flash is a Mosaic kernel) and
            # individually fallible — a failure is recorded, not fatal.
            # gc between legs drops dead device buffers promptly: HBM
            # pressure from earlier legs once blew the 32k LM leg up 25x
            gc.collect()
            lm, attn = [], []
            for seq, batch, model_dim, num_layers, steps in _LM_LEGS:
                try:
                    leg = _bench_lm(seq, batch, model_dim=model_dim,
                                    num_heads=8, num_layers=num_layers,
                                    steps=steps)
                    leg["model_dim"] = model_dim
                    lm.append(leg)
                except Exception as e:
                    lm.append({"seq_len": seq, "model_dim": model_dim,
                               "error": f"{type(e).__name__}: {e}"})
                gc.collect()
            for seq, steps in ((2048, 50), (8192, 25)):
                try:
                    attn.append(_bench_attn(seq, steps=steps))
                except Exception as e:
                    attn.append({"seq_len": seq, "error": f"{type(e).__name__}: {e}"})
                gc.collect()
            out["lm"] = lm
            out["attn"] = attn
            try:
                out["decode"] = _bench_decode()
            except Exception as e:
                out["decode"] = {"error": f"{type(e).__name__}: {e}"}
            _apply_leg_baselines(out, baseline)
    except Exception as e:
        out["value"] = 0.0  # contract: error lines carry the zero sentinel,
        out["vs_baseline"] = 0.0  # even if a sub-step already set a value
        out["error"] = f"{type(e).__name__}: {e}"
        out["traceback_tail"] = traceback.format_exc().strip().splitlines()[-3:]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
