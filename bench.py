"""Headline benchmark: MNIST-CNN training samples/sec/chip (BASELINE.md §1).

Prints exactly one JSON line:
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Runs on whatever accelerator is visible (the driver provides one real TPU
chip).  Data content doesn't affect throughput, so MNIST-shaped synthetic
tensors stand in for the real dataset in offline environments.

``vs_baseline``: the reference publishes no benchmark numbers
(BASELINE.md — "none recoverable"; upstream dist-keras ships no metric
table), so the ratio is against the recorded best of THIS repo
(bench_baseline.json, committed once established).  First run: 1.0.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.parallel.engine import scan_epoch_fn

    batch_size = 256
    num_batches = 200
    spec = mnist_cnn_spec()
    model = Model.init(spec, seed=0)
    optimizer = optax.sgd(0.01, momentum=0.9)
    epoch_fn = scan_epoch_fn(spec.apply_fn(), get_loss("categorical_crossentropy"), optimizer)

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(num_batches, batch_size, 28, 28, 1)).astype(np.float32)
    ys = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=(num_batches, batch_size))]
    xs_d, ys_d = jnp.asarray(xs), jnp.asarray(ys)

    params = jax.tree.map(jnp.array, model.params)
    opt_state = optimizer.init(params)

    # warmup (compile + one full pass); host readback is the only reliable
    # completion barrier on relayed/remote platforms, where
    # block_until_ready can return before execution finishes
    params, opt_state, losses = epoch_fn(params, opt_state, xs_d, ys_d)
    np.asarray(losses)

    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        params, opt_state, losses = epoch_fn(params, opt_state, xs_d, ys_d)
        np.asarray(losses)
    dt = time.perf_counter() - t0

    samples = reps * num_batches * batch_size
    sps = samples / dt
    n_chips = jax.device_count()
    sps_per_chip = sps / n_chips

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("value")
        if base:
            vs = sps_per_chip / base

    print(json.dumps({
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "value": round(sps_per_chip, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
