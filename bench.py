"""Benchmark harness. Prints exactly ONE JSON line on stdout, always.

Headline metric (BASELINE.md §1): MNIST-CNN training samples/sec/chip —
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

Extra keys on the same object (diagnostics + secondary benches):
    platform      — backend actually used ("tpu" or "cpu" fallback)
    init_error    — TPU init failure that forced the CPU fallback, if any
    lm            — TransformerLM train-step bench (tokens/sec + MFU) at
                    2k and 8k tokens, flash attention, TPU only
    attn          — flash-vs-dense attention kernel microbench (fwd+bwd
                    ms/step and speedup) at 2k and 8k tokens, TPU only
    error         — fatal failure note; value stays 0.0 but the line still
                    parses (round-1 failure mode was rc=1 with NO output)

``vs_baseline``: the reference publishes no benchmark numbers (BASELINE.md
— "none recoverable"), so the ratio is against the recorded best of THIS
repo (bench_baseline.json).  First run: 1.0.

Data content doesn't affect throughput, so MNIST-shaped synthetic tensors
stand in for the real dataset in offline environments.
"""

from __future__ import annotations

import json
import os
import time
import traceback

# bf16 peak FLOPs/sec by device_kind prefix (public spec sheets)
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def _peak_flops(device_kind: str):
    for prefix, peak in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(prefix):
            return peak
    return None


def _init_backend(retries: int = 3, wait_s: float = 10.0):
    """Bring up whatever accelerator is visible; never raise.

    Round-1 failure mode (VERDICT weak #2): one transient 'Unable to
    initialize backend axon' aborted the whole bench with rc=1 and zero
    output.  Retry the default platform; if it never comes up, pin the CPU
    platform so the bench still emits a comparable (if slow) number.
    Returns (platform, init_error_or_None).
    """
    import jax

    last = None
    for attempt in range(retries):
        try:
            jax.devices()
            return jax.default_backend(), None
        except RuntimeError as e:  # backend init failure; not a bug in us
            last = e
            if attempt + 1 < retries:
                time.sleep(wait_s)
    from distkeras_tpu.platform import pin_cpu_devices

    pin_cpu_devices(1)
    return jax.default_backend(), f"{type(last).__name__}: {last}"


# v5e sweet spot from the 2026-07-30 in-program sweep (see _bench_mnist_cnn),
# re-confirmed under bf16 (2026-07-31: 1024 -> 1.543M, 2048 -> 1.523M,
# 4096 -> 1.037M); the single source for both the bench config and the
# reported metadata
_MNIST_BATCH = 1024
# round-5 headline config: the compute_dtype="bfloat16" policy (bf16
# activations over f32 params, f32 logits — models/cnn.py) measured
# 1.35x the f32 headline (1.543M vs 1.140M samples/s/chip, device time).
# NOTE the history: round 2 measured "bf16 slower" and kept f32 — that
# experiment cast the whole model; the activations-only policy keeps the
# optimizer/params f32 and lets XLA fuse the casts into the convs.  The
# f32 number stays recorded next to the headline (mnist_cnn_f32).
_MNIST_DTYPE = "bfloat16"

# bump whenever the headline measurement itself changes (batch size, dispatch
# structure, timing source, ...); vs_baseline is only computed against a
# matching tag.  v3-device reads the program's on-device duration from a
# profiler trace (same shift the decode legs made in round 4): the v2 wall
# number swung +-10% with relay tenancy — the official round-4 captures of
# the SAME build read 956k and then 888k — while device time repeats to
# ~0.01%.  Falls back to the v2 wall tag when the trace has no module
# events (CPU runs), so a wall number can never ratio against the
# device-keyed baseline.
# v4: the headline CONFIG changed (bf16 compute_dtype policy, round 5) —
# per the rule above, the tag bumps so a v3-f32 record can never produce a
# bogus cross-config ratio in either direction
_METHODOLOGY = "in-program-multi-epoch-v4-device-bf16"
_METHODOLOGY_WALL = "in-program-multi-epoch-v2"


def _bench_mnist_cnn(batch_size: int = _MNIST_BATCH, num_batches: int = 200, reps: int = 3,
                     repeat: int = 3, compute_dtype=None):
    """Headline number: MNIST-CNN scan-epoch training throughput.
    Returns (samples_per_sec_per_chip, methodology_tag).

    All ``reps`` epochs run inside ONE compiled program (outer lax.scan over
    the inner per-batch scan): on the relayed axon platform each dispatch
    costs ~50-100ms of RPC latency, and the round-1 bench (one dispatch per
    epoch, host sync between) measured that latency, not the chip — moving
    the loop in-program took the same model from ~400k to ~1M samples/sec.
    batch 1024 is the measured v5e sweet spot (sweep 2026-07-30, in-program:
    512->765k, 1024->999k, 2048->565k, 4096->520k samples/sec; re-held
    under bf16 in round 5).  ``compute_dtype`` selects the model's
    mixed-precision policy: "bfloat16" (the round-5 headline) measured
    1.35x f32 — the round-2 "bf16 slower" finding applied to a
    whole-model cast, not the activations-only policy.  Timed on DEVICE
    time
    (median of ``repeat`` in-trace runs; see ``_device_time_ms``), wall
    fallback off-TPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.parallel.engine import make_minibatch_step

    spec = mnist_cnn_spec(compute_dtype=compute_dtype)
    model = Model.init(spec, seed=0)
    optimizer = optax.sgd(0.01, momentum=0.9)
    mini = make_minibatch_step(spec.apply_fn(), get_loss("categorical_crossentropy"), optimizer)

    @jax.jit
    def multi_epoch(params, opt_state, xs, ys):
        def epoch(carry, _):
            carry, losses = lax.scan(mini, carry, (xs, ys))
            return carry, losses[-1]

        (params, opt_state), last = lax.scan(
            epoch, (params, opt_state), None, length=reps)
        return params, opt_state, last

    rng = np.random.default_rng(0)
    xs_d = jnp.asarray(rng.normal(size=(num_batches, batch_size, 28, 28, 1)).astype(np.float32))
    ys_d = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=(num_batches, batch_size))])

    params = jax.tree.map(jnp.array, model.params)
    opt_state = optimizer.init(params)

    samples = reps * num_batches * batch_size
    # _device_time_ms warms up (compile + one full pass) outside the
    # trace, then returns the median on-device duration of `repeat`
    # in-trace runs — or the wall median when no module events exist
    # (CPU), which the returned tag records so the ratio logic can
    # refuse to compare it against a device-keyed baseline
    ms, _, source = _device_time_ms(
        lambda: multi_epoch(params, opt_state, xs_d, ys_d)[2],
        reps=repeat)
    method = _METHODOLOGY if source == "device" else _METHODOLOGY_WALL
    return samples / (ms / 1e3) / jax.device_count(), method


def _bench_lm(seq_len: int, batch: int, *, model_dim: int = 512, num_heads: int = 4,
              num_layers: int = 8, vocab: int = 8192, steps: int = 10,
              remat: bool = False):
    """TransformerLM fwd+bwd train step: tokens/sec + MFU (flash attention).

    ``num_heads`` is a real lever, not plumbing: at fixed model_dim the
    VPU softmax work per score is constant while the per-score matmul
    FLOPs scale with head_dim, so 4 heads x 128 head_dim halves the
    attention VPU-to-MXU ratio of 8 x 64 at identical total FLOPs — the
    round-3 hypothesis for why the 512-dim legs cap near 0.38 MFU while
    1024-dim (head_dim 128) reaches 0.47.

    The loss path is the framework's fused unembed+CE
    (``ops.losses.unembed_cross_entropy``, same as ``make_lm_train_step``):
    the unembed matmul runs in bf16 at MXU rate and the [B, L, V] f32
    logits tensor is never materialized — on v5e this moved the 2k-token
    step from 0.28 to ~0.4 MFU by itself (round-3 sweep).

    MFU counts the matmul FLOPs the model *requires*: 6·T·P_matmul for the
    dense projections + unembed (fwd 2·T·P, bwd 2x) plus the causal
    attention term 6·n_layers·B·L²·E (4·B·L²·E fwd halved by causality,
    times 3 for fwd+bwd) — the standard PaLM-style accounting.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.ops.losses import lm_token_cross_entropy
    from distkeras_tpu.parallel.lm import shift_targets

    spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim, num_heads=num_heads,
                         num_layers=num_layers, max_seq_len=seq_len, remat=remat)
    model = Model.init(spec, seed=0)
    module = spec.build()
    opt = optax.sgd(0.01)

    def loss_fn(params, tok, tgt):
        ce = lm_token_cross_entropy(module, params, tok, tgt)
        return ce[:, :-1].mean()

    # the step loop lives INSIDE the compiled program: per-dispatch host
    # round trips (~100ms on the relayed axon platform) would otherwise
    # dominate and the bench would measure RPC latency, not the chip
    @jax.jit
    def run(params, opt_state, tok, tgt):
        def body(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=steps)
        return params, opt_state, losses

    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)), dtype=jnp.int32)
    tgt = jnp.asarray(shift_targets(np.asarray(tok)))
    params = jax.tree.map(jnp.array, model.params)
    opt_state = opt.init(params)

    def run_all():
        _, _, losses = run(params, opt_state, tok, tgt)
        return losses

    # on-device duration (see _device_time_ms): at 8k the ~10-110ms relay
    # dispatch was 1-6% of the 1.75s wall — enough to misstate MFU
    dev_ms, _, source = _device_time_ms(run_all, reps=2)
    dt = dev_ms / 1e3

    tokens_per_step = batch * seq_len
    e = model_dim
    p_matmul = 12 * e * e * num_layers + e * vocab
    flops_per_step = (6 * tokens_per_step * p_matmul
                      + 6 * num_layers * batch * seq_len * seq_len * e)
    sec_per_step = dt / steps
    peak = _peak_flops(jax.devices()[0].device_kind)
    return {
        "seq_len": seq_len,
        "batch": batch,
        "tokens_per_sec": round(tokens_per_step / sec_per_step, 1),
        "ms_per_step": round(sec_per_step * 1e3, 2),
        "timing": source,
        "mfu": round(flops_per_step / sec_per_step / peak, 4) if peak else None,
    }


def _ab_kernel_ms(flash_loss, dense_loss, steps: int, q, k, v):
    """Shared flash-vs-dense A/B harness for the attn and ring legs:
    per-step on-device ms for both impls via ``_grad_scan_runner`` +
    ``_device_time_ms``.  Returns (flash_ms, dense_ms, timing, speedup);
    ``speedup`` is None when the two sides resolved to DIFFERENT timing
    sources (one device, one wall fallback) — a wall/device ratio would
    fold the relay dispatch share into a "kernel speedup"."""
    def one(loss):
        run = _grad_scan_runner(loss, steps)
        ms, _, src = _device_time_ms(run, q, k, v, reps=2)
        return ms / steps, src

    f_ms, f_src = one(flash_loss)
    d_ms, d_src = one(dense_loss)
    timing = "device" if f_src == d_src == "device" else "wall"
    speedup = round(d_ms / f_ms, 2) if f_src == d_src else None
    return f_ms, d_ms, timing, speedup


def _grad_scan_runner(loss_fn, steps: int):
    """Jitted fwd+bwd timing harness shared by the attn and ring benches:
    ``steps`` gradient steps inside ONE program (lax.scan), feeding each
    step's q-grad back into q so the body stays loop-variant (XLA cannot
    hoist it) and keeping ALL THREE grads live — without the gk/gv sum XLA
    DCEs the dv matmul out of the dense backward while the fused flash VJP
    can't be partially eliminated, which would skew the comparison."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    grad_fn = jax.grad(loss_fn, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(q, _):
            gq, gk, gv = grad_fn(q, k, v)
            return q + 1e-6 * gq, (jnp.sum(gk) + jnp.sum(gv)).astype(jnp.float32)

        q, sums = lax.scan(body, q, None, length=steps)
        return sums

    return run


def _bench_attn(seq_len: int, *, batch: int = 2, heads: int = 8, head_dim: int = 64,
                steps: int = 50):
    """Kernel microbench: Pallas flash vs XLA dense attention, fwd+bwd.

    On-device timing (``_device_time_ms``) like every other kernel leg —
    the wall variant of this bench is where the round-3 "flash needs
    B*L >= 16k tokens" misread came from."""
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.ops.attention import dense_attention
    from distkeras_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    shape = (batch, seq_len, heads, head_dim)
    q, k, v = (jnp.asarray(rng.normal(size=shape) * 0.1, dtype=jnp.bfloat16)
               for _ in range(3))

    def loss_of(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True).astype(jnp.float32))

        return loss

    flash_ms, dense_ms, timing, speedup = _ab_kernel_ms(
        loss_of(flash_attention), loss_of(dense_attention), steps, q, k, v)
    return {
        "seq_len": seq_len,
        "flash_ms": round(flash_ms, 3),
        "dense_ms": round(dense_ms, 3),
        "flash_speedup": speedup,
        "timing": timing,
    }


def _trace_jit_durs(trace_dir: str):
    """All on-device ``jit_*`` XLA-module event durations (ms) found in a
    ``jax.profiler.trace`` output directory — the single home of the trace
    parsing shared by ``_device_time_ms`` (median-of-reps) and
    ``_bench_async`` (sum over a whole run)."""
    import glob
    import gzip
    import os as _os

    durs = []
    for tf in glob.glob(_os.path.join(trace_dir, "**", "*.trace.json.gz"),
                        recursive=True):
        with gzip.open(tf, "rt") as fh:
            data = json.load(fh)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") == "X" and ev.get("name", "").startswith("jit_"):
                durs.append(ev["dur"] / 1e3)
    return durs


def _device_time_ms(fn, *args, reps: int = 3):
    """(median ms per call, wall spread, source) for ``fn(*args)`` where
    ``source`` is ``"device"`` (profiler module events) or ``"wall"`` (the
    fallback) — callers must surface the source in their methodology tag
    so a wall fallback can never match a device-keyed baseline.

    Wall-clock on the relayed axon platform carries a ~10-110ms dispatch
    cost that swings with tenancy — for sub-second programs (every decode
    leg) that noise DOMINATED the round-3 numbers and fired a false
    regression tripwire (BENCH_r03 fp 0.78x).  The on-device duration of
    the program's ``jit_*`` XLA-module event, read from a
    ``jax.profiler.trace``, is stable to ~0.01% run-to-run (measured
    2026-07-31: three reps of the decode program within 5us of each
    other), so per-leg ``vs_baseline`` tripwires key on device time.
    Falls back to wall time when the trace has no module events (CPU
    interpret paths in tests)."""
    import tempfile

    import jax
    import numpy as np

    def once():
        r = fn(*args)
        np.asarray(r[0] if isinstance(r, tuple) else r)

    once()  # compile + warm outside the trace
    walls = []
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(reps):
                t0 = time.perf_counter()
                once()
                walls.append(time.perf_counter() - t0)
        durs = _trace_jit_durs(td)
    import statistics

    wall_med = statistics.median(walls)
    spread = round((max(walls) - min(walls)) / wall_med, 3) if wall_med else 0.0
    # the timed program is the section's only dispatch, so its reps are the
    # largest module events in the trace
    durs = sorted(durs)[-reps:]
    if len(durs) < reps:
        # the caller must TAG the number as wall time — a wall number under
        # a device-keyed baseline would fire the exact false tripwire this
        # helper exists to kill
        return wall_med * 1e3, spread, "wall"
    return statistics.median(durs), spread, "device"


def _train_decode_pair(spec, draft_spec, vocab: int, *, steps: int = 300,
                       batch: int = 16, seq: int = 256, seed: int = 0):
    """Teach the decode target AND draft the same predictable next-token
    structure so speculative acceptance is realistic (round-3 verdict task
    1b: a random-weights draft agrees with a random-weights target ~never,
    which measures nothing).

    The task: tokens follow a fixed random successor map with 10% uniform
    noise — the optimal greedy predictor is the map itself, learnable by
    both the 8-layer target and the small draft, so their greedy argmaxes
    agree wherever both learned the map.  Returns (target_params,
    draft_params); training runs as one compiled scan per model."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.ops.losses import lm_token_cross_entropy

    rng = np.random.default_rng(seed)
    succ = rng.permutation(vocab)
    toks = np.empty((steps, batch, seq), np.int64)
    cur = rng.integers(0, vocab, (steps, batch))
    for t in range(seq):
        toks[:, :, t] = cur
        nxt = succ[cur]
        noise = rng.random((steps, batch)) < 0.10
        cur = np.where(noise, rng.integers(0, vocab, (steps, batch)), nxt)
    tok_d = jnp.asarray(toks, jnp.int32)

    from distkeras_tpu.parallel.lm import shift_targets
    tgt_d = jnp.asarray(shift_targets(toks).astype(np.int32))

    def fit(spec_, seed_):
        module = spec_.build()
        model = Model.init(spec_, seed=seed_)
        opt = optax.adam(1e-3)

        def loss_fn(params, tok, tgt):
            return lm_token_cross_entropy(module, params, tok, tgt)[:, :-1].mean()

        @jax.jit
        def run(params, opt_state):
            def body(carry, data):
                params, opt_state = carry
                loss, grads = jax.value_and_grad(loss_fn)(params, *data)
                updates, opt_state = opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (tok_d, tgt_d))
            return params, losses

        params, losses = run(model.params, opt.init(model.params))
        np.asarray(losses)
        return params

    return fit(spec, seed_=0), fit(draft_spec, seed_=1)


def _bench_decode(*, batch: int = 8, prompt_len: int = 128, new_tokens: int = 512,
                  model_dim: int = 512, num_heads: int = 8, num_layers: int = 8,
                  vocab: int = 8192, reps: int = 3, train_steps: int = 300):
    """KV-cache autoregressive decode throughput (greedy), tokens/sec —
    fp (bf16 activations, f32 weights), int8 (weight-only quantized
    params), and speculative (small draft proposing k=4 tokens per target
    verification, with TRAINED target+draft so acceptance is real).

    The whole generation (prefill + ``new_tokens`` scanned single-token
    steps) is one compiled program.  Round-3 verdict weak #1: min-of-2
    WALL timing over ~0.1s generations swung ±30-60% with relay tenancy
    (a fixed ~10-110ms dispatch cost on sub-second programs) and fired a
    false 0.78x regression tripwire — every leg now reports the ON-DEVICE
    median (``_device_time_ms``; run-to-run stable to ~0.01%) plus the
    wall ``spread`` as a tenancy indicator.  Measured decomposition
    (2026-07-31, fp_b1): 45.5ms device + ~110ms relay in a 156ms wall.

    Speculative legs come in both shapes: batch-1 (compare against
    fp_b1_trained) and full-batch lockstep-commit (compare against
    fp_trained — the batched plain decode of the SAME trained weights).
    b1 decode at this scale is bound by per-op launch overhead, NOT
    weight bandwidth (storing weights bf16/int8 moves b1 <3%), which is
    why the draft's value is cutting sequential target steps, not
    FLOPs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.decode import make_generate_fn
    from distkeras_tpu.models.speculative import make_speculative_generate_fn
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.ops.quantize import quantize_params

    max_len = prompt_len + new_tokens + 16
    spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim, num_heads=num_heads,
                         num_layers=num_layers, max_seq_len=max_len)
    model = Model.init(spec, seed=0)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, vocab, (batch, prompt_len)), jnp.int32)
    key = jax.random.PRNGKey(0)

    sources = []

    def leg(timing, n=new_tokens, **extra):
        ms, spread, source = timing
        sources.append(source)
        dt = ms / 1e3
        return {"tokens_per_sec": round(n / dt, 1),
                "ms_per_token": round(dt / n * 1e3, 4),
                "wall_spread": spread, **extra}

    out = {"batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens}

    # label every plain-decode leg with the step impl that actually ran
    # (same resolver as make_generate_fn's auto path): if a config change
    # ever flips a leg onto the fused kernel, the record says so instead
    # of silently switching the speedup denominators
    from distkeras_tpu.ops.decode_step import resolve_step_impl
    step_b = resolve_step_impl(spec.config, batch, prompt_len + new_tokens, None)
    step_b1 = resolve_step_impl(spec.config, 1, prompt_len + new_tokens, None)

    fn = make_generate_fn(spec, new_tokens)
    out["fp"] = leg(_device_time_ms(fn, model.params, prompt, key, reps=reps),
                    n=batch * new_tokens, step_impl=step_b)

    qparams = quantize_params(model.params)
    out["int8"] = leg(_device_time_ms(fn, qparams, prompt, key, reps=reps),
                      n=batch * new_tokens, step_impl=step_b)

    out["fp_b1"] = leg(_device_time_ms(fn, model.params, prompt[:1], key, reps=reps),
                       step_impl=step_b1)

    # high-throughput serving pair at batch 64: plain bf16-cache decode
    # saturates near 33k tok/s (per-row KV reads grow linearly with
    # batch) while the int8 KV cache (QKVCache) halves that traffic and
    # un-saturates the curve — 62.5k tok/s, 1.91x at b64 (v5e device
    # time, 2026-07-31; crossover ~b12: int8 LOSES at b1/b8 where the
    # quantize-on-write op overhead outweighs the read savings).  Greedy
    # agreement on the trained pair measured 100% over 2048 tokens.
    big = 64
    prompt_big = jnp.asarray(rng.integers(0, vocab, (big, prompt_len)),
                             jnp.int32)
    out["fp_b64"] = leg(
        _device_time_ms(fn, model.params, prompt_big, key, reps=reps),
        n=big * new_tokens,
        step_impl=resolve_step_impl(spec.config, big,
                                    prompt_len + new_tokens, None))
    qfn = make_generate_fn(spec, new_tokens, quantize_cache=True)
    out["kv_int8_b64"] = leg(
        _device_time_ms(qfn, model.params, prompt_big, key, reps=reps),
        n=big * new_tokens, kv_cache="int8")

    # GQA at serving batch (round 5): 8 query heads sharing 2 KV heads
    # cuts the cache — and with it the per-step read traffic that
    # saturates batched decode — 4x; composed with the int8 cache the
    # KV bytes drop 8x vs the bf16 MHA baseline.  Same 512-dim/8L
    # architecture otherwise; weight content doesn't affect throughput
    # (measured for the trained/untrained pairs above)
    gqa_kv = max(1, num_heads // 4)
    gqa_spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim,
                             num_heads=num_heads, num_kv_heads=gqa_kv,
                             num_layers=num_layers, max_seq_len=max_len)
    gqa_model = Model.init(gqa_spec, seed=0)
    gfn = make_generate_fn(gqa_spec, new_tokens)
    out["fp_b64_gqa"] = leg(
        _device_time_ms(gfn, gqa_model.params, prompt_big, key, reps=reps),
        n=big * new_tokens, kv_heads=gqa_kv)
    qgfn = make_generate_fn(gqa_spec, new_tokens, quantize_cache=True)
    out["kv_int8_b64_gqa"] = leg(
        _device_time_ms(qgfn, gqa_model.params, prompt_big, key, reps=reps),
        n=big * new_tokens, kv_heads=gqa_kv, kv_cache="int8")

    # speculative leg: TRAINED 8-layer target + small draft on a
    # predictable task (see _train_decode_pair) — acceptance_rate is part
    # of the leg; a random-weights pair would report ~0 acceptance and the
    # number would mean nothing.  k=8/draft 2L-128 from the 2026-07-31
    # device-time sweep: 29.9k tok/s vs fp_b1's 11.2k (2.66x) with the
    # XLA draft; the fused Pallas draft step (ops/decode_step.py, auto-
    # selected at batch 1 for draft-sized models) lifted it to 40.6k
    # (3.6x) the same day — the leg records which draft step ran
    draft_dim = min(128, model_dim)
    draft_spec = small_lm_spec(vocab_size=vocab, model_dim=draft_dim,
                               num_heads=min(2, num_heads), num_layers=2,
                               max_seq_len=max_len)
    t_params, d_params = _train_decode_pair(spec, draft_spec, vocab,
                                            steps=train_steps)
    k = 8
    # the SAME resolver the generate fn's auto path runs (imported above),
    # so the recorded label can never drift from the implementation that
    # produced the number
    draft_impl = resolve_step_impl(
        draft_spec.config, 1, prompt_len + new_tokens + k + 1, None)
    sfn = make_speculative_generate_fn(spec, draft_spec, new_tokens, k=k,
                                       with_stats=True)
    toks, iters = sfn(t_params, d_params, prompt[:1])
    np.asarray(toks)
    # the while-loop commits new_tokens - 1 tokens (the first comes from
    # the prefill, before the loop), m + 1 per round -> mean m =
    # (n-1)/iters - 1.  The final round may be truncated by the n bound,
    # so clamp to [0, 1] rather than report a boundary artifact
    acceptance = ((new_tokens - 1) / max(int(iters), 1) - 1.0) / k
    acceptance = min(max(acceptance, 0.0), 1.0)
    out["speculative_b1"] = leg(
        _device_time_ms(sfn, t_params, d_params, prompt[:1], reps=reps),
        draft_layers=2, draft_dim=draft_dim, k=k, draft_step=draft_impl,
        acceptance_rate=round(float(acceptance), 3), trained=True)
    # the same trained target through the PLAIN decode path: the apples-to-
    # apples denominator for the speculative speedup claim (weights don't
    # change plain-decode cost, but report it measured, not assumed)
    out["fp_b1_trained"] = leg(_device_time_ms(fn, t_params, prompt[:1], key,
                                               reps=reps), step_impl=step_b1)
    spec_ratio = (out["speculative_b1"]["tokens_per_sec"]
                  / out["fp_b1_trained"]["tokens_per_sec"])
    out["speculative_speedup_vs_fp_b1"] = round(spec_ratio, 3)

    # batched speculative (lockstep min-prefix commit, models/speculative
    # .py): the same draft/verify program over the full batch — at batch 8
    # /k=8 the committed-token rate is 2.6x the plain batched decode on
    # the trained pair (v5e 2026-07-31; k=12 reached 3.2x, recorded in
    # BASELINE.md — k stays 8 here to match the b1 leg)
    toks, iters = sfn(t_params, d_params, prompt)
    np.asarray(toks)
    acc_b = ((new_tokens - 1) / max(int(iters), 1) - 1.0) / k
    out["speculative_batched"] = leg(
        _device_time_ms(sfn, t_params, d_params, prompt, reps=reps),
        n=batch * new_tokens, draft_layers=2, draft_dim=draft_dim, k=k,
        draft_step=resolve_step_impl(
            draft_spec.config, batch, prompt_len + new_tokens + k + 1, None),
        acceptance_rate=round(float(min(max(acc_b, 0.0), 1.0)), 3),
        trained=True)
    # the speedup denominator is the plain batched decode of the SAME
    # trained weights (like fp_b1_trained for the b1 claim): weight-
    # independence of plain decode cost is measured, never assumed
    out["fp_trained"] = leg(_device_time_ms(fn, t_params, prompt, key,
                                            reps=reps), n=batch * new_tokens,
                            step_impl=step_b)
    out["speculative_speedup_vs_fp_batched"] = round(
        out["speculative_batched"]["tokens_per_sec"]
        / out["fp_trained"]["tokens_per_sec"], 3)

    # k=12 promoted from round-4 prose (82.0k tok/s then): a longer draft
    # window commits more tokens per target pass while trained-pair
    # acceptance stays high; recorded + tripwired like every other leg
    k12 = 12
    sfn12 = make_speculative_generate_fn(spec, draft_spec, new_tokens, k=k12,
                                         with_stats=True)
    toks, iters12 = sfn12(t_params, d_params, prompt)
    np.asarray(toks)
    acc12 = ((new_tokens - 1) / max(int(iters12), 1) - 1.0) / k12
    out["speculative_k12"] = leg(
        _device_time_ms(sfn12, t_params, d_params, prompt, reps=reps),
        n=batch * new_tokens, draft_layers=2, draft_dim=draft_dim, k=k12,
        draft_step=resolve_step_impl(
            draft_spec.config, batch, prompt_len + new_tokens + k12 + 1, None),
        acceptance_rate=round(float(min(max(acc12, 0.0), 1.0)), 3),
        trained=True)

    # b64 lockstep speculative, bf16 vs int8 KV caches: at this batch the
    # per-row KV reads are the dominant decode cost (the plain fp_b64 ->
    # kv_int8_b64 pair measured 1.91x), so halving cache traffic should
    # compound with the draft's sequential-step savings — measured, not
    # assumed, incl. the lockstep acceptance decay at 64 rows
    toks, iters64 = sfn(t_params, d_params, prompt_big)
    np.asarray(toks)
    acc64 = ((new_tokens - 1) / max(int(iters64), 1) - 1.0) / k
    out["speculative_b64"] = leg(
        _device_time_ms(sfn, t_params, d_params, prompt_big, reps=reps),
        n=big * new_tokens, draft_layers=2, draft_dim=draft_dim, k=k,
        draft_step=resolve_step_impl(
            draft_spec.config, big, prompt_len + new_tokens + k + 1, None),
        acceptance_rate=round(float(min(max(acc64, 0.0), 1.0)), 3),
        trained=True)
    qsfn = make_speculative_generate_fn(spec, draft_spec, new_tokens, k=k,
                                        with_stats=True, quantize_cache=True)
    toks, qiters64 = qsfn(t_params, d_params, prompt_big)
    np.asarray(toks)
    qacc64 = ((new_tokens - 1) / max(int(qiters64), 1) - 1.0) / k
    out["speculative_kv_int8_b64"] = leg(
        _device_time_ms(qsfn, t_params, d_params, prompt_big, reps=reps),
        n=big * new_tokens, draft_layers=2, draft_dim=draft_dim, k=k,
        kv_cache="int8",
        acceptance_rate=round(float(min(max(qacc64, 0.0), 1.0)), 3),
        trained=True)
    # one wall fallback anywhere taints the whole section's tag: a wall
    # number under a device-keyed baseline is the false-tripwire class
    # this methodology change exists to kill
    source = "device" if all(s == "device" for s in sources) else "wall"
    out["timing"] = f"{source}-median-of-{reps}"
    return out


# (seq_len, batch, model_dim, num_layers, num_heads, steps) for the LM
# train legs.  The head-dim pairs are the controlled experiment the
# round-3 verdict asked for, and it is conclusive (v5e DEVICE time,
# 2026-07-31): at IDENTICAL FLOPs, head_dim 128 (4 heads at 512-dim)
# reaches 0.577 MFU at 2k and 0.515 at 8k where head_dim 64 (8 heads)
# caps at 0.389 / 0.295.  The bound at head_dim 64 is structural, not a
# schedule problem: the attention matmuls contract over 64 — HALF the
# MXU's 128-wide systolic dimension — and carry twice the per-score
# VPU/stat overhead per matmul FLOP; a block re-sweep under the fused
# backward moved the 8k-h8 leg < 1%.  The 1024-dim/16-layer leg (head_dim
# 128, 0.689 MFU) shows the same effect at scale.  steps are sized so
# dispatch overhead stays negligible even in wall terms; timings are
# on-device regardless.
# 32k HBM watch-out: in round 2 a 6-step 32k run inside the full bench
# (after the earlier legs' HBM pressure) once degraded ~25x to 24s/step;
# the fused backward's smaller footprint made 8 steps measure sane
# (692ms/step, round-3 full-bench run), but if the 32k leg ever reports a
# wildly slow step again, suspect HBM pressure from the preceding legs
# first and drop its step count back down.
_LM_LEGS = (
    # HEADLINE rows: head_dim 128 (4 heads at 512-dim) — the recommended
    # and now-default config (models/transformer.py); the h8/head_dim-64
    # rows below stay as the controlled comparison
    (2048, 8, 512, 8, 4, 100),
    (8192, 2, 512, 8, 4, 50),
    (32768, 1, 512, 8, 4, 8),
    (2048, 4, 1024, 16, 8, 30),
    # comparison rows: head_dim 64 (the pre-round-5 default)
    (2048, 8, 512, 8, 8, 100),
    (8192, 2, 512, 8, 8, 50),
    (32768, 1, 512, 8, 8, 8),
)


def _bench_ring(l_local: int, *, batch: int = 1, heads: int = 8,
                head_dim: int = 64, steps: int = 30):
    """Ring-attention PER-BLOCK compute: flash kernel vs dense XLA on one
    [B, l_local, H, D] block, fwd+bwd — the measurement behind
    ``ring_attention``'s auto-select threshold (``ops/attention.py ::
    ring_block_impl``: flash when l_local * head_dim >= 2048 * 64 —
    the crossover tracks per-block work, not length).  Round-3 verdict
    task 4: these crossover numbers lived only in a docstring with no
    tripwire; now they are bench legs with ``vs_baseline``, so threshold
    drift after a kernel change trips visibly.

    The timed work mirrors one LIVE ring step: block attention WITH the
    logsumexp output (the ring merge needs it) and full gradients.
    Times are ON-DEVICE (``_device_time_ms``): at these ~3-10ms/step
    scales a wall reading would carry ~30-100% relay-dispatch noise."""
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.default_rng(0)
    shape = (batch, l_local, heads, head_dim)
    q, k, v = (jnp.asarray(rng.normal(size=shape) * 0.1, dtype=jnp.bfloat16)
               for _ in range(3))

    def dense_with_lse(q, k, v, causal=True):
        # the dense branch of ring_attention.block_attn: f32 scores, (o, lse)
        scale = 1.0 / jnp.sqrt(jnp.float32(head_dim))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        if causal:
            pos = jnp.arange(l_local)
            logits = jnp.where((pos[:, None] >= pos[None, :])[None, None],
                               logits, -jnp.inf)
        m = jnp.max(logits, axis=-1)
        p = jnp.exp(logits - m[..., None])
        l_sum = jnp.sum(p, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        return (o / l_sum.transpose(0, 2, 1)[..., None]).astype(q.dtype), \
            m + jnp.log(l_sum)

    def loss_of(fn):
        def loss(q, k, v):
            o, lse = fn(q, k, v, causal=True)
            # both outputs live (the ring merge differentiates through lse)
            return jnp.sum(o.astype(jnp.float32)) + 1e-3 * jnp.sum(lse)

        return loss

    from distkeras_tpu.ops.attention import ring_block_impl

    flash_ms, dense_ms, timing, speedup = _ab_kernel_ms(
        loss_of(flash_attention_with_lse), loss_of(dense_with_lse),
        steps, q, k, v)
    return {
        "l_local": l_local,
        "batch": batch,
        "heads": heads,
        "head_dim": head_dim,
        "flash_ms": round(flash_ms, 3),
        "dense_ms": round(dense_ms, 3),
        "flash_speedup": speedup,
        "timing": timing,
        # what ring_attention actually auto-selects for this shard length
        # (shared predicate — restating the threshold here would hide the
        # drift this leg exists to catch)
        "auto_selects": ring_block_impl(l_local, head_dim),
    }


def _bench_feed(*, batch: int = 1024, total_batches: int = 96, reps: int = 3,
                sweep_batches_per_chunk=(4, 8, 16, 32), sweep_reps: int = 2):
    """Feed-path overlap: chunked MNIST-CNN epochs timed three ways —
    all chunks pre-placed on device (pure compute), sequential
    place-then-train (the pre-round-5 loop), and the double-buffered
    ``prefetch_to_device`` loop the trainers use.  ``feed_overhead``
    = 1 - compute/wall for each loop.

    Round-6 additions (verdict weak #4/#6): (1) a ``chunk_mb`` SWEEP —
    the same ``total_batches`` of data fed as 4/8/16/32-batch chunks
    (~12/25/49/98 MB) through the prefetch loop; the fastest size is
    promoted IN-RUN to be the config of the headline three-way
    comparison and recorded as ``best_chunk_mb`` (the measured value
    behind ``data.dataset.DEFAULT_CHUNK_BUDGET_BYTES``); (2) a per-chunk
    ``decomposition`` — IO (producing the host chunk), wire (blocking
    H2D place), and step wall vs on-device time (profiler trace) from an
    instrumented sequential pass, so "it's the relay" is a measured
    split, not an inference from totals."""
    import statistics
    import tempfile
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.data.dataset import prefetch_to_device
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.parallel.engine import scan_epoch_fn

    spec = mnist_cnn_spec()
    model = Model.init(spec, seed=0)
    opt = optax.sgd(0.01, momentum=0.9)
    epoch_fn = scan_epoch_fn(spec.apply_fn(), get_loss("categorical_crossentropy"), opt)

    rng = np.random.default_rng(0)
    data_x = rng.normal(size=(total_batches, batch, 28, 28, 1)).astype(np.float32)
    data_y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (total_batches, batch))]

    def make_chunks(per_chunk):
        n = (total_batches // per_chunk) * per_chunk
        return [(data_x[i:i + per_chunk], data_y[i:i + per_chunk])
                for i in range(0, n, per_chunk)]

    params0 = jax.tree.map(jnp.array, model.params)
    opt_state0 = opt.init(params0)

    def run_chunks(placed_iter):
        params = jax.tree.map(jnp.array, params0)
        opt_state = jax.tree.map(jnp.array, opt_state0)
        for xs, ys in placed_iter:
            params, opt_state, losses = epoch_fn(params, opt_state, xs, ys)
            np.asarray(losses)  # the trainer's per-chunk history read

    place = lambda ch: (jnp.asarray(ch[0]), jnp.asarray(ch[1]))

    def timed(make_iter, n_reps=reps):
        walls = []
        for _ in range(n_reps):
            it = make_iter()
            t0 = time.perf_counter()
            run_chunks(it)
            walls.append(time.perf_counter() - t0)
        med = statistics.median(walls)
        spread = round((max(walls) - min(walls)) / med, 3) if med else 0.0
        return med, spread

    # -- chunk-size sweep (prefetch loop; each size recompiles the epoch
    # program once for its [per_chunk, batch, ...] shape).  A non-divisor
    # size trains only the divisible prefix of the data, so every leg's
    # samples_per_sec counts its OWN trained samples and the promotion
    # compares throughput, not wall over unequal work ----------------------
    sweep = []
    for per_chunk in sweep_batches_per_chunk:
        host_chunks = make_chunks(per_chunk)
        leg_samples = len(host_chunks) * per_chunk * batch
        run_chunks(prefetch_to_device(iter(host_chunks), place))  # compile+warm
        t_pre, sp = timed(lambda hc=host_chunks: prefetch_to_device(iter(hc), place),
                          n_reps=sweep_reps)
        sweep.append({
            "batches_per_chunk": per_chunk,
            "chunk_mb": round(host_chunks[0][0].nbytes / 2**20, 1),
            "prefetch_ms": round(t_pre * 1e3, 1),
            "samples_per_sec": round(leg_samples / t_pre, 1),
            "spread": sp,
        })
    best = max(sweep, key=lambda s: s["samples_per_sec"])
    best_per_chunk = best["batches_per_chunk"]

    # -- headline three-way comparison AT the promoted best size -----------
    host_chunks = make_chunks(best_per_chunk)
    chunks = len(host_chunks)
    samples = chunks * best_per_chunk * batch  # what these loops train on
    pre_placed = [place(ch) for ch in host_chunks]
    jax.block_until_ready(pre_placed)
    t_compute, sp_c = timed(lambda: iter(pre_placed))
    # generator places each chunk only when consumed: the old loop's
    # transfer-after-previous-chunk-completes behavior
    t_seq, sp_s = timed(lambda: (place(c) for c in host_chunks))
    t_pre, sp_p = timed(lambda: prefetch_to_device(iter(host_chunks), place))

    # -- per-chunk decomposition (instrumented sequential pass): IO is the
    # host-side chunk production (a copy here — synthetic data stands in
    # for the page-fault cost a ColumnFile feed pays), wire is the
    # BLOCKING place, step is the train call; device time comes from the
    # module events of a trace around the pass.  Blocking on the place
    # defeats overlap by design — this pass measures the parts, the timed
    # loops above measure the composition
    io_ms, wire_ms, step_ms = [], [], []
    params = jax.tree.map(jnp.array, params0)
    opt_state = jax.tree.map(jnp.array, opt_state0)
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for xs_h, ys_h in host_chunks:
                t0 = time.perf_counter()
                xs_h, ys_h = np.array(xs_h), np.array(ys_h)  # produce
                t1 = time.perf_counter()
                placed = place((xs_h, ys_h))
                jax.block_until_ready(placed)
                t2 = time.perf_counter()
                params, opt_state, losses = epoch_fn(params, opt_state, *placed)
                np.asarray(losses)
                t3 = time.perf_counter()
                io_ms.append((t1 - t0) * 1e3)
                wire_ms.append((t2 - t1) * 1e3)
                step_ms.append((t3 - t2) * 1e3)
        dev_ms = sum(_trace_jit_durs(td))
    med = statistics.median
    decomposition = {
        "io_ms_per_chunk": round(med(io_ms), 2),
        "wire_ms_per_chunk": round(med(wire_ms), 2),
        "step_wall_ms_per_chunk": round(med(step_ms), 2),
        "device_ms_per_chunk": round(dev_ms / max(chunks, 1), 2),
    }

    # NOTE (relay platforms): the transfer legs ride a SHARED relay whose
    # bandwidth swings >2x with tenancy — the sequential/prefetch
    # comparison is only meaningful when their spreads are small; the
    # spread columns exist so a reader can tell.  compute_only is stable.
    return {
        "chunks": chunks,
        "chunk_mb": round(host_chunks[0][0].nbytes / 2**20, 1),
        "best_chunk_mb": best["chunk_mb"],
        "sweep": sweep,
        "timing": "wall",
        "compute_only_ms": round(t_compute * 1e3, 1),
        "sequential_ms": round(t_seq * 1e3, 1),
        "prefetch_ms": round(t_pre * 1e3, 1),
        "spread": {"compute_only": sp_c, "sequential": sp_s, "prefetch": sp_p},
        "feed_overhead_sequential": round(max(0.0, 1 - t_compute / t_seq), 4),
        "feed_overhead_prefetch": round(max(0.0, 1 - t_compute / t_pre), 4),
        "samples_per_sec_prefetch": round(samples / t_pre, 1),
        "decomposition": decomposition,
    }


def _bench_pipeline(*, pp: int = 2, num_microbatches: int = 8, batch: int = 8,
                    seq_len: int = 256, model_dim: int = 256,
                    num_heads: int = 2, num_layers: int = 4,
                    vocab: int = 8192, reps: int = 3):
    """GPipe vs 1F1B step time on a (dp=1, pp) mesh, with the analytic
    ``head_recompute_factor`` recorded next to the measurement.  Since
    round 6 the 1F1B head + CE runs inside a ``lax.cond`` taken only on
    the last rank's valid backward units, so the factor is 1.0 (same
    unembed FLOPs as GPipe); the round-5 ``jnp.where`` form paid
    ``pp * (1 + 2(pp-1)/M)`` times GPipe's and lost at every measured M.
    The leg keeps both numbers recorded so a schedule regression trips
    as a measurement, not a docstring drift."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.lm import shift_targets
    from distkeras_tpu.parallel.mesh import create_nd_mesh
    from distkeras_tpu.parallel.pipeline import (head_recompute_factor,
                                                 make_pp_train_step,
                                                 pp_state_shardings,
                                                 split_block_params)

    spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim,
                         num_heads=num_heads, num_layers=num_layers,
                         max_seq_len=seq_len)
    mesh = create_nd_mesh((1, pp), ("dp", "pp"))
    opt = optax.sgd(0.01)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, size=(batch, seq_len)).astype(np.int32)
    tgts = shift_targets(toks)

    out = {"pp": pp, "num_microbatches": num_microbatches, "batch": batch,
           "seq_len": seq_len, "vocab": vocab,
           "head_recompute_factor": round(
               head_recompute_factor(pp, num_microbatches), 3)}
    for schedule in ("gpipe", "1f1b"):
        model = Model.init(spec, seed=0)
        outer, blocks = split_block_params(model.params)
        psh, osh = pp_state_shardings(mesh, opt, outer, blocks)
        params = jax.device_put(
            (jax.tree.map(jnp.asarray, outer), jax.tree.map(jnp.asarray, blocks)),
            psh)
        opt_state = jax.device_put(opt.init(params), osh)
        step = make_pp_train_step(spec, opt, mesh, num_microbatches,
                                  schedule=schedule)
        dsh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp"))
        tok_d = jax.device_put(toks, dsh)
        tgt_d = jax.device_put(tgts, dsh)
        state = {"p": params, "o": opt_state}

        def run_once(state=state, step=step, tok_d=tok_d, tgt_d=tgt_d):
            # donated params/opt_state: thread the new state through so
            # every timed call uses live buffers
            state["p"], state["o"], loss = step(state["p"], state["o"],
                                                tok_d, tgt_d)
            return loss

        ms, spread, source = _device_time_ms(run_once, reps=reps)
        out[schedule] = {"ms_per_step": round(ms, 2),
                         "wall_spread": spread, "timing": source}
    g, f = out["gpipe"]["ms_per_step"], out["1f1b"]["ms_per_step"]
    if g:
        out["1f1b_vs_gpipe"] = round(f / g, 4)
    return out


def _bench_moe_capacity_sweep(*, model_dim: int, num_heads: int, vocab: int,
                              experts: int, batch: int, seq_len: int,
                              num_layers: int, steps: int, factors,
                              aux_weight: float = 0.01):
    """Trained-router drop rates across capacity factors (satellite of the
    sparse-dispatch issue): the recorded ``dropped_fraction`` numbers were
    UNTRAINED-router worst cases (18-30% at factor 2, BENCH_r05) — the
    load-balance aux loss exists precisely to push them toward zero, so
    this sweep trains the MoE LM (adam, ``steps`` batches of fresh random
    tokens, one compiled scan per factor) and records the drop/load stats
    at the START and END of training for each factor.  Runs on the sorted
    dispatch path at a compact depth (the routing statistics are
    per-layer; depth only multiplies identical routers)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.moe import _collect_router_stats

    t = batch * seq_len
    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, size=(steps, batch, seq_len)).astype(np.int32)
    tgts = np.roll(toks, -1, axis=-1)  # CE below drops the last position

    results = []
    for factor in factors:
        cap = max(1, -(-int(factor * t) // experts))
        spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim,
                             num_heads=num_heads, num_layers=num_layers,
                             max_seq_len=seq_len, moe_experts=experts,
                             moe_capacity=cap, moe_top_k=1,
                             moe_dispatch="sorted")
        module = spec.build()
        opt = optax.adam(3e-3)

        def loss_fn(params, tok, tgt, module=module):
            logits, variables = module.apply(
                {"params": params}, tok, mutable=["aux_loss", "router_stats"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tgt.astype(jnp.int32))[:, :-1].mean()
            aux_leaves = jax.tree.leaves(variables.get("aux_loss", {}))
            aux = sum(aux_leaves) / len(aux_leaves)
            stats = {k: sum(v) / len(v) for k, v in _collect_router_stats(
                variables.get("router_stats", {})).items()}
            return ce + aux_weight * aux, stats

        @jax.jit
        def train(params, opt_state, toks_d, tgts_d, opt=opt, loss_fn=loss_fn):
            def body(carry, data):
                params, opt_state = carry
                (loss, stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, *data)
                updates, opt_state = opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state), (
                    loss, stats["dropped_fraction"], stats["max_expert_load"])

            _, ys = jax.lax.scan(body, (params, opt_state), (toks_d, tgts_d))
            return ys

        model = Model.init(spec, seed=0)
        params = jax.tree.map(jnp.asarray, model.params)
        losses, drops, loads = train(params, opt.init(params),
                                     jnp.asarray(toks), jnp.asarray(tgts))
        drops, loads = np.asarray(drops), np.asarray(loads)
        tail = max(1, min(5, steps // 4))
        results.append({
            "capacity_factor": factor,
            "capacity": cap,
            "dropped_fraction_untrained": round(float(np.mean(drops[:tail])), 4),
            "dropped_fraction_trained": round(float(np.mean(drops[-tail:])), 4),
            "max_expert_load_trained": round(float(np.mean(loads[-tail:])), 3),
            "final_loss": round(float(np.asarray(losses)[-1]), 4),
            "train_steps": steps,
        })
    return results


def _bench_moe(*, batch: int = 4, seq_len: int = 512, model_dim: int = 512,
               num_heads: int = 4, num_layers: int = 8, vocab: int = 8192,
               experts: int = 8, reps: int = 3, sweep_layers: int = 2,
               sweep_steps: int = 150,
               capacity_factors=(1.0, 1.25, 1.5, 2.0)):
    """Switch-MoE TransformerLM train step (make_moe_lm_train_step) on the
    real chip: tokens/sec + expert-FLOP-accounted MFU for top-1 (Switch)
    and top-2 (GShard-style) routing — each under BOTH dispatch impls
    (``top1``/``top2`` run the sorted gather path, ``top1_dense``/
    ``top2_dense`` the round-5 one-hot einsums, so the dispatch-tax
    removal is an A/B number, not a claim) — plus the trained-router
    capacity-factor sweep and the issue-2 acceptance tripwires.

    MFU accounting: the model-required matmul FLOPs — dense projections,
    causal attention, unembed, router, and the EXECUTED expert compute
    (E * capacity slots through up/down, i.e. the capacity-padded slabs
    the MXU actually runs, x3 for fwd+bwd) — over device time.  Dispatch/
    combine work is ROUTING OVERHEAD, excluded from MFU and reported as
    ``dispatch_flops_pct`` per impl (``parallel.moe.dispatch_matmul_flops``
    is the single source of truth: 4·T·E·C·D dense, 0 sorted).  This
    field's denominator is the whole MODEL's matmul FLOPs (attention +
    unembed included); the train step's sown stat of the same name is
    MoE-layer-local and therefore reads higher under dense dispatch —
    both are exactly 0 on the sorted path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.mesh import create_nd_mesh
    from distkeras_tpu.parallel.moe import (dispatch_matmul_flops,
                                            make_moe_lm_train_step,
                                            moe_data_sharding,
                                            moe_state_shardings)
    from distkeras_tpu.parallel.lm import shift_targets

    e, f = model_dim, 4 * model_dim
    t = batch * seq_len
    cap = -(-2 * t // experts)  # the TransformerBlock default (factor-2)
    mesh = create_nd_mesh((1, 1), ("dp", "ep"))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, size=(batch, seq_len)).astype(np.int32)
    tgts = shift_targets(toks)
    peak = _peak_flops(jax.devices()[0].device_kind)

    # per-step matmul FLOPs (fwd x3 for fwd+bwd), PaLM-style: per layer
    # the experts' executed slabs (E*cap slots through up+down), qkv+proj
    # (4e^2 per token), causal attention (2*B*L^2*E fwd), the router; plus
    # the tied unembed once
    expert_fl = 3 * 4 * experts * cap * e * f
    attn_proj_fl = 3 * (2 * t * 4 * e * e + 2 * batch * seq_len * seq_len * e)
    router_fl = 3 * 2 * t * e * experts
    unembed_fl = 3 * 2 * t * e * vocab
    model_fl = num_layers * (expert_fl + attn_proj_fl + router_fl) + unembed_fl

    out = {"batch": batch, "seq_len": seq_len, "experts": experts,
           "capacity": cap}
    for top_k in (1, 2):
        for impl in ("sorted", "dense"):
            dispatch_fl = num_layers * 3 * dispatch_matmul_flops(
                t, experts, cap, e, impl)
            spec = small_lm_spec(vocab_size=vocab, model_dim=model_dim,
                                 num_heads=num_heads, num_layers=num_layers,
                                 max_seq_len=seq_len, moe_experts=experts,
                                 moe_top_k=top_k, moe_dispatch=impl)
            model = Model.init(spec, seed=0)
            opt = optax.sgd(0.01)
            step = make_moe_lm_train_step(spec, opt, mesh)
            psh, osh = moe_state_shardings(mesh, opt, model.params)
            params = jax.device_put(jax.tree.map(jnp.asarray, model.params), psh)
            opt_state = jax.device_put(opt.init(params), osh)
            dsh = moe_data_sharding(mesh)
            tok_d, tgt_d = jax.device_put(toks, dsh), jax.device_put(tgts, dsh)
            state = {"p": params, "o": opt_state, "stats": None}

            def run_once(state=state, step=step, tok_d=tok_d, tgt_d=tgt_d):
                # donated params/opt_state: thread the NEW state through so
                # every call uses live buffers
                state["p"], state["o"], loss, state["stats"] = step(
                    state["p"], state["o"], tok_d, tgt_d)
                return loss

            ms, spread, source = _device_time_ms(run_once, reps=reps)
            sec = ms / 1e3
            name = f"top{top_k}" if impl == "sorted" else f"top{top_k}_dense"
            out[name] = {
                "tokens_per_sec": round(t / sec, 1),
                "ms_per_step": round(ms, 2),
                "mfu": round(model_fl / sec / peak, 4) if peak else None,
                "dispatch_impl": impl,
                "dispatch_flops_pct": round(
                    100 * dispatch_fl / (model_fl + dispatch_fl), 1),
                "dropped_fraction": round(float(state["stats"]["dropped_fraction"]), 4),
                "max_expert_load": round(float(state["stats"]["max_expert_load"]), 3),
                "wall_spread": spread,
                "timing": source,
            }
    for top_k in (1, 2):
        s, d = out[f"top{top_k}"], out[f"top{top_k}_dense"]
        out[f"sorted_vs_dense_top{top_k}"] = round(
            s["tokens_per_sec"] / d["tokens_per_sec"], 4)

    try:
        out["capacity_sweep"] = _bench_moe_capacity_sweep(
            model_dim=model_dim, num_heads=num_heads, vocab=vocab,
            experts=experts, batch=batch, seq_len=seq_len,
            num_layers=sweep_layers, steps=sweep_steps,
            factors=capacity_factors)
    except Exception as ex:
        out["capacity_sweep"] = {"error": f"{type(ex).__name__}: {ex}"}

    # issue-2 acceptance tripwires, recorded as booleans so a regression
    # (or an unmet target) is a grep-able field, not a judgement call
    sweep = out["capacity_sweep"] if isinstance(out["capacity_sweep"], list) else []
    by_factor = {s["capacity_factor"]: s for s in sweep}
    trained_drop = by_factor.get(2.0, {}).get("dropped_fraction_trained")
    t1 = out["top1"]
    out["acceptance"] = {
        "mfu_target": 0.45,
        "mfu_ok": None if t1.get("mfu") is None else bool(t1["mfu"] >= 0.45),
        "dispatch_pct_target": 20.0,
        "dispatch_pct_ok": bool(t1["dispatch_flops_pct"] < 20.0),
        "trained_drop_target": 0.05,
        "trained_drop_ok": (None if trained_drop is None
                            else bool(trained_drop < 0.05)),
    }
    return out


def _bench_async(*, workers: int = 2, window: int = 8, batch: int = 256,
                 windows_per_epoch: int = 8, epochs: int = 3,
                 scaling_workers=(1, 4)):
    """Genuinely-async trainer family (runtime/async_trainer.py) on the
    real chip: AsyncADAG (Python hub, C++ hub, int8 Q-commits) and
    AsyncAEASGD wall throughput vs the sync window engine's, with the
    device-time share of the async wall so the dispatch overhead is a
    measured number, not a guess — plus a worker-scaling sweep (weak
    scaling: per-worker data held constant).  The ``native`` and ``int8``
    legs are the round-5 verdict's missing evidence: the C++ hub and the
    4x-smaller Q-commits existed with correctness tests only; these legs
    put wall/device numbers (and a tripwire) on each.

    Methodology: each trainer runs train() TWICE on the same instance —
    the first run compiles (the window program is cached per instance),
    the second is timed.  Timing is WALL by necessity (the async mode IS
    a host-driven loop; its per-window pull/commit/dispatch cost is the
    thing being measured).  ``device_share`` comes from a profiler trace
    of the timed run: sum of on-device module events across all workers
    over the wall time — on the relayed axon platform expect a LOW share
    (each window pays ~3 host round trips at ~10-110ms relay latency
    where co-located hosts pay ~1ms); the leg exists to quantify exactly
    that."""
    import tempfile

    import jax
    import numpy as np

    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG, AsyncAEASGD
    from distkeras_tpu.trainers import ADAG

    spec = mnist_cnn_spec()
    rng = np.random.default_rng(0)

    def make_ds(w):
        n = w * batch * window * windows_per_epoch
        return n, Dataset({
            "features": rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
            "label": np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)],
        })

    n, ds = make_ds(workers)
    samples = n * epochs

    def timed_run(trainer, ds=ds):
        trainer.train(ds, shuffle=False)  # compile + warm
        trainer.model = Model.init(spec, seed=0)
        trainer.history = []  # count only the timed run's windows
        with tempfile.TemporaryDirectory() as td:
            with jax.profiler.trace(td):
                t0 = time.perf_counter()
                trainer.train(ds, shuffle=False)
                # wall stops BEFORE the trace context exits: profiler
                # teardown (collect + gzip to disk) is not training time
                wall = time.perf_counter() - t0
            dev_ms = sum(_trace_jit_durs(td))
        return wall, dev_ms

    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs, "timing": "wall"}
    kwargs = dict(loss="categorical_crossentropy", batch_size=batch,
                  num_epoch=epochs, learning_rate=0.01, seed=0)

    def async_leg(name, cls, extra, w=workers, leg_ds=None, leg_samples=None):
        tr = cls(Model.init(spec, seed=0), num_workers=w,
                 communication_window=window, **dict(kwargs, **extra))
        wall, dev_ms = timed_run(tr, ds=leg_ds if leg_ds is not None else ds)
        n_windows = len(tr.history)
        out[name] = {
            "samples_per_sec": round((leg_samples or samples) / wall, 1),
            "wall_s": round(wall, 3),
            "device_share": round(dev_ms / 1e3 / wall, 4),
            "per_window_wall_ms": round(wall * 1e3 / max(n_windows, 1), 2),
            "per_window_device_ms": round(dev_ms / max(n_windows, 1), 2),
            "hub": "native" if extra.get("native_ps") else "python",
            "compress": extra.get("compress_commits"),
            "transport": extra.get("transport", "socket"),
            "pipeline": extra.get("pipeline", True),
            "num_shards": extra.get("num_shards", 1),
            "recv_batch_depth": extra.get("recv_batch_depth", 0),
            # final-loss parity evidence: pipelined pulls see the center one
            # commit earlier (self-staleness 1), so the issue-3 acceptance
            # records where every leg's trajectory LANDS, not just its speed
            "final_loss": (round(float(np.mean(tr.history[-8:])), 6)
                           if tr.history else None),
        }
        return out[name]

    def decomposition_leg(name, cls, extra):
        """Instrumented re-run of a leg (telemetry ON — its own wall clock,
        NOT comparable to the timed leg): the wall/wire/serialize/device
        split plus the hub's staleness distribution, per transport —
        issue-3's evidence that the relay/transport tax actually moved."""
        from distkeras_tpu import observability as obs

        tr = cls(Model.init(spec, seed=0), num_workers=workers,
                 communication_window=window, **dict(kwargs, **extra))
        tr.train(ds, shuffle=False)  # compile + warm
        tr.model = Model.init(spec, seed=0)
        tr.history = []
        obs.enable()
        obs.reset()
        try:
            with tempfile.TemporaryDirectory() as td:
                with jax.profiler.trace(td):
                    t0 = time.perf_counter()
                    tr.train(ds, shuffle=False)
                    wall_ms = (time.perf_counter() - t0) * 1e3
                dev_ms = sum(_trace_jit_durs(td))
            snap = obs.snapshot()
        finally:
            obs.reset()
            obs.disable()
        hists = snap.get("histograms", {})

        def hsum(key):
            return float((hists.get(key) or {}).get("sum") or 0.0)

        n_windows = max(len(tr.history), 1)
        staleness = hists.get("ps_commit_staleness") or {}
        out[name]["decomposition"] = {
            "timing": "instrumented-wall",
            "wall_ms": round(wall_ms, 1),
            "device_ms": round(dev_ms, 1),
            # wire = time workers actually BLOCKED on the exchange after
            # overlap (pull stalls); serialize = frame pack time; the
            # remainder is dispatch + feed + Python loop
            "wire_stall_ms": round(hsum("ps.pull_stall_ms"), 1),
            "serialize_ms": round(hsum("ps.serialize_ms"), 3),
            "commit_wire_bytes": snap.get("counters", {}).get("ps.commit_bytes", 0.0),
            "per_window_wall_ms": round(wall_ms / n_windows, 2),
            "per_window_wire_stall_ms": round(hsum("ps.pull_stall_ms") / n_windows, 3),
            "staleness": {"count": staleness.get("count"),
                          "mean": staleness.get("mean"),
                          "max": staleness.get("max"),
                          "buckets": staleness.get("buckets")},
        }
        # zero-copy transport evidence (ISSUE 18): frames that crossed
        # shm rings, ring-full backpressure parks, and the hub's frames-
        # per-blocking-fill distribution — the batch tripwire's input
        counters = snap.get("counters", {})
        if counters.get("ps.shm_frames_total"):
            out[name]["decomposition"]["shm_frames_total"] = \
                counters.get("ps.shm_frames_total")
            out[name]["decomposition"]["shm_ring_full_waits"] = \
                counters.get("ps.shm_ring_full_waits", 0.0)
        depth = hists.get("ps_recv_batch_depth")
        if depth:
            out[name]["decomposition"]["recv_batch_depth"] = {
                "count": depth.get("count"), "mean": depth.get("mean"),
                "max": depth.get("max")}

    # transport/hub/compression dimensions on the SAME workload: python hub
    # pipelined sockets (baseline-continuity key), the inproc transport, the
    # serial pre-overhaul exchange (pipeline=False — the final-loss parity
    # reference), the C++ hub, int8 error-feedback commits, and AEASGD.
    # Individually fallible (the native .so may be absent on a dev box) — a
    # failed leg records its error, not the axe
    for name, cls, extra in (
            ("async_adag", AsyncADAG, {}),
            ("async_adag_inproc", AsyncADAG, {"transport": "inproc"}),
            ("async_adag_serial", AsyncADAG, {"pipeline": False}),
            ("async_adag_native", AsyncADAG, {"native_ps": True}),
            ("async_adag_int8", AsyncADAG, {"compress_commits": "int8"}),
            ("async_adag_shards4", AsyncADAG, {"num_shards": 4}),
            # zero-copy transport (ISSUE 18): frames over shm rings (same
            # bytes, no socket) and batched socket receives (recvmmsg)
            ("shm_ring", AsyncADAG, {"transport": "shm"}),
            ("recv_batch", AsyncADAG, {"recv_batch_depth": 8}),
            ("async_aeasgd", AsyncAEASGD, {"rho": 2.0})):
        try:
            async_leg(name, cls, extra)
        except Exception as ex:
            out[name] = {"error": f"{type(ex).__name__}: {ex}"}

    # per-transport decomposition (socket vs inproc vs shm vs batched),
    # on the headline config
    for name, extra in (("async_adag", {}),
                        ("async_adag_inproc", {"transport": "inproc"}),
                        ("shm_ring", {"transport": "shm"}),
                        ("recv_batch", {"recv_batch_depth": 8})):
        if isinstance(out.get(name), dict) and "error" not in out[name]:
            try:
                decomposition_leg(name, AsyncADAG, extra)
            except Exception as ex:
                out[name]["decomposition"] = {"error": f"{type(ex).__name__}: {ex}"}

    # weak-scaling points (per-worker data constant): does adding workers
    # add throughput, or does the shared hub/relay serialize them?  The
    # `workers`-worker point is the async_adag leg above; only the other
    # counts run here
    out["scaling"] = {}
    if isinstance(out.get("async_adag"), dict) and "error" not in out["async_adag"]:
        out["scaling"][str(workers)] = {
            "samples_per_sec": out["async_adag"]["samples_per_sec"],
            "per_window_wall_ms": out["async_adag"]["per_window_wall_ms"]}
    for w in scaling_workers:
        if w == workers:
            continue
        try:
            n_w, ds_w = make_ds(w)
            leg = async_leg(f"async_adag_w{w}", AsyncADAG, {}, w=w,
                            leg_ds=ds_w, leg_samples=n_w * epochs)
            out["scaling"][str(w)] = {
                "samples_per_sec": leg["samples_per_sec"],
                "per_window_wall_ms": leg["per_window_wall_ms"]}
        except Exception as ex:
            out["scaling"][str(w)] = {"error": f"{type(ex).__name__}: {ex}"}

    # sync denominator: the SAME update family (ADAG) through the compiled
    # window engine on the same data and epoch count — one device here, so
    # this is the single-chip sync path the async mode competes with
    try:
        sync = ADAG(Model.init(spec, seed=0), num_workers=1,
                    communication_window=window, **kwargs)
        wall, dev_ms = timed_run(sync)
        out["sync_adag"] = {"samples_per_sec": round(samples / wall, 1),
                            "wall_s": round(wall, 3),
                            "device_share": round(dev_ms / 1e3 / wall, 4)}
    except Exception as ex:
        # a dead sync denominator (e.g. no jax.shard_map in the env) must
        # not axe the async legs and their decomposition evidence — the
        # ratios below just come back absent
        out["sync_adag"] = {"error": f"{type(ex).__name__}: {ex}"}

    # hub-scaling leg (ISSUE 6): pure PS-level commit throughput at 1 vs 4
    # center shards — the single-socket/single-lock ceiling measured
    # directly, without training noise.  Individually fallible like every
    # other leg
    try:
        out["shard_scaling"] = _bench_async_shards()
    except Exception as ex:
        out["shard_scaling"] = {"error": f"{type(ex).__name__}: {ex}"}

    # native feature-parity legs (ISSUE 11): each newly ported feature
    # combination on BOTH hubs, with a per-leg native-beats-python
    # tripwire.  Individually fallible like every other leg
    try:
        out["native_features"] = _bench_async_native_features()
    except Exception as ex:
        out["native_features"] = {"error": f"{type(ex).__name__}: {ex}"}

    _async_acceptance(out)
    return out


def _shard_bench_hub_proc(shapes, conn):
    """Child-process entry (spawn-safe, module level): one PS hub process
    serving one shard's slice — the ``distkeras-ps --shard-index``
    topology, so the 1-shard leg is bottlenecked by exactly what a real
    single-hub deployment is (one process's socket stack, lock and
    interpreter).  Telemetry runs locally; the final stats ride back over
    the pipe."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.runtime.parameter_server import DeltaParameterServer

    obs.enable()
    hub = DeltaParameterServer([np.zeros(s, np.float32) for s in shapes],
                               idle_timeout=None)
    hub.start()
    conn.send(hub.port)
    conn.recv()  # stop request
    hist = (obs.snapshot()["histograms"]
            .get('ps_rpc_seconds{rpc="commit"}') or {})
    conn.send({"num_updates": int(hub.num_updates),
               "hub_commit_s": hist.get("sum")})
    hub.stop()


def _shard_bench_worker_proc(addrs, shapes, num_shards, commits, max_inflight,
                             conn):
    """Child-process entry (spawn-safe, module level): one striped commit
    blaster.  Ready/go handshake over the pipe keeps process startup and
    connection warmup out of the timed window."""
    import numpy as np

    from distkeras_tpu.runtime.parameter_server import (
        ShardedPSClient, shard_plan)

    templates = [np.zeros(s, np.float32) for s in shapes]
    delta = [np.full_like(t, 1e-3) for t in templates]
    plan = shard_plan(templates, num_shards)
    client = ShardedPSClient(addrs, templates, plan, max_inflight=max_inflight)
    client.pull()  # connections + landing buffers warm
    conn.send("ready")
    conn.recv()  # go
    for _ in range(commits):
        client.commit_nowait(delta)
    client.drain()
    conn.send("done")
    client.close()


def _bench_async_shards(*, shard_counts=(1, 4), workers: int = 8,
                        leaves: int = 16, leaf_elems: int = 2048,
                        commits_per_worker: int = 300, max_inflight: int = 8):
    """Sharded-hub commit throughput (ISSUE 6 acceptance leg): ``workers``
    worker PROCESSES blast striped commits at 1 vs 4 hub shard PROCESSES
    (one Python hub per shard — the ``distkeras-ps --shard-index``
    deployment shape), and the aggregate throughput ratio is the evidence
    that partitioning the center removed the single-hub ceiling (target:
    >= 3x at 4 shards, near-linear).  Processes, not threads, on both
    sides: in-process workers share one GIL and measure the CLIENT, not
    the hub.  The payload is deliberately small (16 x 8 KiB leaves) so
    per-commit hub work — syscalls, decode, lock, ack — is the ceiling
    rather than loopback bandwidth, which one machine cannot shard.
    ``cpus`` is recorded because the figure needs ~(workers + shards)
    runnable processes to mean anything; a 2-core container reports a
    degraded ratio, the tripwire stays None-degrading, and the real
    figure comes from bench hardware."""
    import multiprocessing as mp

    from distkeras_tpu.runtime import networking as net
    from distkeras_tpu.runtime.parameter_server import shard_plan

    shapes = [(int(leaf_elems),) for _ in range(leaves)]
    center_bytes = leaves * leaf_elems * 4
    out = {"workers": workers, "leaves": leaves, "leaf_elems": leaf_elems,
           "commits_per_worker": commits_per_worker,
           "center_kb": round(center_bytes / 1024, 1),
           "hub": "python-process-per-shard",
           "cpus": os.cpu_count(),
           "shard_counts": list(shard_counts)}
    # forkserver when available: children come from a clean server process
    # (no re-exec of the caller's __main__, safe to start from a threaded
    # parent); spawn is the portable fallback.  Plain fork is never safe
    # here — the parent may hold live hub threads
    try:
        ctx = mp.get_context("forkserver")
    except ValueError:
        ctx = mp.get_context("spawn")

    def one_leg(num_shards: int) -> dict:
        import numpy as np

        templates = [np.zeros(s, np.float32) for s in shapes]
        plan = shard_plan(templates, num_shards)
        hub_pipes, hub_procs, w_pipes, w_procs = [], [], [], []
        try:
            for sid in range(num_shards):
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_shard_bench_hub_proc,
                    args=([shapes[i] for i in plan.assignments[sid]], child),
                    daemon=True)
                proc.start()
                hub_pipes.append(parent)
                hub_procs.append(proc)
            addrs = []
            for pipe in hub_pipes:
                if not pipe.poll(60):
                    raise RuntimeError("hub shard process failed to report "
                                       "its port within 60s")
                addrs.append(("127.0.0.1", pipe.recv()))
            for _ in range(workers):
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_shard_bench_worker_proc,
                    args=(addrs, shapes, num_shards, commits_per_worker,
                          max_inflight, child),
                    daemon=True)
                proc.start()
                w_pipes.append(parent)
                w_procs.append(proc)
            for pipe in w_pipes:
                if not pipe.poll(120):
                    raise RuntimeError("worker process failed to warm up "
                                       "within 120s")
                pipe.recv()
            t0 = time.perf_counter()
            for pipe in w_pipes:
                pipe.send("go")
            for pipe in w_pipes:
                if not pipe.poll(300):
                    raise RuntimeError("worker process did not finish its "
                                       "commits within 300s")
                pipe.recv()
            wall = time.perf_counter() - t0
            logical = workers * commits_per_worker
            stripe_bytes = sum(
                net.tensor_frame_len([templates[i] for i in idxs])
                for idxs in plan.assignments)
            per_shard = {}
            for sid, pipe in enumerate(hub_pipes):
                pipe.send("stop")
                stats = pipe.recv() if pipe.poll(30) else {}
                shard_frame = net.tensor_frame_len(
                    [templates[i] for i in plan.assignments[sid]])
                n_commits = int(stats.get("num_updates") or 0)
                hub_s = stats.get("hub_commit_s")
                per_shard[str(sid)] = {
                    "leaves": len(plan.assignments[sid]),
                    "center_kb": round(plan.shard_bytes[sid] / 1024, 1),
                    "commits": n_commits,
                    "wire_mb": round(n_commits * shard_frame / 1e6, 2),
                    "hub_commit_s": (round(float(hub_s), 4)
                                     if hub_s is not None else None),
                }
            return {
                "wall_s": round(wall, 4),
                "logical_commits": logical,
                "commits_per_sec": round(logical / wall, 2),
                "mb_per_sec": round(logical * stripe_bytes / 1e6 / wall, 2),
                "per_shard": per_shard,
            }
        finally:
            for proc in w_procs + hub_procs:
                proc.join(timeout=10)
                if proc.is_alive():
                    proc.terminate()

    for num_shards in shard_counts:
        try:
            out[str(num_shards)] = one_leg(int(num_shards))
        except Exception as ex:
            out[str(num_shards)] = {"error": f"{type(ex).__name__}: {ex}"}
    _async_shard_acceptance(out)
    return out


def _async_shard_acceptance(out: dict) -> None:
    """Attach the ISSUE-6 shard-scaling tripwire, in place: aggregate
    commit throughput at 4 shards >= 3x the 1-shard figure.  None (not a
    crash) wherever a leg is missing or errored — the PR-3 convention."""
    def _ok(name):
        return isinstance(out.get(name), dict) and "error" not in out[name]

    ratio = None
    if _ok("1") and _ok("4"):
        base = out["1"].get("commits_per_sec") or 0
        if base:
            ratio = round(out["4"]["commits_per_sec"] / base, 3)
    out["acceptance"] = {
        "shard_scaling_target": 3.0,
        "scaling_x_4_vs_1": ratio,
        "shard_scaling_ok": None if ratio is None else bool(ratio >= 3.0),
    }


def _async_acceptance(out: dict) -> None:
    """Attach the issue-3 ratios + acceptance tripwires to an async-section
    dict, in place.  Booleans (or None when a leg is missing/errored) so a
    transport regression trips visibly in the punchcard instead of hiding
    in a ratio nobody reads.  The r05 reference (BENCH_r05.json
    async_adag: per_window_wall_ms 421.15, adag_vs_sync 0.5186) is the
    pre-overhaul relay-bound hot path this change exists to fix."""
    def _ok(name):
        return isinstance(out.get(name), dict) and "error" not in out[name]

    if _ok("async_adag") and _ok("sync_adag"):
        out["adag_vs_sync"] = round(out["async_adag"]["samples_per_sec"]
                                    / out["sync_adag"]["samples_per_sec"], 4)
    if _ok("async_adag_inproc") and _ok("sync_adag"):
        out["adag_inproc_vs_sync"] = round(
            out["async_adag_inproc"]["samples_per_sec"]
            / out["sync_adag"]["samples_per_sec"], 4)

    r05_wall_ms = 421.15
    speedup = (round(r05_wall_ms / out["async_adag"]["per_window_wall_ms"], 2)
               if _ok("async_adag") else None)
    parity = None
    if _ok("async_adag") and _ok("async_adag_serial"):
        fl_p = out["async_adag"]["final_loss"]
        fl_s = out["async_adag_serial"]["final_loss"]
        parity = {"pipelined": fl_p, "serial": fl_s,
                  "abs_diff": (None if fl_p is None or fl_s is None
                               else round(abs(fl_p - fl_s), 6))}
    # zero-copy transport tripwires (ISSUE 18), None-degrading like the
    # rest: the shm-ring leg must beat the inproc direct pair on
    # per-window wall (rings remove the socket from the same-host path;
    # if they cannot beat even the in-process direct transport's
    # lock-serialized exchange, the ring is overhead, not a fast path),
    # and the recv_batch leg's hub must actually have served >1 frame
    # per blocking fill (else the depth knob bought no syscalls)
    shm_vs_inproc = None
    shm_beats = None
    if _ok("shm_ring") and _ok("async_adag_inproc"):
        shm_vs_inproc = round(
            out["shm_ring"]["per_window_wall_ms"]
            / out["async_adag_inproc"]["per_window_wall_ms"], 4)
        shm_beats = bool(shm_vs_inproc <= 1.0)
    batch_ok = None
    if _ok("recv_batch"):
        depth = ((out["recv_batch"].get("decomposition") or {})
                 .get("recv_batch_depth") or {})
        if depth.get("count"):
            batch_ok = bool((depth.get("max") or 0) > 1)
    out["acceptance"] = {
        "shm_vs_inproc_per_window": shm_vs_inproc,
        "shm_beats_inproc_direct_ok": shm_beats,
        "batch_syscalls_ok": batch_ok,
        "adag_vs_sync_target": 0.85,
        "adag_vs_sync_ok": (bool(out["adag_vs_sync"] >= 0.85)
                            if "adag_vs_sync" in out else None),
        "inproc_vs_sync_target": 0.95,
        "inproc_vs_sync_ok": (bool(out["adag_inproc_vs_sync"] >= 0.95)
                              if "adag_inproc_vs_sync" in out else None),
        "r05_per_window_wall_ms": r05_wall_ms,
        "per_window_speedup_vs_r05": speedup,
        "per_window_speedup_target": 5.0,
        "per_window_speedup_ok": (None if speedup is None
                                  else bool(speedup >= 5.0)),
        "final_loss_parity": parity,
    }


def _bench_async_native_features(*, workers: int = 2, window: int = 4,
                                 batch: int = 64, windows_per_epoch: int = 4,
                                 epochs: int = 2, rows: int = 256,
                                 dim: int = 8, fields: int = 4):
    """ISSUE-11 acceptance legs: every newly ported native feature
    combination — ``sparse`` (S/V/U/X row exchange), ``adaptive`` (the
    C++ Adasum flat-combining merger) and ``sparse_adaptive`` — runs the
    SAME CTR training on the Python hub and the C++ hub, and the
    tripwire pins the native leg at-or-under the Python hub's per-window
    wall (``native_beats_python_ok``, None-degrading per the PR-3
    convention).  The pre-existing ``async_adag_native`` leg covers the
    dense plain combination; these cover what ISSUE 11 ported."""
    import numpy as np

    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = ctr_embedding_spec(rows, dim=dim, fields=fields,
                              hidden_sizes=(16,))
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    ds = Dataset({
        "features": rng.integers(0, rows, size=(n, fields)).astype(np.int32),
        "label": np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=n)],
    })
    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs, "timing": "wall"}
    combos = {"sparse": {"sparse_tables": "auto"},
              "adaptive": {"adaptive": True},
              "sparse_adaptive": {"sparse_tables": "auto",
                                  "adaptive": True}}
    for leg, extra in combos.items():
        for hub in ("python", "native"):
            name = f"{leg}_{hub}"
            try:
                tr = AsyncADAG(Model.init(spec, seed=0), num_workers=workers,
                               communication_window=window,
                               loss="categorical_crossentropy",
                               batch_size=batch, num_epoch=epochs,
                               learning_rate=0.01, seed=0,
                               native_ps=(hub == "native"), **extra)
                tr.train(ds, shuffle=False)  # compile + warm
                tr.model = Model.init(spec, seed=0)
                tr.history = []
                t0 = time.perf_counter()
                tr.train(ds, shuffle=False)
                wall = time.perf_counter() - t0
                n_windows = max(len(tr.history), 1)
                out[name] = {
                    "hub": hub,
                    "wall_s": round(wall, 3),
                    "per_window_wall_ms": round(wall * 1e3 / n_windows, 2),
                    "samples_per_sec": round(n * epochs / wall, 1),
                }
            except Exception as ex:
                out[name] = {"error": f"{type(ex).__name__}: {ex}"}
    _native_features_acceptance(out)
    return out


def _native_features_acceptance(out: dict) -> None:
    """Attach the ISSUE-11 tripwires, in place: for each ported feature
    combination, the native leg must beat (<=) its Python-hub equivalent
    on per-window wall.  None (not a crash) wherever a leg is missing or
    errored — the PR-3 convention."""
    def _ok(name):
        return isinstance(out.get(name), dict) and "error" not in out[name]

    acc = {}
    for leg in ("sparse", "adaptive", "sparse_adaptive"):
        ratio = None
        if _ok(f"{leg}_python") and _ok(f"{leg}_native"):
            py = out[f"{leg}_python"].get("per_window_wall_ms") or 0
            nat = out[f"{leg}_native"].get("per_window_wall_ms")
            if py and nat is not None:
                ratio = round(nat / py, 4)
        acc[f"{leg}_native_vs_python"] = ratio
        acc[f"{leg}_native_beats_python_ok"] = (None if ratio is None
                                               else bool(ratio <= 1.0))
    out["acceptance"] = acc


def _bench_async_recovery(*, workers: int = 2, window: int = 8, batch: int = 256,
                          windows_per_epoch: int = 8, epochs: int = 3):
    """Issue-4 recovery leg: how the async stack behaves when its wires and
    workers actually fail.

    Three sub-legs on the same workload (AsyncADAG, the headline async
    config):

    - ``fault_free``: warm reference run — the loss/wall denominator.
    - ``sever``: an external hub behind a :class:`ChaosProxy` whose seeded
      plan severs each worker's connection once mid-run; workers reconnect
      with backoff (``max_reconnects``) and finish.  Records the
      reconnect count and the ``ps.reconnect_ms`` time-to-recover
      histogram (telemetry), plus final-loss parity vs fault-free.  Cold
      timing: a warm-up run would consume the proxy's connection ordinals
      and defuse the plan, so wall here includes compile and is NOT
      comparable to the fault-free leg — recovery time comes from the
      telemetry histogram, not the wall clock.
    - ``worker_restart``: a seeded :class:`WorkerKillPlan` kills one worker
      mid-window; the supervisor (``on_worker_failure="restart"``)
      restarts it from the hub's center.
    - ``failover`` (issue 7): an external primary with a hot standby
      (``replica_of``), killed on its commit clock mid-run by a
      :class:`HubKillPlan`; workers fail over to the standby inside the
      reconnect budget.  Records ``ps.failover_ms`` time-to-recover, the
      promoted replica's commit count vs the kill clock (the zero
      acked-commit-loss check, slack = workers x max_inflight) and
      final-loss parity vs fault-free.  Cold timing, like ``sever``.
    - ``snapshot_barrier`` (issue 7): commit throughput on a 4-shard
      in-process facade with the coordinated snapshot barrier ticking
      hard vs not at all — the <5% overhead acceptance number.

    Each sub-leg is individually fallible (error recorded, not fatal) and
    the acceptance block degrades to ``None`` for any tripwire whose
    denominator leg failed — PR 3's convention."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG
    from distkeras_tpu.runtime.faults import (ChaosProxy, Fault, FaultPlan,
                                              HubKillPlan, WorkerKillPlan)
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = mnist_cnn_spec()
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    ds = Dataset({
        "features": rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
        "label": np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)],
    })
    kwargs = dict(loss="categorical_crossentropy", batch_size=batch,
                  num_epoch=epochs, learning_rate=0.01, seed=0,
                  num_workers=workers, communication_window=window)
    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs}

    def final_loss(tr):
        return (round(float(np.mean(tr.history[-8:])), 6)
                if tr.history else None)

    try:
        tr = AsyncADAG(Model.init(spec, seed=0), **kwargs)
        tr.train(ds, shuffle=False)  # compile + warm
        tr.model = Model.init(spec, seed=0)
        tr.history = []
        t0 = time.perf_counter()
        tr.train(ds, shuffle=False)
        out["fault_free"] = {"wall_s": round(time.perf_counter() - t0, 3),
                             "final_loss": final_loss(tr)}
    except Exception as ex:
        out["fault_free"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        model0 = Model.init(spec, seed=0)
        ps = start_parameter_server(model0, mode="adag", num_workers=workers,
                                    idle_timeout=120.0)
        # one sever per worker, at distinct established-pipeline frames —
        # explicit plan (not .random) so the bench exercises exactly one
        # recovery per worker every run
        plan = FaultPlan([Fault(conn=i, direction="s2c", frame=4 + 3 * i,
                                kind="sever") for i in range(workers)])
        try:
            with ChaosProxy("127.0.0.1", ps.port, plan) as proxy:
                tr2 = AsyncADAG(Model.init(spec, seed=0),
                                ps_address=("127.0.0.1", proxy.port),
                                max_reconnects=8, reconnect_backoff=0.05,
                                **kwargs)
                obs.enable()
                obs.reset()
                try:
                    t0 = time.perf_counter()
                    tr2.train(ds, shuffle=False)
                    wall = time.perf_counter() - t0
                    snap = obs.snapshot()
                finally:
                    obs.reset()
                    obs.disable()
                fired = len(proxy.faults_fired)
        finally:
            ps.stop()
        rec = (snap.get("histograms", {}).get("ps.reconnect_ms") or {})
        out["sever"] = {
            "timing": "cold-wall (includes compile; see docstring)",
            "wall_s": round(wall, 3),
            "final_loss": final_loss(tr2),
            "faults_fired": fired,
            "reconnects": snap.get("counters", {}).get("ps.reconnects", 0.0),
            "recovery_ms": {"count": rec.get("count"),
                            "mean": rec.get("mean"),
                            "max": rec.get("max")},
        }
    except Exception as ex:
        out["sever"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        kill_plan = WorkerKillPlan([(workers - 1, windows_per_epoch // 2)],
                                   seed=4)
        tr3 = AsyncADAG(Model.init(spec, seed=0),
                        on_worker_failure="restart", max_worker_restarts=2,
                        fault_hook=kill_plan.hook, **kwargs)
        t0 = time.perf_counter()
        tr3.train(ds, shuffle=False)
        out["worker_restart"] = {
            "timing": "cold-wall",
            "wall_s": round(time.perf_counter() - t0, 3),
            "final_loss": final_loss(tr3),
            "kills_fired": len(kill_plan.fired),
            "restarts": tr3.worker_restarts,
            "worker_errors": len(tr3.worker_errors),
        }
    except Exception as ex:
        out["worker_restart"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        model0 = Model.init(spec, seed=0)
        primary = start_parameter_server(model0, mode="adag",
                                         num_workers=workers,
                                         idle_timeout=None)
        replica = None
        # kill mid-run, on the primary's COMMIT clock (same training
        # progress every run, machine-independent)
        kill = HubKillPlan(after_commits=workers * windows_per_epoch)
        try:
            replica = start_parameter_server(
                model0, mode="adag", num_workers=workers, idle_timeout=None,
                replica_of=("127.0.0.1", primary.port))
            tr4 = AsyncADAG(Model.init(spec, seed=0),
                            ps_address=("127.0.0.1", primary.port),
                            ps_failover=("127.0.0.1", replica.port),
                            max_reconnects=8, reconnect_backoff=0.05,
                            **kwargs)
            obs.enable()
            obs.reset()
            try:
                kill.start(primary)
                t0 = time.perf_counter()
                tr4.train(ds, shuffle=False)
                wall = time.perf_counter() - t0
                snap = obs.snapshot()
            finally:
                obs.reset()
                obs.disable()
            kill.join()
            promoted = bool(replica.promoted)
            fired_at = kill.fired_at_clock
            promoted_at = replica.promoted_at_clock
            replica_commits = int(replica.num_updates)
        finally:
            kill.cancel()
            if replica is not None:
                replica.stop()
            try:
                primary.stop()
            except Exception:
                pass
        fo = (snap.get("histograms", {}).get("ps.failover_ms") or {})
        out["failover"] = {
            "timing": "cold-wall (includes compile; see docstring)",
            "wall_s": round(wall, 3),
            "final_loss": final_loss(tr4),
            "killed_at_clock": fired_at,
            # the replica's clock AT promotion: what actually replicated
            # before the switch (end-of-run num_updates would be inflated
            # by post-failover commits and prove nothing)
            "promoted_at_clock": promoted_at,
            "replica_commits": replica_commits,
            # applied-but-unacked commits at the kill instant: the honest
            # slack on the zero-ACKED-loss bound
            "acked_loss_slack": workers * tr4.max_inflight_commits,
            "promoted": promoted,
            "failovers": snap.get("counters", {}).get("ps.failovers", 0.0),
            "failover_ms": {"count": fo.get("count"), "mean": fo.get("mean"),
                            "max": fo.get("max")},
        }
    except Exception as ex:
        out["failover"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        out["snapshot_barrier"] = _bench_snapshot_barrier()
    except Exception as ex:
        out["snapshot_barrier"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        out["adaptive"] = _bench_async_adaptive()
    except Exception as ex:
        out["adaptive"] = {"error": f"{type(ex).__name__}: {ex}"}

    try:
        out["spot_preemption"] = _bench_async_spot_preemption()
    except Exception as ex:
        out["spot_preemption"] = {"error": f"{type(ex).__name__}: {ex}"}

    _async_recovery_acceptance(out)
    return out


def _bench_snapshot_barrier(*, shards: int = 4, min_wall_s: float = 1.0,
                            snapshot_interval: float = 0.05, reps: int = 3):
    """Commit throughput through a sharded in-process facade with
    COORDINATED snapshot sets (the commit barrier) vs INDEPENDENT
    per-shard snapshotters at the same interval — so the measured delta is
    the barrier's tax alone, not raw snapshot I/O (<5% acceptance
    target).  Each leg runs until ``min_wall_s`` has elapsed (many
    snapshot intervals per leg — a leg shorter than one interval measures
    snapshot-count luck, not cost); median of ``reps``."""
    import os as _os
    import statistics
    import tempfile

    import numpy as np

    from distkeras_tpu.runtime.parameter_server import (
        DeltaParameterServer, ShardedParameterServer, shard_plan)

    t = [np.zeros((128, 128), np.float32) for _ in range(2 * shards)]
    plan = shard_plan(t, shards)
    delta = [np.ones(a.shape, np.float32) for a in t]

    def one_leg(coordinated: bool) -> float:
        with tempfile.TemporaryDirectory() as d:
            if coordinated:
                def factory(w, sid):
                    return DeltaParameterServer(w, idle_timeout=None,
                                                shard_id=sid)
                ps = ShardedParameterServer(
                    t, plan, factory, snapshot_dir=d,
                    snapshot_interval=snapshot_interval)
            else:
                def factory(w, sid):
                    return DeltaParameterServer(
                        w, idle_timeout=None, shard_id=sid,
                        snapshot_dir=_os.path.join(d, f"shard-{sid:02d}"),
                        snapshot_interval=snapshot_interval)
                ps = ShardedParameterServer(t, plan, factory)
            ps.start()
            try:
                n = 0
                t0 = time.perf_counter()
                while True:
                    ps.commit_direct(delta, 0)
                    n += 1
                    elapsed = time.perf_counter() - t0
                    if elapsed >= min_wall_s:
                        return n / elapsed
            finally:
                ps.kill()

    base = statistics.median(one_leg(False) for _ in range(reps))
    coord = statistics.median(one_leg(True) for _ in range(reps))
    return {
        "shards": shards,
        "leg_wall_s": min_wall_s,
        "snapshot_interval_s": snapshot_interval,
        "per_shard_commits_per_s": round(base, 1),
        "coordinated_commits_per_s": round(coord, 1),
        "overhead_pct": round(100.0 * (base - coord) / base, 2),
    }


def _bench_async_adaptive(*, workers: int = 8, window: int = 4,
                          batch: int = 64, windows_per_epoch: int = 4,
                          epochs: int = 2,
                          jitter_s=(0.02, 0.06), seed: int = 11):
    """Issue-10 adaptive leg: at ``workers`` workers with ONE
    ChaosProxy-throttled straggler (the whole fleet fronts one proxy;
    seeded jitter applies to conn 0 only), does ``adaptive=True`` beat
    plain ADAG's final loss at comparable wall time?

    Both legs run the IDENTICAL workload, model seed, proxy seed and
    telemetry (health reports every 0.25 s, detectors on a fast drill
    cadence) — the only difference is the knob, so the delta is the
    control loop's: Adasum merging of queued commits, DynSGD-style
    per-worker scales from the live staleness series, and storm
    backpressure.  Cold timing per leg (each leg compiles its own
    trainer); the tripwire therefore compares LOSS at a bounded wall
    RATIO rather than raw walls."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.observability import health as health_mod
    from distkeras_tpu.runtime.async_trainer import AsyncADAG
    from distkeras_tpu.runtime.faults import ChaosProxy
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (32,), "num_outputs": 10},
                     input_shape=(16,))
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    x = rng.normal(size=(n, 16)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)]
    ds = Dataset({"features": x, "label": y})
    kwargs = dict(loss="categorical_crossentropy", batch_size=batch,
                  num_epoch=epochs, learning_rate=0.05, seed=0,
                  num_workers=workers, communication_window=window)
    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs, "jitter_s": list(jitter_s), "seed": seed}

    for name, adaptive in (("plain", False), ("adaptive", True)):
        try:
            health_mod.reset_default()
            mon = health_mod.monitor()
            # drill cadence: the run is seconds long, the default 2 s
            # check / 10 s cooldown would let it end before reacting
            # (restored in the finally — the process monitor outlives
            # this leg)
            old_cadence = (mon.check_interval_s, mon.cooldown_s)
            mon.check_interval_s = 0.2
            mon.cooldown_s = 0.5
            model0 = Model.init(spec, seed=0)
            ps = proxy = None
            try:
                # hub and proxy start INSIDE the try: a bind failure must
                # still stop whatever came up and restore the cadence, or
                # the leak contaminates the second leg
                ps = start_parameter_server(model0, mode="adag",
                                            num_workers=workers,
                                            idle_timeout=None,
                                            adaptive=adaptive)
                proxy = ChaosProxy("127.0.0.1", ps.port,
                                   jitter_delay_s=tuple(jitter_s),
                                   seed=seed, slow_conns={0}).start()
                tr = AsyncADAG(Model.init(spec, seed=0),
                               ps_address=("127.0.0.1", proxy.port),
                               adaptive=adaptive, health_interval_s=0.25,
                               max_reconnects=8, reconnect_backoff=0.05,
                               **kwargs)
                obs.enable()
                obs.reset()
                try:
                    t0 = time.perf_counter()
                    tr.train(ds, shuffle=False)
                    wall = time.perf_counter() - t0
                    snap = obs.snapshot()
                    events = [e["kind"] for e in mon.events()]
                finally:
                    obs.reset()
                    obs.disable()
            finally:
                if proxy is not None:
                    proxy.stop()
                if ps is not None:
                    ps.stop()
                mon.check_interval_s, mon.cooldown_s = old_cadence
                health_mod.reset_default()
            counters = snap.get("counters", {})
            loss = (round(float(np.mean(tr.history[-8:])), 6)
                    if tr.history else None)
            out[name] = {
                "timing": "cold-wall (each leg compiles its own trainer)",
                "wall_s": round(wall, 3),
                "final_loss": loss,
                "merged_commits": counters.get("ps_merged_commits_total",
                                               0.0),
                "rate_scaled_commits": counters.get(
                    "ps_rate_scaled_commits_total", 0.0),
                "backpressure_hints": counters.get(
                    "ps_backpressure_hints_total", 0.0),
                "events": sorted(set(events)),
            }
        except Exception as ex:
            out[name] = {"error": f"{type(ex).__name__}: {ex}"}
    return out


def _bench_async_spot_preemption(*, workers: int = 6, preempt: int = 2,
                                 window: int = 4, batch: int = 64,
                                 windows_per_epoch: int = 6,
                                 epochs: int = 3, deadline_s: float = 5.0):
    """Issue-19 self-scaling leg: preempt ``preempt`` of ``workers``
    workers mid-run with a planned :class:`SpotPreemptionPlan` notice
    (SIGTERM-with-deadline semantics) under ``autoscale=True``.  Each
    preempted worker drains gracefully — in-flight commits acked, BYE
    sent — and the FleetController authorizes a budget-neutral respawn
    against the hub's current center, with zero operator input.

    Measures fleet throughput (windows/s from the trainer's window log)
    BEFORE the first notice vs AFTER the last one: the
    ``preemption_recovered_ok`` tripwire wants >= 90% restored.
    ``drain_zero_loss_ok`` wants every drain clean with nothing left
    unacked.  Cold timing (one compile inside the measured wall), so the
    rates — not the wall — carry the verdict."""
    import numpy as np

    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.observability import health as health_mod
    from distkeras_tpu.runtime.async_trainer import AsyncADAG
    from distkeras_tpu.runtime.faults import SpotPreemptionPlan

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (32,), "num_outputs": 10},
                     input_shape=(16,))
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    x = rng.normal(size=(n, 16)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)]
    ds = Dataset({"features": x, "label": y})
    # notices land on the LAST `preempt` workers, staggered one window
    # apart, in the middle of epoch 1 — past compile, with room to
    # measure the restored rate afterwards
    mid = windows_per_epoch // 2
    plan = SpotPreemptionPlan(
        [(workers - 1 - i, mid + i) for i in range(preempt)],
        deadline_s=deadline_s)
    out = {"workers": workers, "preempt": preempt, "window": window,
           "batch": batch, "epochs": epochs, "deadline_s": deadline_s}
    health_mod.reset_default()
    mon = health_mod.monitor()
    old_cadence = (mon.check_interval_s, mon.cooldown_s)
    mon.check_interval_s = 0.2
    mon.cooldown_s = 0.5
    try:
        tr = AsyncADAG(Model.init(spec, seed=0),
                       loss="categorical_crossentropy", batch_size=batch,
                       num_epoch=epochs, learning_rate=0.05, seed=0,
                       num_workers=workers, communication_window=window,
                       elastic=True, autoscale=True,
                       health_interval_s=0.25,
                       on_worker_failure="restart", max_worker_restarts=1,
                       fault_hook=plan.hook)
        t0 = time.perf_counter()
        tr.train(ds, shuffle=False)
        wall = time.perf_counter() - t0
    finally:
        mon.check_interval_s, mon.cooldown_s = old_cadence
        health_mod.reset_default()
    log = sorted(tr._window_log)
    fired_at = sorted(plan.fired_at)
    pre_rate = post_rate = None
    if log and fired_at:
        t_start, t_end = log[0][0], log[-1][0]
        t_pre, t_post = fired_at[0], fired_at[-1]
        n_pre = sum(1 for ts, _ in log if ts < t_pre)
        n_post = sum(1 for ts, _ in log if ts >= t_post)
        if t_pre > t_start:
            pre_rate = n_pre / (t_pre - t_start)
        if t_end > t_post:
            post_rate = n_post / (t_end - t_post)
    stats = (tr.fleet_controller.stats()
             if tr.fleet_controller is not None else {})
    drains = list(tr.worker_preemptions)
    out.update({
        "timing": "cold-wall (one compile inside the measured wall)",
        "wall_s": round(wall, 3),
        "final_loss": (round(float(np.mean(tr.history[-8:])), 6)
                       if tr.history else None),
        "preemptions_fired": len(plan.fired),
        "drains": drains,
        "drains_clean": (all(d["drained_clean"] for d in drains)
                         if drains else None),
        "outstanding_after_drain": (max(d["outstanding_after_drain"]
                                        for d in drains)
                                    if drains else None),
        "respawns": stats.get("preemptions", 0),
        "pre_rate_windows_s": (round(pre_rate, 2)
                               if pre_rate is not None else None),
        "post_rate_windows_s": (round(post_rate, 2)
                                if post_rate is not None else None),
        "restarts": tr.worker_restarts,
        "worker_errors": len(tr.worker_errors),
    })
    return out


def _async_recovery_acceptance(out: dict) -> None:
    """Attach the issue-4 recovery tripwires, in place.  Booleans, or None
    when a denominator leg is missing/errored (graceful degradation,
    matching ``_async_acceptance``): recovery must COMPLETE (every planned
    fault fired, every reconnect/restart succeeded, the run finished) and
    the recovered trajectory must LAND where the fault-free one does."""
    def _ok(name):
        return isinstance(out.get(name), dict) and "error" not in out[name]

    ff_loss = out["fault_free"].get("final_loss") if _ok("fault_free") else None

    def parity(leg):
        loss = out[leg].get("final_loss") if _ok(leg) else None
        if loss is None or ff_loss is None:
            return None, None
        tol = max(0.05, 0.15 * abs(ff_loss))
        return round(abs(loss - ff_loss), 6), tol

    sever_diff, sever_tol = parity("sever")
    restart_diff, restart_tol = parity("worker_restart")
    failover_diff, failover_tol = parity("failover")
    fo = out.get("failover", {})
    barrier = out.get("snapshot_barrier", {})
    barrier_pct = (barrier.get("overhead_pct")
                   if isinstance(barrier, dict) and "error" not in barrier
                   else None)
    # issue-10 adaptive leg: adaptive vs plain ADAG with one throttled
    # straggler — loss must not be worse at comparable wall, and the
    # control loop must have visibly REACTED (merged or rate-scaled at
    # least one commit); None-degrading like every other leg
    ad = out.get("adaptive", {})

    def _leg(name):
        leg = ad.get(name) if isinstance(ad, dict) else None
        return (leg if isinstance(leg, dict) and "error" not in leg
                else None)

    ad_plain, ad_adap = _leg("plain"), _leg("adaptive")
    ad_ratio = None
    ad_beats = None
    ad_reacted = None
    if ad_plain is not None and ad_adap is not None:
        p_loss, a_loss = ad_plain.get("final_loss"), ad_adap.get("final_loss")
        p_wall, a_wall = ad_plain.get("wall_s"), ad_adap.get("wall_s")
        if p_wall:
            ad_ratio = round(a_wall / p_wall, 3)
        if p_loss is not None and a_loss is not None and ad_ratio is not None:
            # "beats at equal wall time": both legs run the same windows,
            # so equal-work walls must stay comparable (<= 1.25x) and the
            # adaptive loss must land at or below plain (small slack for
            # run-to-run float noise)
            ad_beats = bool(a_loss <= p_loss + 0.01 * max(1.0, abs(p_loss))
                            and ad_ratio <= 1.25)
    if ad_adap is not None:
        ad_reacted = bool((ad_adap.get("merged_commits") or 0)
                          + (ad_adap.get("rate_scaled_commits") or 0) >= 1)
    # issue-19 spot-preemption leg: every planned notice fired, every
    # preempted worker drained and was respawned without operator input,
    # and the fleet restored >= 90% of its pre-preemption throughput;
    # drain_zero_loss separately pins that NOTHING acked was left behind
    sp = out.get("spot_preemption", {})
    sp_ok = sp if isinstance(sp, dict) and sp and "error" not in sp else None
    sp_recovered = None
    sp_zero_loss = None
    if sp_ok is not None:
        pre = sp_ok.get("pre_rate_windows_s")
        post = sp_ok.get("post_rate_windows_s")
        planned = int(sp_ok.get("preempt") or 0)
        if pre and post is not None:
            sp_recovered = bool(
                sp_ok.get("preemptions_fired") == planned
                and (sp_ok.get("respawns") or 0) >= planned
                and post >= 0.9 * pre
                and sp_ok.get("worker_errors") == 0)
        sp_zero_loss = bool(
            len(sp_ok.get("drains") or ()) == sp_ok.get("preemptions_fired")
            and sp_ok.get("drains_clean") is True
            and sp_ok.get("outstanding_after_drain") == 0)
    out["acceptance"] = {
        "sever_recovered_ok": (bool(out["sever"]["faults_fired"] >= 1
                                    and out["sever"]["reconnects"] >= 1)
                               if _ok("sever") else None),
        "sever_loss_abs_diff": sever_diff,
        "sever_loss_tol": sever_tol,
        "sever_loss_parity_ok": (None if sever_diff is None
                                 else bool(sever_diff <= sever_tol)),
        "worker_restart_ok": (bool(out["worker_restart"]["restarts"] >= 1
                                   and out["worker_restart"]["worker_errors"] == 0)
                              if _ok("worker_restart") else None),
        "restart_loss_abs_diff": restart_diff,
        "restart_loss_tol": restart_tol,
        "restart_loss_parity_ok": (None if restart_diff is None
                                   else bool(restart_diff <= restart_tol)),
        # issue-7 failover leg: the kill fired, workers failed over, the
        # standby promoted, and every ACKED commit survived — judged at
        # PROMOTION time (clock at promotion >= kill clock minus the
        # honest in-flight slack; post-failover commits can't inflate it)
        "failover_recovered_ok": (bool(
            fo["promoted"] and fo["failovers"] >= 1
            and fo["promoted_at_clock"] is not None
            and fo["promoted_at_clock"] >= (fo["killed_at_clock"]
                                            - fo["acked_loss_slack"]))
            if _ok("failover") else None),
        "failover_ms_recorded": (bool((fo["failover_ms"]["count"] or 0) >= 1)
                                 if _ok("failover") else None),
        "failover_loss_abs_diff": failover_diff,
        "failover_loss_tol": failover_tol,
        "failover_loss_parity_ok": (None if failover_diff is None
                                    else bool(failover_diff <= failover_tol)),
        "snapshot_barrier_overhead_pct": barrier_pct,
        "snapshot_barrier_ok": (None if barrier_pct is None
                                else bool(barrier_pct < 5.0)),
        "adaptive_plain_final_loss": (ad_plain.get("final_loss")
                                      if ad_plain else None),
        "adaptive_final_loss": (ad_adap.get("final_loss")
                                if ad_adap else None),
        "adaptive_wall_ratio": ad_ratio,
        "adaptive_beats_plain_ok": ad_beats,
        "adaptive_reacted_ok": ad_reacted,
        "preemption_pre_rate_windows_s": (sp_ok.get("pre_rate_windows_s")
                                          if sp_ok else None),
        "preemption_post_rate_windows_s": (sp_ok.get("post_rate_windows_s")
                                           if sp_ok else None),
        "preemption_recovered_ok": sp_recovered,
        "drain_zero_loss_ok": sp_zero_loss,
    }


def _bench_observability(*, workers: int = 2, window: int = 8, batch: int = 256,
                         windows_per_epoch: int = 8, epochs: int = 3,
                         reps: int = 3):
    """Issue-5 observability leg: what does fleet-wide tracing COST, and
    does the attribution pipeline actually work end to end?

    Two sub-legs on the headline async config (AsyncADAG, python hub,
    pipelined sockets):

    - ``telemetry_off`` vs ``telemetry_on``: the same warmed trainer timed
      with telemetry disabled and then fully enabled (registry + spans +
      per-worker trace contexts + end-of-run trace flush to a temp
      ``DKT_TRACE_DIR``).  ``overhead_pct`` is the median-of-``reps``
      relative wall cost — the <3% acceptance target.  No profiler here:
      the leg measures telemetry's own tax, nothing else's.
    - the on-leg's flushed trace is merged (``merge_traces``) and
      ``fleet_report`` runs over it: the leg records hub-commit context
      coverage (the >=95% acceptance criterion) and whether a straggler
      ranking came back.
    """
    import os as _os
    import tempfile

    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.observability import distributed as dtrace
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = mnist_cnn_spec()
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    ds = Dataset({
        "features": rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
        "label": np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)],
    })
    kwargs = dict(loss="categorical_crossentropy", batch_size=batch,
                  num_epoch=epochs, learning_rate=0.01, seed=0,
                  num_workers=workers, communication_window=window)

    tr = AsyncADAG(Model.init(spec, seed=0), **kwargs)
    tr.train(ds, shuffle=False)  # compile + warm

    def timed(telemetry: bool, trace_dir=None):
        walls = []
        for _ in range(reps):
            tr.model = Model.init(spec, seed=0)
            tr.history = []
            if telemetry:
                obs.enable()
                obs.reset()
                # one rep = one job's evidence: earlier reps' flushed
                # files must not stack up as phantom extra "processes"
                # in the merged trace
                if trace_dir is not None:
                    import glob as _glob

                    for f in _glob.glob(_os.path.join(trace_dir,
                                                      "trace-*.jsonl")):
                        _os.remove(f)
            else:
                # the off leg must actually be OFF even when the operator
                # exported DKT_TELEMETRY=1 (the documented enable path) —
                # otherwise overhead_pct compares on vs on and reads ~0
                obs.disable()
            t0 = time.perf_counter()
            tr.train(ds, shuffle=False)
            walls.append(time.perf_counter() - t0)
            if telemetry:
                obs.disable()
        return float(np.median(walls))

    was_enabled = obs.enabled()
    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs, "reps": reps, "timing": "wall-median"}
    wall_off = timed(False)
    out["telemetry_off"] = {"wall_s": round(wall_off, 3)}

    with tempfile.TemporaryDirectory() as td:
        old_dir = _os.environ.get("DKT_TRACE_DIR")
        _os.environ["DKT_TRACE_DIR"] = td
        try:
            wall_on = timed(True, trace_dir=td)
        finally:
            if old_dir is None:
                _os.environ.pop("DKT_TRACE_DIR", None)
            else:
                _os.environ["DKT_TRACE_DIR"] = old_dir
            if was_enabled:
                obs.enable()
        merged = dtrace.merge_traces(td)
        report = dtrace.fleet_report(trace_dir=td)
    out["telemetry_on"] = {"wall_s": round(wall_on, 3)}
    out["overhead_pct"] = round((wall_on / wall_off - 1.0) * 100.0, 2)
    out["merged_trace"] = {
        "processes": merged["otherData"]["processes"],
        "spans": merged["otherData"]["spans"],
        "alignment_error_us": merged["otherData"]["alignment_error_us"],
    }
    out["fleet"] = {
        "commit_context_coverage": report["commit_context_coverage"],
        "total_commits": report["total_commits"],
        "top_straggler": report["top_straggler"],
        "workers_seen": len(report["workers"]),
    }
    _observability_acceptance(out)
    return out


def _observability_acceptance(out: dict) -> None:
    """Attach the issue-5 tripwires, in place: tracing overhead under the
    3% target, and >=95% of hub commit spans carrying a worker trace
    context.  Booleans, or None when a leg is missing/errored (graceful
    degradation, the PR-3 convention)."""
    overhead = out.get("overhead_pct")
    coverage = (out.get("fleet") or {}).get("commit_context_coverage")
    out["acceptance"] = {
        "overhead_pct": overhead,
        "overhead_pct_target": 3.0,
        "overhead_ok": None if overhead is None else bool(overhead < 3.0),
        "commit_context_coverage": coverage,
        "coverage_target": 0.95,
        "coverage_ok": None if coverage is None else bool(coverage >= 0.95),
        "straggler_ranked": (bool((out.get("fleet") or {}).get("top_straggler")
                                  is not None)
                             if isinstance(out.get("fleet"), dict) else None),
    }


def _bench_health(*, workers: int = 2, window: int = 8, batch: int = 256,
                  windows_per_epoch: int = 8, epochs: int = 3,
                  reps: int = 3, health_interval_s: float = 0.25):
    """Issue-8 fleet-health leg: what does the LIVE health plane COST with
    everything on, and does it actually see the fleet?

    Same warmed AsyncADAG / python-hub / pipelined-socket config as
    ``_bench_observability``, timed twice:

    - ``health_off``: telemetry disabled, no tracking, no reports — the
      zero-cost-when-off contract's reference wall.
    - ``health_on``: registry + spans enabled, the trainer's window
      instruments opted into sliding-window time series (``obs.track``),
      workers streaming periodic reports to the hub (wire action ``M``)
      where the rolling detectors run — the WHOLE plane.

    ``overhead_pct`` is the median-of-``reps`` relative wall cost — the
    <3% acceptance tripwire.  The on-leg also records what the plane saw:
    per-worker collector coverage, reports ingested, tracked series, and
    any ringed events (a healthy 2-worker run should fire none)."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.observability import health as _health
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import mnist_cnn_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    spec = mnist_cnn_spec()
    rng = np.random.default_rng(0)
    n = workers * batch * window * windows_per_epoch
    ds = Dataset({
        "features": rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
        "label": np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=n)],
    })
    tr = AsyncADAG(Model.init(spec, seed=0),
                   loss="categorical_crossentropy", batch_size=batch,
                   num_epoch=epochs, learning_rate=0.01, seed=0,
                   num_workers=workers, communication_window=window)
    tr.train(ds, shuffle=False)  # compile + warm

    tracked = ("async_window_wall_seconds", "async_windows_total",
               "ps_commit_staleness")

    def timed(on: bool):
        walls = []
        for _ in range(reps):
            tr.model = Model.init(spec, seed=0)
            tr.history = []
            if on:
                obs.enable()
                obs.reset()
                _health.reset_default()
                for name in tracked:
                    obs.track(name)
                tr.health_interval_s = float(health_interval_s)
            else:
                # fully off even under an exported DKT_TELEMETRY=1 —
                # otherwise overhead_pct compares on vs on and reads ~0
                obs.disable()
                tr.health_interval_s = None
            t0 = time.perf_counter()
            tr.train(ds, shuffle=False)
            walls.append(time.perf_counter() - t0)
            if on:
                obs.disable()
        return float(np.median(walls))

    was_enabled = obs.enabled()
    out = {"workers": workers, "window": window, "batch": batch,
           "epochs": epochs, "reps": reps,
           "health_interval_s": health_interval_s, "timing": "wall-median"}
    try:
        wall_off = timed(False)
        out["health_off"] = {"wall_s": round(wall_off, 3)}
        wall_on = timed(True)
        out["health_on"] = {"wall_s": round(wall_on, 3)}
        out["overhead_pct"] = round((wall_on / wall_off - 1.0) * 100.0, 2)
        # evidence from the LAST on-rep (reset_default ran per rep, so
        # this is one run's view, not reps stacked)
        fleet = _health.collector().snapshot()
        seen = fleet.get("workers") or {}
        out["collector"] = {
            "workers_seen": len(seen),
            "reports_ingested": sum((e.get("meta") or {}).get("reports", 0)
                                    for e in seen.values()),
            "tracked_series": len(obs.tracked_snapshot()),
            "events": len(_health.monitor().events()),
        }
    finally:
        for name in tracked:
            obs.untrack(name)
        _health.reset_default()
        if was_enabled:
            obs.enable()
    _health_acceptance(out)
    return out


def _health_acceptance(out: dict) -> None:
    """Attach the issue-8 tripwires, in place: the fully-on health plane
    (tracking + streaming collector + detectors) under the 3% wall
    overhead target, and the collector actually covering the fleet (every
    worker reported at least once).  Booleans, or None when a leg is
    missing/errored (graceful degradation, the PR-3 convention)."""
    overhead = out.get("overhead_pct")
    col = out.get("collector") if isinstance(out.get("collector"), dict) else {}
    seen = col.get("workers_seen")
    reports = col.get("reports_ingested")
    workers = out.get("workers")
    out["acceptance"] = {
        "overhead_pct": overhead,
        "overhead_pct_target": 3.0,
        "overhead_ok": None if overhead is None else bool(overhead < 3.0),
        "workers_seen": seen,
        "fleet_covered": (None if seen is None or workers is None
                          else bool(seen >= workers)),
        "reports_ok": None if reports is None else bool(reports > 0),
    }


def _bench_embedding(*, rows: int = 25600, dim: int = 128, fields: int = 2,
                     batch: int = 32, window: int = 4,
                     windows_per_epoch: int = 4, epochs: int = 2,
                     workers: int = 2, reps: int = 3):
    """Issue-9 row-sparse embedding leg: what does the PS wire COST when a
    CTR-shaped model (one [rows, dim] table dwarfing the dense head) moves
    only the rows each window touches?

    Same AsyncADAG / python-hub / pipelined-socket config as the other
    async legs, run twice on a synthetic CTR log whose per-window batches
    draw ``batch * window * fields`` uniform ids (~1% of the vocabulary at
    the default shape):

    - ``dense``: sparse_tables=None — every window moves the whole leaf
      both ways (today's wire).
    - ``sparse``: sparse_tables="auto" — pulls carry row-id sets (action
      S/V), commits carry (row_ids, row_grads) pairs (action U).

    ``wire_bytes`` is the hub's pull+commit byte counters; the EXCHANGE
    bytes subtract each worker's one initial full-center pull (both legs
    pay it identically — it seeds the sparse caches), so the tripwire
    ratio compares the steady-state window exchange the issue is about.
    Records rows/s (committed rows over the run wall), the measured
    touched-row fraction, and the issue-9 acceptance tripwire:
    sparse exchange bytes <= 1.1 x touched_fraction x dense exchange."""
    import numpy as np

    from distkeras_tpu import observability as obs
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG
    from distkeras_tpu.utils import flatten_weights

    # a small head (hidden 8): the leg measures the TABLE's wire story,
    # and the head rides every sparse frame whole — at CTR shapes the
    # table dwarfs it, which is the regime the tripwire bound assumes
    spec = ctr_embedding_spec(rows, dim=dim, fields=fields,
                              hidden_sizes=(8,))
    n = workers * batch * window * windows_per_epoch
    # hot_prob=0: uniform id draws, so the touched fraction is set by
    # batch*window*fields vs rows (the 1%-fraction shape the tripwire
    # is phrased at), not by hot-set luck
    ds = synthetic_ctr_dataset(n, rows, fields=fields, seed=0, hot_prob=0.0)
    n_windows = workers * windows_per_epoch * epochs
    flat, _ = flatten_weights(Model.init(spec, seed=0).params)
    center_bytes = sum(np.asarray(w).nbytes for w in flat)

    def leg(sparse: bool):
        tr = AsyncADAG(Model.init(spec, seed=0),
                       loss="categorical_crossentropy", batch_size=batch,
                       num_epoch=epochs, learning_rate=0.05, seed=0,
                       num_workers=workers, communication_window=window,
                       sparse_tables="auto" if sparse else None)
        tr.train(ds, shuffle=False)  # compile + warm (telemetry off)
        walls = []
        counters = {}
        for _ in range(reps):
            tr.model = Model.init(spec, seed=0)
            tr.history = []
            obs.enable()
            obs.reset()
            t0 = time.perf_counter()
            tr.train(ds, shuffle=False)
            walls.append(time.perf_counter() - t0)
            counters = dict(obs.snapshot()["counters"])
            obs.disable()
            obs.reset()
        wall = float(np.median(walls))
        wire = (counters.get("ps_pull_bytes_total", 0.0)
                + counters.get("ps_commit_bytes_total", 0.0))
        exchange = max(0.0, wire - workers * center_bytes)
        out = {"wall_s": round(wall, 3), "wire_bytes": round(wire),
               "exchange_bytes": round(exchange)}
        if sparse:
            committed = counters.get("ps.sparse_rows_committed", 0.0)
            out["rows_pulled"] = round(
                counters.get("ps.sparse_rows_pulled", 0.0))
            out["rows_committed"] = round(committed)
            out["rows_per_s"] = (round(committed / wall, 1) if wall > 0
                                 else None)
            out["wire_bytes_saved"] = round(
                counters.get("ps.sparse_wire_bytes_saved", 0.0))
            out["touched_row_fraction"] = (
                round(committed / (n_windows * rows), 5)
                if n_windows * rows else None)
        return out

    def hot_leg(hot_fraction=0.01, hot_prob=0.9):
        """The issue-15 cold-start + skewed-access leg: a hot/cold CTR
        draw, a hot-tier client cache sized to ~2x the hot set, and a
        sparse-capable standby attached to the hub — so the leg measures
        the THREE hyperscale edges at once: client cache memory (bounded
        LRU vs full table), replication bytes (REPL_SPARSE row deltas vs
        the dense-R equivalent) and the cache hit economics (cold start
        misses, warm hits at skew)."""
        from distkeras_tpu.models.base import sparse_leaf_indices
        from distkeras_tpu.runtime.parameter_server import (
            ADAGParameterServer)

        ds_hot = synthetic_ctr_dataset(n, rows, fields=fields, seed=0,
                                       hot_fraction=hot_fraction,
                                       hot_prob=hot_prob)
        hot_rows = max(1, int(round(rows * hot_fraction)))
        cache_rows = min(rows, 2 * hot_rows)
        model = Model.init(spec, seed=0)
        flat_w = [np.asarray(w, np.float32)
                  for w in flatten_weights(model.params)[0]]
        sparse_idx = sparse_leaf_indices(spec, model.params)
        hub = ADAGParameterServer(flat_w, num_workers=workers,
                                  idle_timeout=None,
                                  sparse_leaves=sparse_idx)
        # bench runs are short: decay (and publish) the hot-set estimate
        # every few folds so the leg records a non-None estimate
        hub.TOUCH_DECAY_EVERY = 8
        hub.start()
        standby = ADAGParameterServer(flat_w, num_workers=workers,
                                      idle_timeout=None,
                                      sparse_leaves=sparse_idx,
                                      replica_of=("127.0.0.1", hub.port))
        standby.start()
        try:
            if not standby.wait_synced(30):
                raise RuntimeError("hot leg: standby never synced")
            tr = AsyncADAG(model, loss="categorical_crossentropy",
                           batch_size=batch, num_epoch=epochs,
                           learning_rate=0.05, seed=0,
                           num_workers=workers,
                           communication_window=window,
                           sparse_tables="auto",
                           sparse_cache_rows=cache_rows,
                           ps_address=("127.0.0.1", hub.port))
            obs.enable()
            obs.reset()
            t0 = time.perf_counter()
            tr.train(ds_hot, shuffle=False)
            wall = time.perf_counter() - t0
            counters = dict(obs.snapshot()["counters"])
            gauges = dict(obs.snapshot()["gauges"])
            obs.disable()
            obs.reset()
            repl_bytes = hub._feed.repl_sparse_bytes if hub._feed else 0
            saved = sum(v for k, v in counters.items()
                        if k.startswith("ps.repl_sparse_bytes_saved"))
            hits = sum(v for k, v in counters.items()
                       if k.startswith("ps_sparse_cache_hits_total"))
            misses = sum(v for k, v in counters.items()
                         if k.startswith("ps_sparse_cache_misses_total"))
            committed = sum(v for k, v in counters.items()
                            if k.startswith("ps.sparse_rows_committed"))
            hot_est = [v for k, v in gauges.items()
                       if k.startswith("ps.sparse_hot_rows")]
            commits = counters.get("ps_commits_total", 0.0)
            table_bytes = rows * dim * 4
            return {
                "wall_s": round(wall, 3),
                "hot_fraction": hot_fraction, "hot_prob": hot_prob,
                "cache_rows": cache_rows,
                # per-worker host bytes the hot tier holds vs the full
                # table cache a PR-9 client would hold
                "cache_bytes": cache_rows * dim * 4,
                "full_cache_bytes": table_bytes,
                "cache_memory_ratio": round(cache_rows / rows, 5),
                "cache_hits": round(hits), "cache_misses": round(misses),
                "cache_hit_rate": (round(hits / (hits + misses), 4)
                                   if hits + misses else None),
                "repl_sparse_bytes": round(repl_bytes),
                "repl_bytes_saved": round(saved),
                "repl_dense_equiv_bytes": round(repl_bytes + saved),
                "rows_committed": round(committed),
                "hot_rows_estimate": (round(max(hot_est))
                                      if hot_est else None),
                "touched_row_fraction": (
                    round(committed / (commits * rows), 5)
                    if commits and rows else None),
            }
        finally:
            standby.stop()
            hub.stop()

    was_enabled = obs.enabled()
    out = {"rows": rows, "dim": dim, "fields": fields, "batch": batch,
           "window": window, "epochs": epochs, "workers": workers,
           "reps": reps, "timing": "wall-median",
           "table_mb": round(rows * dim * 4 / 2**20, 2),
           "center_bytes": center_bytes}
    try:
        out["dense"] = leg(False)
        out["sparse"] = leg(True)
        try:
            out["hot"] = hot_leg()
        except Exception as e:  # the hot leg must not axe the PR-9 legs
            out["hot"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        if was_enabled:
            obs.enable()
        else:
            obs.disable()
    _embedding_acceptance(out)
    return out


def _embedding_acceptance(out: dict) -> None:
    """Attach the issue-9 + issue-15 tripwires, in place: the sparse
    leg's steady-state exchange bytes under ``1.1 x touched_fraction``
    of the dense leg's, with a rows/s figure recorded; the hot leg's
    replication bytes under ``1.1 x touched_fraction`` of the dense-R
    equivalent, its client cache memory scaling with the hot fraction
    (cache/table ratio <= 4x the hot fraction by construction of the
    2x-hot-set sizing, asserted anyway against drift), and a warm hit
    rate that shows the hot tier actually absorbing the skew.  Booleans,
    or None when a leg is missing/errored (graceful degradation, the
    PR-3 convention)."""
    dense = out.get("dense") if isinstance(out.get("dense"), dict) else {}
    sparse = out.get("sparse") if isinstance(out.get("sparse"), dict) else {}
    hot = out.get("hot") if isinstance(out.get("hot"), dict) else {}
    dense_bytes = dense.get("exchange_bytes")
    sparse_bytes = sparse.get("exchange_bytes")
    frac = sparse.get("touched_row_fraction")
    ratio = (round(sparse_bytes / dense_bytes, 5)
             if sparse_bytes and dense_bytes else None)
    bound = round(1.1 * frac, 5) if frac else None
    rows_per_s = sparse.get("rows_per_s")
    repl = hot.get("repl_sparse_bytes")
    repl_equiv = hot.get("repl_dense_equiv_bytes")
    hot_frac = hot.get("touched_row_fraction")
    repl_ratio = (round(repl / repl_equiv, 5)
                  if repl and repl_equiv else None)
    repl_bound = round(1.1 * hot_frac, 5) if hot_frac else None
    cache_ratio = hot.get("cache_memory_ratio")
    hot_fraction = hot.get("hot_fraction")
    hit_rate = hot.get("cache_hit_rate")
    out["acceptance"] = {
        "wire_ratio": ratio,
        "wire_ratio_bound": bound,
        "touched_row_fraction": frac,
        "sparse_wire_ok": (None if ratio is None or bound is None
                           else bool(ratio <= bound)),
        "rows_per_s": rows_per_s,
        "rows_per_s_recorded": (None if rows_per_s is None
                                else bool(rows_per_s > 0)),
        # -- issue-15 hyperscale tripwires --------------------------------
        "repl_ratio": repl_ratio,
        "repl_ratio_bound": repl_bound,
        "repl_sparse_ok": (None if repl_ratio is None or repl_bound is None
                           else bool(repl_ratio <= repl_bound)),
        "cache_memory_ratio": cache_ratio,
        "cache_memory_ok": (None if cache_ratio is None
                            or not hot_fraction
                            else bool(cache_ratio <= 4.0 * hot_fraction)),
        "cache_hit_rate": hit_rate,
        "cache_hit_ok": (None if hit_rate is None
                         else bool(hit_rate >= 0.3)),
    }


def _leg_ratio(current: float, base: float):
    """current/base rounded, or None when either side is missing/zero."""
    if not current or not base:
        return None
    return round(current / base, 4)


def _apply_leg_baselines(out: dict, baseline: dict) -> None:
    """Attach per-leg ``vs_baseline`` ratios (throughput ratios, > 1 means
    faster than the recorded best) so an MFU/decode regression trips
    visibly.  Legs are matched by config key; a methodology or config
    change simply finds no match and reports no ratio."""
    for leg in out.get("lm", ()):
        if leg.get("timing") != "device":
            continue  # wall fallback (or an untagged leg from an older
            #           build) must not ratio against device records
        key = (f"lm:{leg.get('seq_len')}x{leg.get('batch')}"
               f":d{leg.get('model_dim', 512)}h{leg.get('num_heads', 8)}")
        base = baseline.get("legs", {}).get(key, {})
        r = _leg_ratio(leg.get("tokens_per_sec"), base.get("tokens_per_sec"))
        if r is not None:
            leg["vs_baseline"] = r
    for leg in out.get("attn", ()):
        if leg.get("timing") != "device":
            continue  # wall fallback must not ratio against device records
        # ":device" in the key so a stale wall-era record (or a checkout
        # whose json predates the methodology switch) can never match
        key = f"attn:{leg.get('seq_len')}:device"
        base = baseline.get("legs", {}).get(key, {})
        # ms ratio inverted so > 1 still means "faster than baseline"
        r = _leg_ratio(base.get("flash_ms"), leg.get("flash_ms"))
        if r is not None:
            leg["vs_baseline"] = r
    for leg in out.get("ring", ()):
        if leg.get("timing") != "device":
            continue  # wall fallback must not ratio against device records
        key = (f"ring:{leg.get('l_local')}:b{leg.get('batch', 1)}"
               f"h{leg.get('heads', 8)}d{leg.get('head_dim', 64)}:device")
        base = baseline.get("legs", {}).get(key, {})
        r = _leg_ratio(base.get("flash_ms"), leg.get("flash_ms"))
        if r is not None:
            leg["vs_baseline"] = r
    moe = out.get("moe", {})
    # the bare top1/top2 keys carry the DEFAULT dispatch path (sorted as
    # of round 6; dense before) — so the first sorted capture ratios
    # against the round-5 dense record and SHOWS the dispatch-tax removal
    # as vs_baseline > 1, after which the record advances.  The *_dense
    # legs get their own keys so the A/B baseline persists independently
    for mode in ("top1", "top2", "top1_dense", "top2_dense"):
        sub = moe.get(mode)
        if isinstance(sub, dict) and sub.get("timing") == "device":
            key = (f"moe:{mode}:b{moe.get('batch')}s{moe.get('seq_len')}"
                   f"e{moe.get('experts')}:device")
            base = baseline.get("legs", {}).get(key, {})
            r = _leg_ratio(sub.get("tokens_per_sec"), base.get("tokens_per_sec"))
            if r is not None:
                sub["vs_baseline"] = r
    # async legs are wall-timed by nature (a host-driven loop IS the thing
    # measured), and wall on the relay swings ±30% — so their tripwire keys
    # on per-window DEVICE time, which is tenancy-stable; ms ratio inverted
    # so > 1 still means faster
    asy = out.get("async", {})
    for mode in ("async_adag", "async_aeasgd", "async_adag_native",
                 "async_adag_int8", "async_adag_inproc", "async_adag_serial"):
        sub = asy.get(mode)
        if isinstance(sub, dict):
            key = (f"async:{mode}:w{asy.get('workers')}x{asy.get('window')}"
                   f"b{asy.get('batch')}:device-window")
            base = baseline.get("legs", {}).get(key, {})
            r = _leg_ratio(base.get("per_window_device_ms"),
                           sub.get("per_window_device_ms"))
            if r is not None:
                sub["vs_baseline"] = r
    dec = out.get("decode", {})
    # modes that run the SECTION batch (their tokens/sec scales ~linearly
    # with it, and lockstep acceptance shrinks as agreement^batch) carry
    # the batch in their key; the *_b1 modes always run batch 1 and must
    # NOT be invalidated by a section-batch change
    batched_modes = {"fp", "int8", "fp_trained", "speculative_batched",
                     "speculative_k12"}
    # fp_b64 / kv_int8_b64 / speculative_*b64 run a FIXED batch 64 (the
    # mode name carries it), independent of the section batch
    for mode in ("fp", "int8", "fp_b1", "fp_b1_trained", "fp_trained",
                 "speculative_b1", "speculative_batched", "speculative_k12",
                 "fp_b64", "kv_int8_b64", "speculative_b64",
                 "speculative_kv_int8_b64", "fp_b64_gqa", "kv_int8_b64_gqa"):
        sub = dec.get(mode)
        # methodology-coded key: generation length and timing stat are part
        # of the identity, so the round-3 min-of-2-wall/256-token records
        # can never produce a ratio against a device-median/512-token run
        bpart = f":b{dec.get('batch')}" if mode in batched_modes else ""
        key = f"decode:{mode}{bpart}:n{dec.get('new_tokens')}:{dec.get('timing')}"
        base = baseline.get("legs", {}).get(key, {})
        if isinstance(sub, dict):
            r = _leg_ratio(sub.get("tokens_per_sec"), base.get("tokens_per_sec"))
            if r is not None:
                sub["vs_baseline"] = r


def main() -> None:
    out = {
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "value": 0.0,
        "unit": "samples/sec/chip",
        "vs_baseline": 0.0,
    }
    try:
        platform, init_error = _init_backend()
        out["platform"] = platform
        if init_error:
            out["init_error"] = init_error

        sps_per_chip, method = _bench_mnist_cnn(compute_dtype=_MNIST_DTYPE)
        out["value"] = round(sps_per_chip, 1)
        out["batch_size"] = _MNIST_BATCH
        out["compute_dtype"] = _MNIST_DTYPE
        out["methodology"] = method
        try:
            # A/B: the same headline model in plain float32 — the
            # pre-round-5 headline config — recorded next to the bf16
            # headline so the compute_dtype policy's win at this scale
            # stays a recorded number, not folklore (see _MNIST_DTYPE)
            f32_sps, f32_method = _bench_mnist_cnn()
            out["mnist_cnn_f32"] = {
                "samples_per_sec_per_chip": round(f32_sps, 1),
                "headline_vs_f32": round(sps_per_chip / f32_sps, 4),
                "methodology": f32_method,
            }
        except Exception as e:
            out["mnist_cnn_f32"] = {"error": f"{type(e).__name__}: {e}"}

        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
        baseline = {}
        if os.path.exists(baseline_path):
            with open(baseline_path) as f:
                baseline = json.load(f)
        vs = 1.0
        base = baseline.get("value")
        base_method = baseline.get("methodology")
        if base and baseline.get("platform", "tpu") != platform:
            # CPU-fallback throughput vs a TPU baseline is meaningless;
            # skip the ratio (keep 1.0) and flag why
            out["vs_baseline_note"] = (
                f"baseline recorded on {baseline.get('platform', 'tpu')}; "
                f"this run on {platform} — ratio not computed")
        elif base and base_method != method:
            # a ratio across bench-methodology changes measures the
            # measurement, not the chip (the round-2 dispatch-overhead
            # fix alone moved the same model 539k -> 934k; the v3 device
            # tag keeps a CPU wall fallback from ratioing against it)
            out["vs_baseline_note"] = (
                f"baseline methodology {base_method!r} != {method!r}"
                " — ratio not computed")
        elif base:
            vs = sps_per_chip / base
        out["vs_baseline"] = round(vs, 6)

        if platform == "tpu":
            import gc

            # secondary benches are TPU-only (flash is a Mosaic kernel) and
            # individually fallible — a failure is recorded, not fatal.
            # gc between legs drops dead device buffers promptly: HBM
            # pressure from earlier legs once blew the 32k LM leg up 25x
            gc.collect()
            lm, attn, ring = [], [], []
            for seq, batch, model_dim, num_layers, num_heads, steps in _LM_LEGS:
                try:
                    leg = _bench_lm(seq, batch, model_dim=model_dim,
                                    num_heads=num_heads, num_layers=num_layers,
                                    steps=steps)
                    leg["model_dim"] = model_dim
                    leg["num_heads"] = num_heads
                    lm.append(leg)
                except Exception as e:
                    lm.append({"seq_len": seq, "model_dim": model_dim,
                               "num_heads": num_heads,
                               "error": f"{type(e).__name__}: {e}"})
                gc.collect()
            for seq, steps in ((2048, 50), (8192, 25)):
                try:
                    attn.append(_bench_attn(seq, steps=steps))
                except Exception as e:
                    attn.append({"seq_len": seq, "error": f"{type(e).__name__}: {e}"})
                gc.collect()
            for l_local in (1024, 2048, 4096):
                try:
                    ring.append(_bench_ring(l_local))
                except Exception as e:
                    ring.append({"l_local": l_local,
                                 "error": f"{type(e).__name__}: {e}"})
                gc.collect()
            out["lm"] = lm
            out["attn"] = attn
            out["ring"] = ring
            try:
                out["decode"] = _bench_decode()
            except Exception as e:
                out["decode"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["feed"] = _bench_feed()
            except Exception as e:
                out["feed"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["moe"] = _bench_moe()
            except Exception as e:
                out["moe"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["pipeline"] = _bench_pipeline()
            except Exception as e:
                out["pipeline"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["async"] = _bench_async()
            except Exception as e:
                out["async"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["async_recovery"] = _bench_async_recovery()
            except Exception as e:
                out["async_recovery"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["observability"] = _bench_observability()
            except Exception as e:
                out["observability"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["health"] = _bench_health()
            except Exception as e:
                out["health"] = {"error": f"{type(e).__name__}: {e}"}
            gc.collect()
            try:
                out["embedding"] = _bench_embedding()
            except Exception as e:
                out["embedding"] = {"error": f"{type(e).__name__}: {e}"}
            _apply_leg_baselines(out, baseline)
    except Exception as e:
        out["value"] = 0.0  # contract: error lines carry the zero sentinel,
        out["vs_baseline"] = 0.0  # even if a sub-step already set a value
        out["error"] = f"{type(e).__name__}: {e}"
        out["traceback_tail"] = traceback.format_exc().strip().splitlines()[-3:]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
