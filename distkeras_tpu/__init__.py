"""distkeras_tpu — a TPU-native distributed deep-learning framework.

A ground-up re-design of the capabilities of ``weiboai/dist-keras`` (a
Spark + Keras parameter-server framework; see SURVEY.md) for TPU hardware:

- The socket-based parameter-server pull/commit loop (reference:
  ``distkeras/parameter_servers.py``, ``distkeras/networking.py``) becomes
  XLA collectives (``psum``/``all_gather``) over an ICI device mesh, driven
  by ``jax.shard_map``.
- Keras model definitions (reference: serialized via
  ``distkeras/utils.py :: serialize_keras_model``) become Flax modules with
  a registry-backed architecture+weights serialization of the same shape.
- The Spark RDD data plane (reference: ``rdd.mapPartitionsWithIndex``)
  becomes a host-sharded columnar ``Dataset`` feeding device-sharded
  batches.
- Spark-ML-style predictors/transformers/evaluators (reference:
  ``distkeras/predictors.py``, ``transformers.py``, ``evaluators.py``)
  become jit'd pure functions over the columnar ``Dataset``.

Public API mirrors the reference's trainer surface:
``SingleTrainer``, ``ADAG``, ``DOWNPOUR``, ``AEASGD``, ``EAMSGD``,
``DynSGD``, ``AveragingTrainer``, ``EnsembleTrainer``.
"""

__version__ = "0.1.0"

from distkeras_tpu.trainers import (  # noqa: F401
    Trainer,
    SingleTrainer,
    DistributedTrainer,
    ADAG,
    DOWNPOUR,
    AEASGD,
    EAMSGD,
    DynSGD,
    AveragingTrainer,
    EnsembleTrainer,
)
from distkeras_tpu.runtime.async_trainer import (  # noqa: F401
    AsyncADAG,
    AsyncAEASGD,
    AsyncDistributedTrainer,
    AsyncDOWNPOUR,
    AsyncDynSGD,
    AsyncEAMSGD,
)
from distkeras_tpu.checkpoint import Checkpointer  # noqa: F401
from distkeras_tpu.data.dataset import Dataset  # noqa: F401
from distkeras_tpu.models.base import Model, ModelSpec  # noqa: F401
from distkeras_tpu.predictors import ModelPredictor  # noqa: F401
from distkeras_tpu.evaluators import AccuracyEvaluator  # noqa: F401
