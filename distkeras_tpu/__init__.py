"""distkeras_tpu — a TPU-native distributed deep-learning framework.

A ground-up re-design of the capabilities of ``weiboai/dist-keras`` (a
Spark + Keras parameter-server framework; see SURVEY.md) for TPU hardware:

- The socket-based parameter-server pull/commit loop (reference:
  ``distkeras/parameter_servers.py``, ``distkeras/networking.py``) becomes
  XLA collectives (``psum``/``all_gather``) over an ICI device mesh, driven
  by ``jax.shard_map``.
- Keras model definitions (reference: serialized via
  ``distkeras/utils.py :: serialize_keras_model``) become Flax modules with
  a registry-backed architecture+weights serialization of the same shape.
- The Spark RDD data plane (reference: ``rdd.mapPartitionsWithIndex``)
  becomes a host-sharded columnar ``Dataset`` feeding device-sharded
  batches.
- Spark-ML-style predictors/transformers/evaluators (reference:
  ``distkeras/predictors.py``, ``transformers.py``, ``evaluators.py``)
  become jit'd pure functions over the columnar ``Dataset``.

Public API mirrors the reference's trainer surface:
``SingleTrainer``, ``ADAG``, ``DOWNPOUR``, ``AEASGD``, ``EAMSGD``,
``DynSGD``, ``AveragingTrainer``, ``EnsembleTrainer``.
"""

__version__ = "0.1.0"

# Lazy re-exports (PEP 562).  Keeps `import distkeras_tpu` (and importing
# leaf submodules like distkeras_tpu.platform) free of jax/flax/optax
# import-time work, so platform pinning can run before any backend touch.
_EXPORTS = {
    "Trainer": "distkeras_tpu.trainers",
    "SingleTrainer": "distkeras_tpu.trainers",
    "DistributedTrainer": "distkeras_tpu.trainers",
    "ADAG": "distkeras_tpu.trainers",
    "DOWNPOUR": "distkeras_tpu.trainers",
    "AEASGD": "distkeras_tpu.trainers",
    "EAMSGD": "distkeras_tpu.trainers",
    "DynSGD": "distkeras_tpu.trainers",
    "AveragingTrainer": "distkeras_tpu.trainers",
    "EnsembleTrainer": "distkeras_tpu.trainers",
    "AsyncDistributedTrainer": "distkeras_tpu.runtime.async_trainer",
    "AsyncADAG": "distkeras_tpu.runtime.async_trainer",
    "AsyncDOWNPOUR": "distkeras_tpu.runtime.async_trainer",
    "AsyncAEASGD": "distkeras_tpu.runtime.async_trainer",
    "AsyncEAMSGD": "distkeras_tpu.runtime.async_trainer",
    "AsyncDynSGD": "distkeras_tpu.runtime.async_trainer",
    "Punchcard": "distkeras_tpu.runtime.job_deployment",
    "Job": "distkeras_tpu.runtime.job_deployment",
    "StreamingInferenceServer": "distkeras_tpu.runtime.streaming",
    "StreamingClient": "distkeras_tpu.runtime.streaming",
    "initialize_multihost": "distkeras_tpu.runtime.launcher",
    "process_shard": "distkeras_tpu.runtime.launcher",
    "start_parameter_server": "distkeras_tpu.runtime.launcher",
    "Checkpointer": "distkeras_tpu.checkpoint",
    "Dataset": "distkeras_tpu.data.dataset",
    "Tokenizer": "distkeras_tpu.data.text",
    "pad_sequences": "distkeras_tpu.data.text",
    "ColumnFile": "distkeras_tpu.data.colfile",
    "write_columns": "distkeras_tpu.data.colfile",
    "Model": "distkeras_tpu.models.base",
    "ModelSpec": "distkeras_tpu.models.base",
    "generate": "distkeras_tpu.models.decode",
    "make_generate_fn": "distkeras_tpu.models.decode",
    "make_speculative_generate_fn": "distkeras_tpu.models.speculative",
    "beam_search": "distkeras_tpu.models.beam",
    "make_beam_search_fn": "distkeras_tpu.models.beam",
    "ModelPredictor": "distkeras_tpu.predictors",
    "AccuracyEvaluator": "distkeras_tpu.evaluators",
    "pin_cpu_devices": "distkeras_tpu.platform",
    "quantize_params": "distkeras_tpu.ops.quantize",
    "dequantize_params": "distkeras_tpu.ops.quantize",
    "get_optimizer": "distkeras_tpu.ops.optimizers",
    "get_schedule": "distkeras_tpu.ops.optimizers",
    "get_loss": "distkeras_tpu.ops.losses",
    "register_loss": "distkeras_tpu.ops.losses",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
