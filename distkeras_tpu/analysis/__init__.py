"""``distkeras_tpu.analysis`` — the project-aware static-analysis suite
behind ``distkeras-lint`` (ISSUE 12).

Four project-specific passes plus the consolidated F401 sweep:

- :mod:`~distkeras_tpu.analysis.lock_order` — lock-acquisition graph
  over ``runtime/`` + ``observability/`` checked against the declared
  :mod:`~distkeras_tpu.analysis.lock_manifest`;
- :mod:`~distkeras_tpu.analysis.blocking` — blocking calls
  (``send*``/``recv*``/``time.sleep``/``Thread.join``/``subprocess``/
  ``.result()``) lexically inside held-lock regions;
- :mod:`~distkeras_tpu.analysis.wire_parity` — ``ACTION_*`` registry vs
  the C++ hub's char-literal dispatch, plus NotImplementedError knob
  staleness;
- :mod:`~distkeras_tpu.analysis.telemetry` — every metric/span name
  literal checked against
  :mod:`~distkeras_tpu.analysis.telemetry_registry`;
- :mod:`~distkeras_tpu.analysis.unused_imports` — the one F401
  implementation the per-package test cells delegate to.

``tests/test_analysis.py`` runs the full suite over the repo as a tier-1
gate; the console script is ``distkeras-lint`` (see
:mod:`~distkeras_tpu.analysis.cli`).
"""

from distkeras_tpu.analysis.core import Finding  # noqa: F401  (re-export)
