"""``distkeras_tpu.analysis`` — the project-aware static-analysis suite
behind ``distkeras-lint`` (ISSUE 12 + the ISSUE 14 concurrency layer).

Seven project-specific passes plus the consolidated F401 sweep:

- :mod:`~distkeras_tpu.analysis.lock_order` — lock-acquisition graph
  over ``runtime/`` + ``observability/`` checked against the declared
  :mod:`~distkeras_tpu.analysis.lock_manifest`;
- :mod:`~distkeras_tpu.analysis.blocking` — blocking calls
  (``send*``/``recv*``/``time.sleep``/``Thread.join``/``subprocess``/
  ``.result()``) lexically inside held-lock regions;
- :mod:`~distkeras_tpu.analysis.guarded_by` — which lock protects which
  attribute: thread-root discovery, shared-state detection, and
  held-region checking against ``lock_manifest.GUARDED_BY``;
- :mod:`~distkeras_tpu.analysis.lockset` — Eraser-style DYNAMIC
  validation of the same table under a stress harness (opt-in,
  ``DKT_LOCKSET=1``);
- :mod:`~distkeras_tpu.analysis.protocol_model` — the declared
  client<->hub transition table cross-checked against the hub dispatch
  plus bounded exhaustive interleaving/standby model checking;
- :mod:`~distkeras_tpu.analysis.wire_parity` — ``ACTION_*`` registry vs
  the C++ hub's char-literal dispatch, plus NotImplementedError knob
  staleness;
- :mod:`~distkeras_tpu.analysis.telemetry` — every metric/span name
  literal checked against
  :mod:`~distkeras_tpu.analysis.telemetry_registry`;
- :mod:`~distkeras_tpu.analysis.unused_imports` — the one F401
  implementation the per-package test cells delegate to.

``tests/test_analysis.py`` runs the full suite over the repo as a tier-1
gate (plus slow-marked lockset-stress and TSAN cells); the console
script is ``distkeras-lint`` (see :mod:`~distkeras_tpu.analysis.cli`,
including ``--baseline`` for incremental adoption).
"""

from distkeras_tpu.analysis.core import Finding  # noqa: F401  (re-export)
