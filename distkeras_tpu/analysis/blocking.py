"""Blocking-call-under-lock detector (pass 2 of ``distkeras-lint``).

Flags calls that can park a thread — socket sends/receives (anything
``send*``/``recv*``, including the repo's framed-transport wrappers),
``time.sleep``, zero-arg ``.join()`` (``Thread.join``; one-arg joins are
``str.join``), ``subprocess.*``, ``.result()``, ``accept``/``connect`` —
lexically inside a held-lock region.  This is the PR-7 heartbeat bug
shape (the ping held the client io lock into a 60 s data-plane timeout),
caught at parse time instead of in a distributed-timeout postmortem.

Two suppression mechanisms, both with mandatory reasons:

- ``# lint: blocking-ok <reason>`` on the flagged line (point sites
  where the blocking call IS the design, e.g. the replication feed's
  send-before-ack contract);
- ``lock_manifest.IO_LOCKS`` for locks whose declared purpose is
  serializing blocking I/O (the PSClient io lock): a region is skipped
  only when EVERY held lock is so declared — holding a state lock
  alongside an io lock still flags.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from distkeras_tpu.analysis import lock_manifest
from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)
from distkeras_tpu.analysis.lock_order import (DEFAULT_SUBDIRS, LockIndex,
                                               _local_aliases, _own_exprs,
                                               _sub_bodies,
                                               _walk_outside_lambda)

_BLOCKING_ATTR_EXACT = {"sleep", "result", "accept", "connect",
                        "create_connection", "getaddrinfo"}
_BLOCKING_NAME_EXACT = {"sleep", "connect", "create_connection"}
_SUBPROCESS_BASES = {"subprocess"}


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call counts as blocking, or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        attr = f.attr
        base = f.value
        while isinstance(base, ast.Attribute):
            base = base.value
        if isinstance(base, ast.Name) and base.id in _SUBPROCESS_BASES:
            return f"subprocess.{attr}"
        if attr.startswith("send") or attr.startswith("recv"):
            return f".{attr}() does socket I/O"
        if attr in _BLOCKING_ATTR_EXACT:
            return f".{attr}() blocks"
        if attr == "join" and not call.args and not call.keywords:
            return ".join() on a thread blocks"
        if attr == "join" and call.keywords \
                and all(k.arg == "timeout" for k in call.keywords) \
                and not call.args:
            return ".join(timeout=...) on a thread blocks"
        return None
    if isinstance(f, ast.Name):
        name = f.id
        if name.startswith("send") or name.startswith("recv"):
            return f"{name}() does socket I/O"
        if name in _BLOCKING_NAME_EXACT:
            return f"{name}() blocks"
    return None


class _Scanner:
    def __init__(self, index: LockIndex, mod, cls, root: str,
                 io_locks: Dict[str, str]):
        self.index = index
        self.mod = mod
        self.cls = cls
        self.root = root
        self.io_locks = io_locks
        self.findings: List[Finding] = []

    def run(self, fn: ast.AST) -> None:
        self.aliases = _local_aliases(fn)
        self._walk(getattr(fn, "body", []), [])

    def _walk(self, body: Sequence[ast.stmt], held: List[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in stmt.items:
                    lk = self.index.resolve_lock(item.context_expr, self.mod,
                                                 self.cls, self.aliases)
                    if lk:
                        acquired.append(lk)
                    else:
                        # a non-lock context expression evaluated while
                        # earlier items/locks are held may itself block
                        # (``with lock: with sock.accept() as c:``)
                        self._flag_exprs([item.context_expr],
                                         held + acquired)
                self._walk(stmt.body, held + acquired)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, [])  # runs later, not under held
            else:
                # only this statement's OWN expressions: nested statement
                # bodies are walked separately (with any locks THEY add)
                self._flag_exprs(_own_exprs(stmt), held)
                for sub in _sub_bodies(stmt):
                    self._walk(sub, held)

    def _flag_exprs(self, exprs, held: List[str]) -> None:
        culprits = [h for h in held if h not in self.io_locks]
        if not culprits:
            return
        for node in (n for e in exprs for n in _walk_outside_lambda(e)):
            if not isinstance(node, ast.Call):
                continue
            why = _blocking_reason(node)
            if why is None:
                continue
            self.findings.append(Finding(
                "blocking", rel(self.mod.path, self.root), node.lineno,
                f"{why} while holding {', '.join(culprits)} — annotate "
                f"'# lint: blocking-ok <reason>' if the stall is bounded "
                f"by design",
                end_line=getattr(node, "end_lineno", 0) or 0))


def check(sources: Dict[str, SourceFile], root: str,
          io_locks: Optional[Dict[str, str]] = None) -> List[Finding]:
    io_locks = dict(lock_manifest.IO_LOCKS if io_locks is None else io_locks)
    findings: List[Finding] = []
    for node, reason in io_locks.items():
        if not str(reason).strip():
            findings.append(Finding(
                "blocking", "distkeras_tpu/analysis/lock_manifest.py", 1,
                f"IO_LOCKS entry {node} has no reason string"))
    index = LockIndex(sources)
    for mod in index.modules.values():
        scopes = [(None, fn) for fn in mod.functions.values()]
        for cls in mod.classes.values():
            scopes.extend((cls, fn) for fn in cls.methods.values())
        for cls, fn in scopes:
            s = _Scanner(index, mod, cls, root, io_locks)
            s.run(fn)
            findings.extend(s.findings)
    return apply_annotations(findings, sources, root, rule="blocking")


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    if sources is None:
        sources = load_sources(python_files(root, DEFAULT_SUBDIRS))
    return check(sources, root)
