"""``distkeras-lint`` — run the project-aware static-analysis suite.

Usage::

    distkeras-lint [--root DIR] [--json] [--pass NAME ...] [--dump-graph]

Exit code 0 when the tree is clean, 1 when any pass has findings (and 2
on usage errors).  ``--json`` emits a machine-readable report; the
default output groups findings by pass.  ``--dump-graph`` prints the
discovered lock-acquisition graph (the input to the lock-order check) —
the tool to run when extending ``lock_manifest.LOCK_ORDER``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional, Sequence

from distkeras_tpu.analysis import (blocking, lock_order, telemetry,
                                    unused_imports, wire_parity)
from distkeras_tpu.analysis.core import (RULES, Finding, load_sources,
                                         python_files, repo_root)

#: one pass per rule id — the vocabulary lives in core.RULES so the
#: annotation grammar and the CLI can never drift apart
PASSES = RULES


def run_all(root: Optional[str] = None,
            passes: Optional[Sequence[str]] = None
            ) -> Dict[str, List[Finding]]:
    """Run the requested passes (default: all), parsing each source file
    exactly once — the hub subset (lock passes) aliases into the full
    package set, so the gate's cost is one parse of the tree."""
    root = root or repo_root()
    names = list(passes) if passes else list(PASSES)
    pkg_sources = hub_sources = None
    if any(n in names for n in ("wire-parity", "telemetry", "lock-order",
                                "blocking")):
        pkg_sources = load_sources(python_files(root, ("distkeras_tpu",),
                                                extra=("bench.py",)))
        hub_paths = set(python_files(root, lock_order.DEFAULT_SUBDIRS))
        hub_sources = {p: s for p, s in pkg_sources.items()
                       if p in hub_paths}
    runners = {
        "lock-order": lambda: lock_order.run(root, hub_sources),
        "blocking": lambda: blocking.run(root, hub_sources),
        "wire-parity": lambda: wire_parity.run(root, pkg_sources),
        "telemetry": lambda: telemetry.run(root, pkg_sources),
        # package files reuse the shared parse; tests/ etc. parse here
        "unused-import": lambda: unused_imports.run(root, pkg_sources),
    }
    return {name: runners[name]() for name in names}


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="distkeras-lint",
        description="project-aware static analysis: lock order, blocking "
                    "calls under locks, Python<->C++ wire-action parity, "
                    "telemetry-name registry, unused imports")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the checkout this "
                             "package lives in)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings report")
    parser.add_argument("--pass", action="append", dest="passes",
                        choices=list(PASSES), default=None,
                        help="run only this pass (repeatable)")
    parser.add_argument("--dump-graph", action="store_true",
                        help="print the discovered lock-acquisition graph "
                             "and exit")
    args = parser.parse_args(argv)
    root = args.root or repo_root()

    if args.dump_graph:
        sources = load_sources(
            python_files(root, lock_order.DEFAULT_SUBDIRS))
        edges = lock_order.build_graph(sources, root)
        for (src, dst), locs in sorted(edges.items()):
            print(f"{src} -> {dst}")
            for path, line, via in locs[:4]:
                print(f"    {path}:{line} ({via})")
        return 0

    t0 = time.perf_counter()
    results = run_all(root, args.passes)
    elapsed = time.perf_counter() - t0
    total = sum(len(v) for v in results.values())

    if args.as_json:
        print(json.dumps({
            "root": root,
            "elapsed_s": round(elapsed, 3),
            "total": total,
            "findings": {name: [f.to_dict() for f in fs]
                         for name, fs in results.items()},
        }, indent=2))
        return 1 if total else 0

    for name in results:
        fs = results[name]
        status = "clean" if not fs else f"{len(fs)} finding(s)"
        print(f"[{name}] {status}")
        for f in fs:
            print(f"  {f}")
    print(f"distkeras-lint: {total} finding(s) across "
          f"{len(results)} pass(es) in {elapsed:.2f}s")
    return 1 if total else 0


if __name__ == "__main__":  # pragma: no cover - console entry
    sys.exit(main())
