"""``distkeras-lint`` — run the project-aware static-analysis suite.

Usage::

    distkeras-lint [--root DIR] [--json] [--pass NAME ...] [--dump-graph]
                   [--baseline FILE] [--write-baseline]

Exit code 0 when the tree is clean, 1 when any pass has findings (and 2
on usage errors).  ``--json`` emits a machine-readable report; the
default output groups findings by pass.  ``--dump-graph`` prints the
discovered lock-acquisition graph AND the guarded-by table (the inputs
to the lock-order and guarded-by checks) — the tool to run when
extending ``lock_manifest``.

``--baseline FILE`` compares findings against a recorded snapshot:
baselined findings are reported as suppressed (not failures), so a new
pass can land incrementally without a flag-day cleanup; entries the
tree no longer produces are listed as stale so the baseline shrinks to
nothing over time.  ``--write-baseline`` (with ``--baseline FILE``)
records the current findings as the new snapshot.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from distkeras_tpu.analysis import (blocking, guarded_by, lock_order,
                                    lockset, protocol_model, telemetry,
                                    unused_imports, wire_parity)
from distkeras_tpu.analysis.core import (RULES, Finding, load_sources,
                                         python_files, repo_root)

#: the ONE pass table: pass name -> the rule ids it emits.  Mostly pass
#: name == rule id; the guarded-by pass emits rule ``unguarded`` (the
#: annotation grammar), and ``lockset`` is inert unless ``DKT_LOCKSET=1``
#: (dynamic checking is opt-in — the static passes carry the always-on
#: gate).  ``PASSES`` and the baseline staleness logic both derive from
#: this table, and the assert below pins it to ``core.RULES`` so the
#: annotation vocabulary and the CLI can never drift apart (``lockset``
#: is DELIBERATELY absent from RULES — see core.py).
PASS_RULES: Dict[str, Tuple[str, ...]] = {
    "lock-order": ("lock-order",),
    "blocking": ("blocking",),
    "wire-parity": ("wire-parity",),
    "telemetry": ("telemetry",),
    "unused-import": ("unused-import",),
    "guarded-by": ("unguarded",),
    "lockset": ("lockset",),
    "protocol": ("protocol",),
}
PASSES = tuple(PASS_RULES)
assert {r for rs in PASS_RULES.values() for r in rs} - {"lockset"} \
    == set(RULES), "PASS_RULES and core.RULES drifted apart"


def run_all(root: Optional[str] = None,
            passes: Optional[Sequence[str]] = None
            ) -> Dict[str, List[Finding]]:
    """Run the requested passes (default: all), parsing each source file
    exactly once — the hub subset (lock/guarded-by/protocol passes)
    aliases into the full package set, so the gate's cost is one parse
    of the tree."""
    root = root or repo_root()
    names = list(passes) if passes else list(PASSES)
    pkg_sources = hub_sources = None
    if any(n in names for n in ("wire-parity", "telemetry", "lock-order",
                                "blocking", "guarded-by", "protocol")):
        pkg_sources = load_sources(python_files(root, ("distkeras_tpu",),
                                                extra=("bench.py",)))
        hub_paths = set(python_files(root, lock_order.DEFAULT_SUBDIRS))
        hub_sources = {p: s for p, s in pkg_sources.items()
                       if p in hub_paths}
    runners = {
        "lock-order": lambda: lock_order.run(root, hub_sources),
        "blocking": lambda: blocking.run(root, hub_sources),
        "wire-parity": lambda: wire_parity.run(root, pkg_sources),
        "telemetry": lambda: telemetry.run(root, pkg_sources),
        # package files reuse the shared parse; tests/ etc. parse here
        "unused-import": lambda: unused_imports.run(root, pkg_sources),
        "guarded-by": lambda: guarded_by.run(root, hub_sources),
        "lockset": lambda: lockset.run(root),
        "protocol": lambda: protocol_model.run(root, hub_sources),
    }
    return {name: runners[name]() for name in names}


# -- baseline snapshots --------------------------------------------------------

def _finding_key(f: Finding) -> Tuple[str, str, str]:
    """Baseline identity: rule + path + message (no line numbers — they
    shift under unrelated edits; the message pins the construct)."""
    return (f.rule, f.path, f.message)


def write_baseline(path: str, results: Dict[str, List[Finding]],
                   preserved: Sequence[Tuple[str, str, str]] = ()) -> int:
    """Record the run's findings (duplicates kept — suppression is
    multiplicity-aware) plus ``preserved`` entries carried over from
    passes this run did not execute."""
    keys = [_finding_key(f) for fs in results.values() for f in fs]
    keys.extend(tuple(e) for e in preserved)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1,
                   "findings": [{"rule": r, "path": p, "message": m}
                                for r, p, m in sorted(keys)]},
                  fh, indent=2)
        fh.write("\n")
    return len(keys) - len(preserved)


def load_baseline(path: str) -> List[Tuple[str, str, str]]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return [(e["rule"], e["path"], e["message"])
            for e in data.get("findings", [])]


def apply_baseline(results: Dict[str, List[Finding]],
                   baseline: Sequence[Tuple[str, str, str]]
                   ) -> Tuple[Dict[str, List[Finding]], int,
                              List[Tuple[str, str, str]]]:
    """Split results into (new findings, suppressed count, stale
    baseline entries).  Suppression is MULTIPLICITY-aware: a baseline
    recorded with N identical (rule, path, message) entries suppresses
    at most N live findings — an (N+1)th occurrence (a brand-new
    violation whose message happens to match, e.g. a second unguarded
    write of the same attribute) still fails.  Entries are only
    reported stale when the pass that emits their rule actually ran
    this invocation — ``--pass`` subsets must not advise deleting live
    suppressions."""
    from collections import Counter

    allowed = Counter(baseline)
    out: Dict[str, List[Finding]] = {}
    suppressed = 0
    for name, fs in results.items():
        kept = []
        for f in fs:
            k = _finding_key(f)
            if allowed.get(k, 0) > 0:
                allowed[k] -= 1
                suppressed += 1
            else:
                kept.append(f)
        out[name] = kept
    ran_rules = {r for name in results for r in PASS_RULES.get(name, ())
                 # the lockset pass is INERT without DKT_LOCKSET=1 — it
                 # "ran" but checked nothing, so its baseline entries
                 # must not read as stale on a plain invocation
                 if name != "lockset" or lockset.enabled()}
    stale = sorted(k for k, n in allowed.items()
                   if n > 0 and k[0] in ran_rules)
    return out, suppressed, stale


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="distkeras-lint",
        description="project-aware static analysis: lock order, blocking "
                    "calls under locks, guarded-by manifest, "
                    "Python<->C++ wire-action parity, protocol model "
                    "check, telemetry-name registry, unused imports "
                    "(+ the DKT_LOCKSET=1 dynamic lockset stress)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the checkout this "
                             "package lives in)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings report")
    parser.add_argument("--pass", action="append", dest="passes",
                        choices=list(PASSES), default=None,
                        help="run only this pass (repeatable)")
    parser.add_argument("--dump-graph", action="store_true",
                        help="print the discovered lock-acquisition graph "
                             "and the guarded-by table, then exit")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="suppress findings recorded in FILE (land "
                             "new passes incrementally); stale entries "
                             "are reported so the baseline burns down")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record the current findings into --baseline "
                             "FILE and exit 0")
    args = parser.parse_args(argv)
    root = args.root or repo_root()
    if args.write_baseline and not args.baseline:
        parser.error("--write-baseline requires --baseline FILE")

    if args.dump_graph:
        sources = load_sources(
            python_files(root, lock_order.DEFAULT_SUBDIRS))
        edges = lock_order.build_graph(sources, root)
        for (src, dst), locs in sorted(edges.items()):
            print(f"{src} -> {dst}")
            for path, line, via in locs[:4]:
                print(f"    {path}:{line} ({via})")
        print()
        print("guarded-by table (shared attributes and their guards):")
        for line in guarded_by.dump_table(sources, root):
            print(line)
        return 0

    t0 = time.perf_counter()
    results = run_all(root, args.passes)
    elapsed = time.perf_counter() - t0

    if args.baseline and args.write_baseline:
        preserved: List[Tuple[str, str, str]] = []
        if os.path.exists(args.baseline):
            # a --pass subset refresh must not delete the OTHER passes'
            # suppressions: keep every entry whose rule this run did not
            # re-check (same ran-rules gate apply_baseline uses,
            # including the inert-lockset case)
            ran = {r for name in results for r in PASS_RULES.get(name, ())
                   if name != "lockset" or lockset.enabled()}
            try:
                preserved = [e for e in load_baseline(args.baseline)
                             if e[0] not in ran]
            except (OSError, ValueError, KeyError, TypeError) as e:
                parser.error(f"cannot read existing baseline "
                             f"{args.baseline}: {e}")
        n = write_baseline(args.baseline, results, preserved=preserved)
        print(f"distkeras-lint: wrote {n} finding(s) to baseline "
              f"{args.baseline}"
              + (f" (+{len(preserved)} preserved from passes not run)"
                 if preserved else ""))
        return 0
    suppressed, stale = 0, []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            # a missing/torn snapshot is a usage error (exit 2), not a
            # findings failure CI would misread as lint regressions
            parser.error(f"cannot read baseline {args.baseline}: {e}")
        results, suppressed, stale = apply_baseline(results, baseline)
    total = sum(len(v) for v in results.values())

    if args.as_json:
        print(json.dumps({
            "root": root,
            "elapsed_s": round(elapsed, 3),
            "total": total,
            "suppressed_by_baseline": suppressed,
            "stale_baseline_entries": [list(s) for s in stale],
            "findings": {name: [f.to_dict() for f in fs]
                         for name, fs in results.items()},
        }, indent=2))
        return 1 if total else 0

    for name in results:
        fs = results[name]
        status = "clean" if not fs else f"{len(fs)} finding(s)"
        print(f"[{name}] {status}")
        for f in fs:
            print(f"  {f}")
    if suppressed:
        print(f"baseline: {suppressed} finding(s) suppressed by "
              f"{args.baseline}")
    for rule, path, msg in stale:
        print(f"baseline: STALE entry (no longer produced): "
              f"[{rule}] {path}: {msg}")
    print(f"distkeras-lint: {total} finding(s) across "
          f"{len(results)} pass(es) in {elapsed:.2f}s")
    return 1 if total else 0


if __name__ == "__main__":  # pragma: no cover - console entry
    sys.exit(main())
