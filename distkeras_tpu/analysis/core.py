"""Shared infrastructure for the ``distkeras-lint`` passes.

Every pass produces :class:`Finding` records over repo files and honors
the one suppression grammar::

    # lint: <rule>-ok <reason>

placed on the flagged line.  The reason is MANDATORY — an annotation
without one is itself a finding, so the tree can never accumulate
unexplained suppressions (the "no blanket suppressions" contract of
ISSUE 12).  Structural exceptions that are not tied to one source line
(lock-order edges, whole locks whose purpose is I/O serialization) live
in :mod:`distkeras_tpu.analysis.lock_manifest` instead, each with a
named reason string.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: rule ids (the annotation grammar's ``<rule>`` vocabulary).  Mostly one
#: per pass; the guarded-by pass owns rule ``unguarded`` (the annotation
#: reads ``# lint: unguarded-ok <reason>``) and ``protocol`` belongs to
#: the model checker.  The DYNAMIC lockset pass is deliberately absent:
#: its findings are runtime observations with no stable source anchor to
#: annotate — fix the race or declare the attribute in GUARDED_BY — so a
#: ``# lint: lockset-ok`` comment would be inert, and the hygiene sweep
#: flags it as an unknown rule instead of letting it accumulate.
RULES = ("lock-order", "blocking", "wire-parity", "telemetry",
         "unused-import", "unguarded", "protocol")

#: anchored to the START of a comment token, so prose that merely
#: mentions the grammar ("suppress with '# lint: ...'") never registers
#: as a live suppression
ANNOTATION_RE = re.compile(r"^#[ \t]*lint:\s*([a-z][a-z-]*)-ok\b[ \t]*(.*?)\s*$")


@dataclass(frozen=True)
class Finding:
    """One static-analysis violation, pinned to a file and line.
    ``end_line`` (when > line) is the flagged construct's last line —
    an annotation anywhere in [line, end_line] suppresses, so the
    natural end-of-statement placement works on multi-line calls."""

    rule: str
    path: str  # repo-relative
    line: int
    message: str
    end_line: int = 0

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


def repo_root() -> str:
    """The checkout root this package lives in (two levels above
    ``distkeras_tpu/analysis/``)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:  # pragma: no cover - windows drive mismatch
        return path


class SourceFile:
    """One parsed Python source: text, lines, AST, and its ``# lint:``
    annotations keyed by line number."""

    def __init__(self, path: str, text: Optional[str] = None):
        self.path = path
        if text is None:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        #: line -> (rule, reason); reason may be "" (which is a finding).
        #: Parsed from REAL comment tokens — a docstring that merely
        #: mentions the grammar must not register as a suppression.
        self.annotations: Dict[int, Tuple[str, str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    m = ANNOTATION_RE.match(tok.string)
                    if m:
                        self.annotations[tok.start[0]] = (m.group(1),
                                                          m.group(2))
        except tokenize.TokenError:  # pragma: no cover - ast.parse gates
            pass


def apply_annotations(findings: Sequence[Finding], sources: Dict[str, SourceFile],
                      root: str, rule: Optional[str] = None) -> List[Finding]:
    """Filter ``findings`` through the per-line annotation grammar.

    A finding on an annotated line whose rule matches is suppressed IFF
    the annotation carries a non-empty reason; an empty reason is a
    finding of its own.  With ``rule`` given (the calling pass's id),
    the sweep is finding-independent: EVERY annotation of that rule in
    ``sources`` is examined — a reasonless one is always reported, and
    one that no longer suppresses anything is reported as stale (the
    ruff unused-``noqa`` discipline), so suppressions can never silently
    accumulate after the code they excused is refactored away.
    """
    out: List[Finding] = []
    by_path = {rel(p, root): s for p, s in sources.items()}
    suppressed_at = set()
    for f in findings:
        src = by_path.get(f.path)
        ann_line = None
        if src is not None:
            last = max(f.line, f.end_line)
            for ln in range(f.line, last + 1):
                ann = src.annotations.get(ln)
                if ann is not None and ann[0] == f.rule:
                    ann_line = ln
                    break
        if ann_line is not None:
            suppressed_at.add((f.path, ann_line))
            continue  # reasonless annotations are reported in the sweep
        out.append(f)
    if rule is not None:
        for path, src in sorted(by_path.items()):
            for line, (arule, reason) in sorted(src.annotations.items()):
                if arule != rule:
                    continue
                if not reason:
                    out.append(Finding(rule, path, line,
                                       "suppression annotation requires a "
                                       "reason: '# lint: %s-ok <reason>'"
                                       % rule))
                elif (path, line) not in suppressed_at:
                    out.append(Finding(rule, path, line,
                                       f"stale suppression: this line no "
                                       f"longer triggers a {rule} finding — "
                                       f"drop the '# lint: {rule}-ok' "
                                       f"annotation"))
    return out


def python_files(root: str, subdirs: Sequence[str] = ("distkeras_tpu",),
                 extra: Sequence[str] = ()) -> List[str]:
    """All ``.py`` files under ``root``'s ``subdirs`` (recursive, sorted,
    ``__pycache__`` skipped) plus any ``extra`` root-relative files that
    exist."""
    out: List[str] = []
    for sub in subdirs:
        top = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                       if f.endswith(".py"))
    for name in extra:
        p = os.path.join(root, name)
        if os.path.exists(p):
            out.append(p)
    return out


def load_sources(paths: Sequence[str]) -> Dict[str, SourceFile]:
    return {p: SourceFile(p) for p in paths}
