"""Guarded-by analyzer (pass 5 of ``distkeras-lint``) — ISSUE 14 tentpole.

PR 12 checks lock *ordering*; this pass checks *which state each lock
actually protects*.  Over the hub stack (``runtime/`` +
``observability/``) it:

1. discovers every **thread root** — methods handed to
   ``threading.Thread(target=...)``, callbacks registered through
   ``*.subscribe(...)``, and the nested functions those forms spawn —
   and whether each root runs as ONE thread (a daemon loop) or MANY
   (handler threads created in an accept loop, one worker thread per
   index);
2. builds a resolved call graph (the ``lock_order`` resolution rules:
   ``self.meth``, typed attribute chains, local aliases, bare in-module
   functions) and propagates **execution contexts** — which roots can be
   on the stack when each method runs (public methods and methods with
   no in-tree callers additionally run on the caller's thread,
   context ``main``);
3. collects every ``self._attr`` **write site** (plain/aug/ann
   assignments and element stores like ``self.center[i][ids] += g``)
   outside ``__init__``.  An attribute written from more than one
   context — or from any *multi* root, where N copies of the same loop
   race each other — is **shared state** and must be declared in
   :data:`~distkeras_tpu.analysis.lock_manifest.GUARDED_BY`;
4. checks every write to a declared attribute happens while its
   declared guard is held — lexically (``with self._lock:``) or at
   method entry, inferred as the intersection of the held sets at every
   resolved call site (the ``*_locked`` helper convention, checked
   instead of trusted).

Findings carry rule id ``unguarded``; point suppressions use
``# lint: unguarded-ok <reason>`` with PR 12's self-cleaning grammar
(reasonless/stale annotations are findings).  The manifest itself is
self-cleaning too: a ``GUARDED_BY`` entry whose attribute is no longer
shared, whose lock node no longer exists, or whose by-design ``None``
guard lacks a reason is a finding.

Known, documented limits: container mutations through bound methods
(``self._conns.append(c)``) are not write sites (the lock-order pass's
one-level call resolution does not model ``list.append``); reads are
not tracked (the dynamic lockset checker covers read-vs-write races at
runtime); attributes only ever written before threads start are
single-context by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from distkeras_tpu.analysis import lock_manifest
from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)
from distkeras_tpu.analysis.lock_order import (DEFAULT_SUBDIRS, ClassInfo,
                                               LockIndex, ModuleIndex,
                                               _attr_chain, _find_method,
                                               _local_aliases)

RULE = "unguarded"

#: context tag for code running on the caller's (API/user) thread
MAIN = "main"

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


class Scope:
    """One analyzed function body: a method, module function, or nested
    ``def`` (which may be a thread target)."""

    def __init__(self, name: str, mod: ModuleIndex, cls: Optional[ClassInfo],
                 fn: ast.AST, aliases: Dict[str, Tuple[str, ...]]):
        self.name = name
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.aliases = aliases
        self.is_init = name.endswith(".__init__")
        #: (callee scope name, frozenset held at the call site, line)
        self.calls: List[Tuple[str, frozenset, int]] = []
        #: (attr, line, end_line, frozenset held lexically, element_store)
        self.writes: List[Tuple[str, int, int, frozenset, bool]] = []
        #: thread-root registrations found in this scope's body:
        #: (target scope name, multi) — multi when the registration sits
        #: inside a loop/comprehension (N concurrent copies of the root)
        self.spawns: List[Tuple[str, bool]] = []


class GuardedByIndex:
    """The whole-tree index: scopes, call graph, roots, write sites."""

    def __init__(self, sources: Dict[str, SourceFile], root: str):
        self.root = root
        self.index = LockIndex(sources)
        self.scopes: Dict[str, Scope] = {}
        #: root scope name -> multi flag (True once ANY registration is
        #: multi — a root spawned once per connection races itself)
        self.roots: Dict[str, bool] = {}
        for mod in self.index.modules.values():
            for fname, fn in mod.functions.items():
                self._add_scope(f"{mod.stem}.{fname}", mod, None, fn, {})
            for cls in mod.classes.values():
                for mname, fn in cls.methods.items():
                    self._add_scope(f"{cls.name}.{mname}", mod, cls, fn, {})
        for scope in list(self.scopes.values()):
            self._walk_scope(scope)
        self._resolve_spawns()

    # -- construction ----------------------------------------------------------

    def _add_scope(self, name: str, mod: ModuleIndex, cls: Optional[ClassInfo],
                   fn: ast.AST, outer_aliases: Dict[str, Tuple[str, ...]]):
        aliases = dict(outer_aliases)
        aliases.update(_local_aliases(fn))
        self.scopes[name] = Scope(name, mod, cls, fn, aliases)

    def _walk_scope(self, scope: Scope) -> None:
        walker = _ScopeWalker(self, scope)
        walker.walk(getattr(scope.fn, "body", []), frozenset(), in_loop=False)

    def _resolve_spawns(self) -> None:
        for scope in self.scopes.values():
            for target, multi in scope.spawns:
                if target in self.scopes:
                    self.roots[target] = self.roots.get(target, False) or multi

    # -- resolution helpers ----------------------------------------------------

    def resolve_callee(self, call: ast.Call, scope: Scope) -> Optional[str]:
        """Resolve a call expression to a scope name (lock_order rules)."""
        f = call.func
        if isinstance(f, ast.Name):
            nested = f"{scope.name}.{f.id}"
            if nested in self.scopes:
                return nested
            if f.id in scope.mod.functions:
                return f"{scope.mod.stem}.{f.id}"
            return None
        chain = _attr_chain(f)
        if chain is None:
            return None
        if chain[0] in scope.aliases:
            chain = scope.aliases[chain[0]] + chain[1:]
        if chain[0] != "self" or scope.cls is None or len(chain) < 2:
            return None
        owner: Optional[ClassInfo] = scope.cls
        for attr in chain[1:-1]:
            owner = self.index._attr_type(owner, attr)
            if owner is None:
                return None
        found = _find_method(self.index, owner, chain[-1])
        if found is None:
            return None
        _fn, defining = found
        return f"{defining.name}.{chain[-1]}"

    def resolve_target_ref(self, expr: ast.AST,
                           scope: Scope) -> Optional[str]:
        """Resolve a function REFERENCE (``target=self._loop``,
        ``subscribe(self._on_event)``, a bare nested-def name) to a scope
        name."""
        if isinstance(expr, ast.Name):
            nested = f"{scope.name}.{expr.id}"
            if nested in self.scopes:
                return nested
            if expr.id in scope.mod.functions:
                return f"{scope.mod.stem}.{expr.id}"
            return None
        chain = _attr_chain(expr)
        if chain is None or len(chain) != 2 or chain[0] != "self" \
                or scope.cls is None:
            return None
        found = _find_method(self.index, scope.cls, chain[1])
        if found is None:
            return None
        _fn, defining = found
        return f"{defining.name}.{chain[1]}"

    def defining_attr_class(self, cls: ClassInfo, attr: str) -> str:
        """The class (walking known bases) whose ``__init__`` first
        assigns ``attr`` — so subclass writes unify under one node name
        (the LOCK_ORDER naming convention).  Falls back to the writing
        class."""
        seen: Set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            init = c.methods.get("__init__")
            if init is not None and attr in _attrs_assigned(init):
                return c.name
            stack.extend(self.index.class_by_name[b] for b in c.bases
                         if b in self.index.class_by_name)
        return cls.name

    # -- analyses --------------------------------------------------------------

    def contexts(self) -> Dict[str, Set[str]]:
        """Which execution contexts (thread roots + ``main``) can be on
        the stack when each scope runs — seeded at roots, public methods
        and no-caller scopes, propagated along the call graph."""
        callers: Dict[str, List[str]] = {}
        for scope in self.scopes.values():
            for callee, _held, _line in scope.calls:
                callers.setdefault(callee, []).append(scope.name)
        ctx: Dict[str, Set[str]] = {name: set() for name in self.scopes}
        for name in self.scopes:
            short = name.rsplit(".", 1)[-1]
            if name in self.roots:
                ctx[name].add(name)
            is_public = not short.startswith("_") or short.startswith("__")
            # public methods run on the caller's thread; private scopes
            # with no resolved in-tree caller are assumed externally
            # callable too — UNLESS they are thread roots (a private
            # daemon loop's only caller is the thread that runs it)
            if (is_public or (name not in callers
                              and name not in self.roots)) \
                    and not self._is_nested(name):
                ctx[name].add(MAIN)
        changed = True
        while changed:
            changed = False
            for scope in self.scopes.values():
                for callee, _held, _line in scope.calls:
                    if callee in ctx and not ctx[scope.name] <= ctx[callee]:
                        ctx[callee] |= ctx[scope.name]
                        changed = True
        for name, c in ctx.items():
            if not c:
                c.add(MAIN)
        return ctx

    def entry_held(self) -> Dict[str, frozenset]:
        """Locks provably held at every resolved call site of each scope
        (the checked form of the ``*_locked`` convention).  Thread roots,
        no-caller scopes and public methods hold nothing at entry."""
        callers: Dict[str, List[Tuple[str, frozenset]]] = {}
        for scope in self.scopes.values():
            for callee, held, _line in scope.calls:
                callers.setdefault(callee, []).append((scope.name, held))
        held_at: Dict[str, Optional[frozenset]] = {}
        for name in self.scopes:
            short = name.rsplit(".", 1)[-1]
            is_public = not short.startswith("_") or short.startswith("__")
            if name in self.roots or name not in callers \
                    or (is_public and not self._is_nested(name)):
                held_at[name] = frozenset()
            else:
                held_at[name] = None  # ⊤ until a caller resolves
        changed = True
        while changed:
            changed = False
            for name, sites in callers.items():
                if held_at.get(name) == frozenset():
                    continue  # seeded — external callers hold nothing
                cands = [h | held_at[c] for c, h in sites
                         if held_at.get(c) is not None]
                if not cands:
                    continue
                new = frozenset.intersection(*cands)
                if held_at[name] is None or new < held_at[name]:
                    held_at[name] = new
                    changed = True
        return {n: (h if h is not None else frozenset())
                for n, h in held_at.items()}

    def _is_nested(self, name: str) -> bool:
        return name.count(".") >= 2

    def shared_attrs(self, ctx: Dict[str, Set[str]]
                     ) -> Dict[str, Dict[str, object]]:
        """``Class._attr`` -> {contexts, multi, writes} for every
        attribute written outside ``__init__`` from more than one
        context, or from any multi root."""
        per_attr: Dict[str, Dict[str, object]] = {}
        for scope in self.scopes.values():
            if scope.cls is None or scope.is_init:
                continue
            for attr, line, end, held, elem in scope.writes:
                key = f"{self.defining_attr_class(scope.cls, attr)}.{attr}"
                rec = per_attr.setdefault(
                    key, {"contexts": set(), "multi": False, "writes": []})
                rec["contexts"] |= ctx.get(scope.name, {MAIN})
                rec["multi"] = rec["multi"] or any(
                    self.roots.get(r, False) for r in ctx.get(scope.name, ()))
                rec["writes"].append((scope, attr, line, end, held, elem))
        return {k: v for k, v in per_attr.items()
                if len(v["contexts"]) > 1 or v["multi"]}


def _attrs_assigned(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.add(t.attr)
    return out


def _write_target_attr(target: ast.AST,
                       aliases: Dict[str, Tuple[str, ...]]
                       ) -> Optional[Tuple[str, bool]]:
    """``(attr, element_store)`` when ``target`` writes through
    ``self.attr`` — plain (``self.x = v``), tuple-unpack members, or an
    element store (``self.x[i] = v``, ``self.x[i][ids] += v``)."""
    elem = False
    node = target
    while isinstance(node, ast.Subscript):
        elem = True
        node = node.value
    # element stores may go through a deeper chain (self.center[i][...])
    chain = _attr_chain(node)
    if chain is None:
        return None
    if chain[0] in aliases and (elem or len(chain) > 1):
        # alias substitution applies when writing THROUGH the aliased
        # object (``center[i] = v`` with ``center = self.center``) — a
        # plain store to the bare local name only rebinds the local
        chain = aliases[chain[0]] + chain[1:]
    if chain[0] != "self" or len(chain) < 2:
        return None
    if len(chain) > 2 and not elem:
        return None  # self.a.b = v mutates the OTHER object; out of scope
    return chain[1], elem or len(chain) > 2


class _ScopeWalker:
    """Held-set-tracking walk of one scope body, recording calls, write
    sites and thread-root registrations; nested ``def``s become child
    scopes (their bodies run on some other stack)."""

    def __init__(self, gb: GuardedByIndex, scope: Scope):
        self.gb = gb
        self.scope = scope

    def walk(self, body: Sequence[ast.stmt], held: frozenset,
             in_loop: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child = f"{self.scope.name}.{stmt.name}"
                self.gb._add_scope(child, self.scope.mod, self.scope.cls,
                                   stmt, self.scope.aliases)
                self.gb._walk_scope(self.gb.scopes[child])
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: Set[str] = set()
                for item in stmt.items:
                    lk = self.gb.index.resolve_lock(
                        item.context_expr, self.scope.mod, self.scope.cls,
                        self.scope.aliases)
                    if lk:
                        acquired.add(lk)
                    else:
                        self._scan_exprs([item.context_expr],
                                         held | frozenset(acquired), in_loop,
                                         stmt.lineno)
                self.walk(stmt.body, held | frozenset(acquired), in_loop)
                continue
            now_loop = in_loop or isinstance(stmt, _LOOP_NODES)
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (list(stmt.targets) if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                flat: List[ast.AST] = []
                for t in targets:
                    flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                                else [t])
                for t in flat:
                    hit = _write_target_attr(t, self.scope.aliases)
                    if hit is not None:
                        self.scope.writes.append(
                            (hit[0], stmt.lineno,
                             getattr(stmt, "end_lineno", 0) or 0, held,
                             hit[1]))
            self._scan_exprs(_stmt_exprs(stmt), held, now_loop, stmt.lineno)
            for sub in _stmt_bodies(stmt):
                self.walk(sub, held, now_loop)

    def _scan_exprs(self, exprs, held: frozenset, in_loop: bool,
                    line: int) -> None:
        for e in exprs:
            for node in _walk_exprs(e):
                in_loop_here = in_loop or node[1]
                call = node[0]
                if not isinstance(call, ast.Call):
                    continue
                self._maybe_spawn(call, in_loop_here)
                callee = self.gb.resolve_callee(call, self.scope)
                if callee is not None:
                    self.scope.calls.append((callee, held, call.lineno))

    def _maybe_spawn(self, call: ast.Call, in_loop: bool) -> None:
        f = call.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if fname == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    tgt = self.gb.resolve_target_ref(kw.value, self.scope)
                    if tgt is not None:
                        self.scope.spawns.append((tgt, in_loop))
        elif fname == "subscribe" and call.args:
            tgt = self.gb.resolve_target_ref(call.args[0], self.scope)
            if tgt is not None:
                # subscription callbacks fire from whatever thread emits
                # the event — handler/ingest threads, concurrently
                self.scope.spawns.append((tgt, True))


def _stmt_exprs(stmt: ast.stmt):
    for _field, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _stmt_bodies(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val and isinstance(val[0], ast.stmt):
            yield val
    for h in getattr(stmt, "handlers", []):
        yield h.body
    for c in getattr(stmt, "cases", []):
        yield c.body


def _walk_exprs(expr: ast.AST):
    """Yield ``(node, in_comprehension)`` pairs, skipping lambda bodies
    (deferred) but descending into comprehensions (which DO run here, in
    a loop)."""
    stack: List[Tuple[ast.AST, bool]] = [(expr, False)]
    while stack:
        node, comp = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node, comp
        child_comp = comp or isinstance(node, _LOOP_NODES)
        stack.extend((c, child_comp) for c in ast.iter_child_nodes(node))


# -- the pass ------------------------------------------------------------------

def known_lock_nodes(gb: GuardedByIndex) -> Set[str]:
    out: Set[str] = set(lock_manifest.LOCK_ORDER)
    for mod in gb.index.modules.values():
        out.update(f"{mod.stem}.{n}" for n in mod.module_locks)
        for cls in mod.classes.values():
            out.update(f"{cls.name}.{a}" for a in cls.lock_attrs)
    return out


def check(sources: Dict[str, SourceFile], root: str,
          guarded_by: Optional[Dict[str, Tuple[Optional[str], str]]] = None
          ) -> List[Finding]:
    """Run the guarded-by pass; ``guarded_by`` defaults to the repo
    manifest (overridable for fixture tests)."""
    table = dict(lock_manifest.GUARDED_BY
                 if guarded_by is None else guarded_by)
    gb = GuardedByIndex(sources, root)
    ctx = gb.contexts()
    entry = gb.entry_held()
    shared = gb.shared_attrs(ctx)
    locks = known_lock_nodes(gb)
    findings: List[Finding] = []
    manifest_path = "distkeras_tpu/analysis/lock_manifest.py"

    for key, (lock, reason) in sorted(table.items()):
        if lock is None and not str(reason).strip():
            findings.append(Finding(
                RULE, manifest_path, 1,
                f"GUARDED_BY entry {key} declares no guard (None) and no "
                f"reason — by-design unguarded state needs a reason string"))
        if lock is not None and lock not in locks:
            findings.append(Finding(
                RULE, manifest_path, 1,
                f"GUARDED_BY entry {key} names guard '{lock}' which is not "
                f"a known lock node (not discovered, not in LOCK_ORDER)"))
        if key not in shared:
            findings.append(Finding(
                RULE, manifest_path, 1,
                f"stale GUARDED_BY entry: {key} is no longer written from "
                f"multiple thread roots — drop the entry (it would "
                f"pre-suppress a future genuine finding)"))

    for key in sorted(shared):
        rec = shared[key]
        entry_for = table.get(key)
        roots = sorted(rec["contexts"])
        if entry_for is None:
            for scope, attr, line, end, held, _elem in \
                    sorted(rec["writes"], key=lambda w: (w[0].mod.path, w[2])):
                findings.append(Finding(
                    RULE, rel(scope.mod.path, root), line,
                    f"{key} is written from multiple thread roots "
                    f"({', '.join(roots)}) but has no GUARDED_BY entry — "
                    f"declare its guard in lock_manifest.GUARDED_BY or "
                    f"annotate '# lint: unguarded-ok <reason>'",
                    end_line=end))
            continue
        lock, _reason = entry_for
        if lock is None:
            continue  # by-design unguarded, reason checked above
        for scope, attr, line, end, held, _elem in \
                sorted(rec["writes"], key=lambda w: (w[0].mod.path, w[2])):
            effective = held | entry.get(scope.name, frozenset())
            if lock not in effective:
                findings.append(Finding(
                    RULE, rel(scope.mod.path, root), line,
                    f"{key} is declared guarded by {lock} but this write "
                    f"is outside its held region (held here: "
                    f"{sorted(effective) or 'nothing'}) — take the lock or "
                    f"annotate '# lint: unguarded-ok <reason>'",
                    end_line=end))
    return apply_annotations(findings, sources, root, rule=RULE)


def dump_table(sources: Dict[str, SourceFile], root: str) -> List[str]:
    """Human-readable guarded-by discovery (``--dump-graph`` extension):
    every shared attribute, its contexts, and its declared guard."""
    gb = GuardedByIndex(sources, root)
    shared = gb.shared_attrs(gb.contexts())
    out: List[str] = []
    for key in sorted(shared):
        rec = shared[key]
        lock, reason = lock_manifest.GUARDED_BY.get(key, (None, "<undeclared>"))
        guard = lock if lock else f"UNGUARDED ({reason})"
        multi = " [multi-root]" if rec["multi"] else ""
        out.append(f"{key} <- {guard}{multi}")
        out.append(f"    contexts: {', '.join(sorted(rec['contexts']))}")
        for scope, _attr, line, _end, _held, _el in rec["writes"][:4]:
            out.append(f"    write {rel(scope.mod.path, root)}:{line} "
                       f"({scope.name})")
    return out


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    if sources is None:
        sources = load_sources(python_files(root, DEFAULT_SUBDIRS))
    return check(sources, root)
