"""The declared lock-order manifest for ``runtime/`` + ``observability/``.

``distkeras-lint``'s lock-order pass discovers every ``threading.Lock``/
``RLock``/``Condition`` attribute in the analyzed modules, builds the
acquisition graph (lock A held while acquiring lock B — from nested
``with`` blocks and one level of intra-module call resolution), and then
checks that graph against THIS file:

- every edge must be acyclic, and
- every edge whose endpoints both appear in :data:`LOCK_ORDER` must point
  forward in that list (outermost first).

A lock that participates in any acquisition edge must be listed here —
adding a new nested acquisition forces an explicit ordering decision
instead of a reviewer's memory (the PR-8 ``monitor()`` deadlock shipped
precisely because no such decision existed).  Locks that are only ever
held alone need no entry.

Node naming: ``ClassName._attr`` for instance locks (named by the class
that DEFINES the attribute, so subclass acquisitions unify), and
``module._name`` for module-level locks (e.g. ``health._default_lock``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

#: Outermost-first global acquisition order.  An observed edge A->B with
#: both ends listed must satisfy index(A) < index(B).
LOCK_ORDER = [
    # coordinator / snapshot plane (holds center locks via its cut)
    "SnapshotSetCoordinator._save_lock",
    "HubSnapshotter._save_lock",
    # adaptive combiner: drain owner applies batches into the center
    "_AdaptiveCombiner._drain",
    "_AdaptiveCombiner._qlock",
    # replication feed: attach full-syncs under the hub's center lock
    "ReplicationFeed._lock",
    # the center lock itself
    "SocketParameterServer._lock",
    # hub side-structures, only ever leaves under the center/feed locks
    "SocketParameterServer._conn_lock",
    "SocketParameterServer._member_lock",
    "SocketParameterServer._feed_lock",
    "SocketParameterServer._bp_lock",
    # client-side I/O serializer
    "PSClient._io_lock",
    # native hub wrapper
    "NativeParameterServer._stats_lock",
    "NativeParameterServer._drain_lock",
    # health plane
    "health._default_lock",  # lint: telemetry-ok lock node name, not a metric
    "HealthMonitor._lock",
    "HealthCollector._lock",
    # fleet controller (ISSUE 19): a leaf — spawn/retire callbacks and
    # telemetry run OUTSIDE it by contract (monitor callbacks arrive on
    # emitting threads that may hold hub/trainer locks above)
    "FleetController._lock",
    # leaf infrastructure: metrics registry and instruments, tracer, sinks
    "MetricsRegistry._lock",
    "SpanTracer._lock",
    "JsonlFlusher._write_lock",
    "TimeSeries._lock",
    "Counter._lock",
    "Gauge._lock",
    "Histogram._lock",
    "distributed._clock_lock",
]

#: Allow-listed acquisition edges ``(holder, acquired) -> reason``.
#: Every entry documents WHY the edge cannot deadlock; the pass drops
#: these edges before cycle/order checking.  No blanket suppressions —
#: an empty reason string is rejected by the pass itself, and an entry
#: must correspond to an edge the analyzer actually SEES (a dead entry
#: would pre-suppress future genuine findings on that pair; see the
#: coordinator note below for the one acquisition the AST cannot see).
EXCEPTIONS: Dict[Tuple[str, str], str] = {}

#: Documented-but-AST-invisible acquisition: ``SnapshotSetCoordinator.
#: _cut`` holds EVERY shard hub's center lock at once via
#: ``ExitStack.enter_context`` over a list of lock objects (an
#: acquisition form the ``with``-scan cannot resolve, so it produces no
#: graph edge and needs no EXCEPTIONS entry).  It cannot deadlock: the
#: locks belong to DISTINCT hub instances, acquired in fixed hub-list
#: order, and commit handlers take exactly one shard lock each — no
#: cross-ordering exists to invert.  Recorded here so the design
#: decision survives; if the cut is ever rewritten as literal nested
#: ``with`` statements, the analyzer will see a
#: (SocketParameterServer._lock, SocketParameterServer._lock) self-edge
#: and THAT is the moment to allow-list it explicitly.

#: Guarded-by manifest (ISSUE 14): ``ClassName._attr`` -> (guard, reason)
#: for every attribute the guarded-by pass discovers as SHARED — written
#: from more than one thread root (or from a multi-instance root such as
#: the per-connection handler loop).  ``guard`` is a lock node name from
#: the vocabulary above; every write to the attribute must then be
#: inside that lock's held region (lexically or at method entry, see
#: ``analysis/guarded_by.py``).  ``guard=None`` declares BY-DESIGN
#: unguarded state and the reason is mandatory.  The table is
#: self-cleaning: entries for attributes that are no longer shared,
#: guards that name unknown locks, and reasonless ``None`` entries are
#: all findings.  The dynamic lockset checker (``analysis/lockset.py``,
#: ``DKT_LOCKSET=1``) validates the SAME table at runtime.
GUARDED_BY: Dict[str, Tuple[Optional[str], str]] = {
    # -- hub core state: everything the commit/pull/replication paths
    #    read-modify-write lives under the center lock
    "SocketParameterServer._clock": ("SocketParameterServer._lock", ""),
    "SocketParameterServer._clock_fence": ("SocketParameterServer._lock", ""),
    "SocketParameterServer.num_updates": ("SocketParameterServer._lock", ""),
    "SocketParameterServer._standby": ("SocketParameterServer._lock", ""),
    "SocketParameterServer.promoted": ("SocketParameterServer._lock", ""),
    "SocketParameterServer.promoted_at_clock":
        ("SocketParameterServer._lock", ""),
    # -- hub side-structures under their dedicated leaf locks
    "SocketParameterServer._feed": ("SocketParameterServer._feed_lock", ""),
    "SocketParameterServer._members":
        ("SocketParameterServer._member_lock", ""),
    "SocketParameterServer._member_seq":
        ("SocketParameterServer._member_lock", ""),
    "SocketParameterServer._retry_seq": ("SocketParameterServer._bp_lock", ""),
    "SocketParameterServer._storm_until":
        ("SocketParameterServer._bp_lock", ""),
    "SocketParameterServer.backpressure_hints":
        ("SocketParameterServer._bp_lock", ""),
    # -- by-design unguarded hub state (reasons mandatory)
    "SocketParameterServer._health": (None, (
        "idempotent lazy bind of the process-wide health collector: every "
        "racing handler stores the SAME singleton object, so the worst "
        "outcome is a duplicate module attribute lookup")),
    "SocketParameterServer._health_mod": (None, (
        "idempotent lazy bind of the health module reference (same "
        "singleton-bind argument as _health)")),
    "SocketParameterServer._health_monitor": (None, (
        "idempotent lazy bind of the process-wide monitor singleton; "
        "readers null-check every use")),
    # -- snapshot plane
    "HubSnapshotter._next_step": ("HubSnapshotter._save_lock", ""),
    "SnapshotSetCoordinator._next_step":
        ("SnapshotSetCoordinator._save_lock", ""),
    # -- adaptive plane
    "AdaptiveRateController._scales": ("AdaptiveRateController._lock", ""),
    # -- hyperscale embedding tier (ISSUE 15)
    "SocketParameterServer._touch_folds": ("SocketParameterServer._lock", ""),
    "ReplicationFeed.repl_sparse_bytes": ("ReplicationFeed._lock", ""),
    "VarFrameEncoder._tx": (None, (
        "one encoder per connection/direction owner by documented "
        "contract (the FlatFrameCodec._tx argument); the replication "
        "feed's shared instance is additionally serialized by the feed "
        "lock around every pack/send")),
    "VarFrameEncoder.frame_len": (None, (
        "same single-owner contract as VarFrameEncoder._tx — frame_len "
        "is the most-recent-pack bookkeeping of that same buffer")),
    # -- client pipeline state: the io lock serializes the FIFO and owns
    #    the freshness clock the heartbeat reads
    "PSClient._last_io": ("PSClient._io_lock", ""),
    # -- codec tx buffer: single-owner per connection/direction BY
    #    CONTRACT (class docstring); class-level analysis cannot see
    #    instance confinement, so the contract is declared here instead
    "FlatFrameCodec._tx": (None, (
        "one codec per connection/direction owner by documented contract "
        "— instances are thread-confined even though the CLASS is "
        "reachable from many thread roots")),
    # -- zero-copy transport (ISSUE 18): the shm ring is SPSC by
    #    construction — ownership is split per COUNTER, not per object,
    #    so the contract lives here rather than in a lock
    "ShmFrameRing._q": (None, (
        "SPSC ring counters behind this view are split-owned: the head "
        "word (_SHM_Q_HEAD) is written only by the producer role and "
        "the tail word (_SHM_Q_TAIL) only by the consumer, each "
        "published after its payload copy so the peer never observes "
        "torn bytes; the attribute itself is rebound (to None) only in "
        "close()/_release() by that same single owner")),
    "ShmFrameRing._i": (None, (
        "closed-flag words: one-way latches raised by the owning role "
        "in close() or by either side in mark_closed() for shutdown "
        "wakeup; peers re-check every park iteration, so the worst "
        "cost of a stale read is one extra spin")),
    "ShmFrameRing._data": (None, (
        "payload bytes are handed off by the head/tail ticket protocol "
        "in ShmFrameRing._q: the producer only writes free space below "
        "tail+capacity and publishes head AFTER the copy, the consumer "
        "only reads below head — the two sides never touch the same "
        "byte range concurrently")),
    "ShmEndpoint._timeout": (None, (
        "GIL-atomic float/None rebinding mirroring socket.settimeout "
        "semantics; endpoint use is already serialized by the owning "
        "connection (PSClient._io_lock / one hub handler thread) and a "
        "stale timeout for one operation is benign")),
    "SocketParameterServer._conns": ("SocketParameterServer._conn_lock", ""),
    "SocketParameterServer._shm_seq":
        ("SocketParameterServer._conn_lock", ""),
    # -- multi-job admission (ISSUE 19): namespaces, verdict counters and
    #    every per-job center mutation settle under the center lock
    "SocketParameterServer._jobs": ("SocketParameterServer._lock", ""),
    "SocketParameterServer.jobs_admitted":
        ("SocketParameterServer._lock", ""),
    "SocketParameterServer.jobs_rejected":
        ("SocketParameterServer._lock", ""),
    # -- fleet controller (ISSUE 19): decision state under its leaf lock
    "FleetController._last_spawn": ("FleetController._lock", ""),
    "FleetController._spawns": ("FleetController._lock", ""),
    "FleetController._retires": ("FleetController._lock", ""),
    "FleetController._strikes": ("FleetController._lock", ""),
    # -- punchcard daemon
    "Punchcard._jobs": ("Punchcard._lock", ""),
    "Punchcard._lock_path": ("Punchcard._lock", ""),
    "Punchcard._running": (None, (
        "GIL-atomic run flag with one lifecycle transition each way; "
        "accept/executor loops tolerate a stale read for one iteration "
        "by design (stop() additionally severs the listener to wake them)")),
    # -- native hub wrapper: same singleton-bind rule as the Python hub
    "NativeParameterServer._health": (None, (
        "idempotent lazy bind of the process-wide health collector "
        "(poll thread and start() store the same singleton)")),
    "NativeParameterServer._health_monitor": (None, (
        "idempotent lazy bind of the process-wide monitor singleton; "
        "readers null-check every use")),
}

#: Locks whose DECLARED PURPOSE is serializing blocking I/O on a shared
#: resource -> reason.  The blocking-call-under-lock pass skips regions
#: whose held locks all appear here; any other lock held concurrently
#: still flags.  Point suppressions on individual lines use
#: ``# lint: blocking-ok <reason>`` instead.
IO_LOCKS: Dict[str, str] = {
    "PSClient._io_lock": (
        "the io lock IS the socket serializer: every request/reply pair, "
        "heartbeat round trip and reconnect swap must run under it so the "
        "pipelined FIFO can never interleave (the PR-7 fix bounded the "
        "held-time with a short ping timeout rather than moving I/O out)"),
    "JsonlFlusher._write_lock": (
        "the write lock exists solely to keep concurrent JSONL appends "
        "from tearing lines in the shared sink file"),
}
