"""Lock-order analyzer (pass 1 of ``distkeras-lint``).

An AST pass over the hub stack (``runtime/`` + ``observability/``) that:

1. discovers every lock attribute — ``self._x = threading.Lock()`` (or
   ``RLock``/``Condition``) in any method, plus module-level locks —
   naming each node by the class that DEFINES it (``ClassName._attr``) or
   its module (``module._name``);
2. builds the acquisition graph: lock A "held into" lock B when a
   ``with B`` nests lexically inside a ``with A`` region, or when a call
   made while A is held resolves (ONE level, intra-module: ``self.meth``
   through the class and its in-module bases, bare names through
   module-level functions) to a function that acquires B.  Simple local
   aliases (``hub = self.hub``) and annotated constructor attributes
   (``self.hub = hub`` with ``hub: "SocketParameterServer"``) are
   resolved so the real cross-class edges (feed -> hub center lock) are
   seen;
3. fails on self-edges (re-acquiring a non-reentrant lock — the PR-8
   ``monitor()`` deadlock shape), on cycles, and on any edge that points
   BACKWARD against the declared :data:`~distkeras_tpu.analysis.
   lock_manifest.LOCK_ORDER`; an edge lock must be listed in the
   manifest so every new nesting is an explicit ordering decision.

Documented exceptions (e.g. ``SnapshotSetCoordinator`` holding every
center lock at once) are allow-listed in ``lock_manifest.EXCEPTIONS``
with a reason string; an empty reason is itself a finding.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from distkeras_tpu.analysis import lock_manifest
from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

Edge = Tuple[str, str]


def _is_lock_value(node: ast.AST) -> bool:
    """True if the assigned value contains a ``threading.Lock()``-style
    call (covers conditional forms like ``Lock() if x else None``)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "threading":
                return True
            if isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES:
                return True
    return False


class ClassInfo:
    def __init__(self, name: str, module: str):
        self.name = name
        self.module = module
        #: back-reference to the defining ModuleIndex — callee lock
        #: resolution must use the module the code is DEFINED in, not
        #: the caller's (same-named module locks would cross-talk)
        self.modindex: Optional["ModuleIndex"] = None
        self.bases: List[str] = []
        self.lock_attrs: Set[str] = set()
        #: attr -> class name, from annotated ``self.attr = param``
        self.attr_class: Dict[str, str] = {}
        self.methods: Dict[str, ast.FunctionDef] = {}


class ModuleIndex:
    """Lock/class/function index of one module."""

    def __init__(self, path: str, src: SourceFile):
        self.path = path
        self.stem = os.path.splitext(os.path.basename(path))[0]
        self.src = src
        self.classes: Dict[str, ClassInfo] = {}
        self.module_locks: Set[str] = set()
        self.functions: Dict[str, ast.FunctionDef] = {}
        self._build()

    def _build(self) -> None:
        for node in self.src.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_value(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                info = ClassInfo(node.name, self.stem)
                info.modindex = self
                info.bases = [b.id for b in node.bases
                              if isinstance(b, ast.Name)]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info.methods[item.name] = item
                        self._scan_method(info, item)
                self.classes[node.name] = info

    def _scan_method(self, info: ClassInfo, fn: ast.FunctionDef) -> None:
        ann: Dict[str, str] = {}
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = a.annotation
            if isinstance(t, ast.Constant) and isinstance(t.value, str):
                ann[a.arg] = t.value.strip("'\"")
            elif isinstance(t, ast.Name):
                ann[a.arg] = t.id
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    if _is_lock_value(node.value):
                        info.lock_attrs.add(t.attr)
                    elif isinstance(node.value, ast.Name) \
                            and node.value.id in ann:
                        info.attr_class[t.attr] = ann[node.value.id]
                    elif isinstance(node.value, ast.Call):
                        # direct constructor assignment (``self._feed =
                        # ReplicationFeed(self)``): the attribute's type
                        # is the called class — resolved later against
                        # the cross-module class index, so non-class
                        # callees simply never resolve
                        f = node.value.func
                        cname = (f.id if isinstance(f, ast.Name)
                                 else f.attr if isinstance(f, ast.Attribute)
                                 else None)
                        if cname is not None and cname[:1].isupper():
                            info.attr_class.setdefault(t.attr, cname)


class LockIndex:
    """The cross-module index the lock-order and blocking passes share."""

    def __init__(self, sources: Dict[str, SourceFile]):
        self.modules: Dict[str, ModuleIndex] = {
            p: ModuleIndex(p, s) for p, s in sources.items()}
        self.class_by_name: Dict[str, ClassInfo] = {}
        for m in self.modules.values():
            for c in m.classes.values():
                self.class_by_name.setdefault(c.name, c)

    # -- resolution ------------------------------------------------------------
    def _defining_class(self, cls: ClassInfo, attr: str) -> Optional[ClassInfo]:
        seen: Set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.lock_attrs:
                return c
            stack.extend(self.class_by_name[b] for b in c.bases
                         if b in self.class_by_name)
        return None

    def _attr_type(self, cls: ClassInfo, attr: str) -> Optional[ClassInfo]:
        seen: Set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.attr_class:
                return self.class_by_name.get(c.attr_class[attr])
            stack.extend(self.class_by_name[b] for b in c.bases
                         if b in self.class_by_name)
        return None

    def resolve_lock(self, expr: ast.AST, mod: ModuleIndex,
                     cls: Optional[ClassInfo],
                     aliases: Dict[str, Tuple[str, ...]]) -> Optional[str]:
        """Resolve a ``with``-item (or ``.acquire()`` receiver) expression
        to a lock node name, or None for non-lock/unresolvable items."""
        chain = _attr_chain(expr)
        if chain is None:
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in aliases:
                chain = aliases[name]
            elif name in mod.module_locks:
                return f"{mod.stem}.{name}"
            else:
                return None
        elif chain[0] in aliases:
            chain = aliases[chain[0]] + chain[1:]
        if chain[0] != "self" or cls is None or len(chain) < 2:
            return None
        owner: Optional[ClassInfo] = cls
        for attr in chain[1:-1]:
            owner = self._attr_type(owner, attr)
            if owner is None:
                return None
        defining = self._defining_class(owner, chain[-1])
        if defining is None:
            return None
        return f"{defining.name}.{chain[-1]}"

    def locks_acquired_in(self, fn: ast.AST, mod: ModuleIndex,
                          cls: Optional[ClassInfo]) -> Set[str]:
        """Every lock node this function acquires anywhere in its body
        (``with`` items and bare ``.acquire()`` calls) — the one-level
        call-resolution summary.  Deferred code (lambdas, nested defs)
        is excluded: it runs later, on some other call stack."""
        out: Set[str] = set()
        aliases = _local_aliases(fn)
        for node in _walk_outside_deferred(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lk = self.resolve_lock(item.context_expr, mod, cls,
                                           aliases)
                    if lk:
                        out.add(lk)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                lk = self.resolve_lock(node.func.value, mod, cls, aliases)
                if lk:
                    out.add(lk)
        return out


def _attr_chain(expr: ast.AST) -> Optional[Tuple[str, ...]]:
    """``self.hub._lock`` -> ("self", "hub", "_lock"); None when the
    expression is not a plain name/attribute chain."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _local_aliases(fn: ast.AST) -> Dict[str, Tuple[str, ...]]:
    """First-assignment local aliases of self-attribute chains
    (``hub = self.hub`` -> {"hub": ("self", "hub")})."""
    out: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            chain = _attr_chain(node.value)
            if chain and chain[0] == "self" \
                    and node.targets[0].id not in out:
                out[node.targets[0].id] = chain
    return out


class _EdgeCollector:
    """Walks one function body tracking the held-lock stack, emitting
    acquisition edges (nested ``with`` + one-level call resolution)."""

    def __init__(self, index: LockIndex, mod: ModuleIndex,
                 cls: Optional[ClassInfo], root: str):
        self.index = index
        self.mod = mod
        self.cls = cls
        self.root = root
        self.edges: Dict[Edge, List[Tuple[str, int, str]]] = {}

    def _add(self, src: str, dst: str, line: int, via: str) -> None:
        self.edges.setdefault((src, dst), []).append(
            (rel(self.mod.path, self.root), line, via))

    def run(self, fn: ast.AST) -> None:
        self.aliases = _local_aliases(fn)
        self._walk(getattr(fn, "body", []), [])

    def _walk(self, body: Sequence[ast.stmt], held: List[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in stmt.items:
                    lk = self.index.resolve_lock(item.context_expr, self.mod,
                                                 self.cls, self.aliases)
                    if lk:
                        for h in held + acquired:
                            self._add(h, lk, stmt.lineno, "with")
                        acquired.append(lk)
                    elif held or acquired:
                        # non-lock context manager entered while held may
                        # still acquire (obs.span does not; a callable
                        # that does would need its own with-scan) — only
                        # CALL resolution below sees through it
                        self._scan_calls(item.context_expr, held + acquired,
                                         stmt.lineno)
                self._walk(stmt.body, held + acquired)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later, not under the current held set
                _EdgeCollector(self.index, self.mod, self.cls,
                               self.root)._merge_into(self, stmt)
            else:
                if held:
                    self._scan_calls(stmt, held, stmt.lineno)
                for child_body in _sub_bodies(stmt):
                    self._walk(child_body, held)

    def _merge_into(self, parent: "_EdgeCollector", fn: ast.AST) -> None:
        self.run(fn)
        for edge, locs in self.edges.items():
            parent.edges.setdefault(edge, []).extend(locs)

    def _scan_calls(self, node: ast.AST, held: List[str], line: int) -> None:
        """One level of intra-module call resolution: edges from every
        held lock to every lock the (resolvable) callee acquires.  When
        handed a statement, only its OWN expressions are scanned — its
        nested statement bodies are walked separately."""
        roots = (list(_own_exprs(node)) if isinstance(node, ast.stmt)
                 else [node])
        # lambdas built while held run LATER, outside the lock — calls
        # inside them are neither blocking-under-lock nor acquisitions
        for call in (c for r in roots for c in _walk_outside_lambda(r)):
            if not isinstance(call, ast.Call):
                continue
            callee: Optional[ast.AST] = None
            callee_cls = self.cls
            callee_mod = self.mod
            f = call.func
            if isinstance(f, ast.Attribute):
                chain = _attr_chain(f)
                if f.attr == "acquire":
                    lk = self.index.resolve_lock(f.value, self.mod, self.cls,
                                                 self.aliases)
                    if lk:
                        for h in held:
                            self._add(h, lk, call.lineno, "acquire()")
                    continue
                found = None
                if chain and chain[0] == "self" and len(chain) == 2 \
                        and self.cls is not None:
                    found = _find_method(self.index, self.cls, chain[1])
                elif chain and len(chain) >= 2:
                    # method on a typed attribute chain (self.hub.promote)
                    base = chain[:-1]
                    owner = self._resolve_owner(base)
                    if owner is not None:
                        found = _find_method(self.index, owner, chain[-1])
                        callee_cls = owner
                if found is not None:
                    callee, defining = found
                    # resolve the callee's bare-name/module locks against
                    # the module its code lives in, not the caller's
                    callee_mod = defining.modindex or self.mod
            elif isinstance(f, ast.Name) and f.id in self.mod.functions:
                callee = self.mod.functions[f.id]
                callee_cls = None
            if callee is None:
                continue
            for lk in self.index.locks_acquired_in(callee, callee_mod,
                                                   callee_cls):
                for h in held:
                    self._add(h, lk, call.lineno,
                              f"call {ast.unparse(f)}()")

    def _resolve_owner(self, base: Tuple[str, ...]) -> Optional[ClassInfo]:
        if base[0] in self.aliases:
            base = self.aliases[base[0]] + base[1:]
        if base[0] != "self" or self.cls is None:
            return None
        owner: Optional[ClassInfo] = self.cls
        for attr in base[1:]:
            owner = self.index._attr_type(owner, attr)
            if owner is None:
                return None
        return owner


def _find_method(index: LockIndex, cls: ClassInfo, name: str
                 ) -> Optional[Tuple[ast.FunctionDef, ClassInfo]]:
    """Resolve ``name`` through ``cls`` and its known bases, returning
    the method AND the class that defines it (whose module scopes the
    callee's lock resolution)."""
    seen: Set[str] = set()
    stack = [cls]
    while stack:
        c = stack.pop(0)
        if c.name in seen:
            continue
        seen.add(c.name)
        if name in c.methods:
            return c.methods[name], c
        stack.extend(index.class_by_name[b] for b in c.bases
                     if b in index.class_by_name)
    return None


def _sub_bodies(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val and isinstance(val[0], ast.stmt):
            yield val
    for h in getattr(stmt, "handlers", []):
        yield h.body
    for c in getattr(stmt, "cases", []):  # match-case arms
        yield c.body


def _own_exprs(stmt: ast.stmt):
    """The expression children of one statement, EXCLUDING nested
    statement lists (those are walked separately with their own held
    sets — scanning them here would double-count)."""
    for _field, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _walk_outside_lambda(expr: ast.AST):
    """``ast.walk`` that does not descend into ``lambda`` bodies — a
    lambda built under a lock runs LATER, outside it."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _walk_outside_deferred(fn: ast.AST):
    """Walk a function body excluding deferred code — lambdas AND nested
    function definitions (both run later, on another call stack)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def build_graph(sources: Dict[str, SourceFile],
                root: str) -> Dict[Edge, List[Tuple[str, int, str]]]:
    """The full acquisition graph over ``sources``:
    ``(holder, acquired) -> [(path, line, via), ...]``."""
    index = LockIndex(sources)
    edges: Dict[Edge, List[Tuple[str, int, str]]] = {}
    for mod in index.modules.values():
        scopes = [(None, fn) for fn in mod.functions.values()]
        for cls in mod.classes.values():
            scopes.extend((cls, fn) for fn in cls.methods.values())
        for cls, fn in scopes:
            c = _EdgeCollector(index, mod, cls, root)
            c.run(fn)
            for edge, locs in c.edges.items():
                edges.setdefault(edge, []).extend(locs)
    return edges


def _fmt_locs(locs: Sequence[Tuple[str, int, str]], limit: int = 2) -> str:
    return "; ".join(f"{p}:{ln} ({via})" for p, ln, via in locs[:limit])


def check(sources: Dict[str, SourceFile], root: str,
          order: Optional[Sequence[str]] = None,
          exceptions: Optional[Dict[Edge, str]] = None) -> List[Finding]:
    """Run the lock-order pass; ``order``/``exceptions`` default to the
    repo manifest (overridable for fixture tests)."""
    order = list(lock_manifest.LOCK_ORDER if order is None else order)
    exceptions = dict(lock_manifest.EXCEPTIONS
                      if exceptions is None else exceptions)
    findings: List[Finding] = []
    edges = build_graph(sources, root)
    for edge, reason in exceptions.items():
        if not str(reason).strip():
            findings.append(Finding(
                "lock-order", "distkeras_tpu/analysis/lock_manifest.py", 1,
                f"exception {edge[0]} -> {edge[1]} has no reason string"))
        elif edge not in edges:
            # self-cleaning manifest: a dead entry would pre-suppress a
            # FUTURE genuine finding on this pair (the masked-bug class
            # the manifest's own docstring warns about)
            findings.append(Finding(
                "lock-order", "distkeras_tpu/analysis/lock_manifest.py", 1,
                f"stale exception: edge {edge[0]} -> {edge[1]} no longer "
                f"exists in the acquisition graph — drop the EXCEPTIONS "
                f"entry"))
    live = {e: locs for e, locs in edges.items() if e not in exceptions}
    pos = {name: i for i, name in enumerate(order)}

    for (src, dst), locs in sorted(live.items()):
        path, line, via = locs[0]
        if src == dst:
            findings.append(Finding(
                "lock-order", path, line,
                f"re-acquisition of non-reentrant {src} while already "
                f"held ({via}) — deadlock (the PR-8 monitor() shape); "
                f"order it or allow-list it in lock_manifest.EXCEPTIONS"))
            continue
        if src in pos and dst in pos and pos[src] > pos[dst]:
            findings.append(Finding(
                "lock-order", path, line,
                f"{src} held while acquiring {dst} inverts the declared "
                f"LOCK_ORDER (at {_fmt_locs(locs)})"))
        for node in (src, dst):
            if node not in pos:
                findings.append(Finding(
                    "lock-order", path, line,
                    f"lock {node} participates in acquisition edge "
                    f"{src} -> {dst} but is not declared in "
                    f"lock_manifest.LOCK_ORDER"))

    # cycle detection over the remaining (non-self) edges: any strongly
    # connected component with more than one node is a potential deadlock
    graph: Dict[str, Set[str]] = {}
    for (src, dst) in live:
        if src != dst:
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
    for comp in _sccs(graph):
        if len(comp) > 1:
            cyc = sorted(comp)
            locs = [loc for e, ls in live.items()
                    if e[0] in comp and e[1] in comp for loc in ls]
            path, line = (locs[0][0], locs[0][1]) if locs else ("<graph>", 0)
            findings.append(Finding(
                "lock-order", path, line,
                f"lock acquisition cycle: {' -> '.join(cyc + [cyc[0]])} "
                f"(at {_fmt_locs(locs)})"))
    return apply_annotations(findings, sources, root, rule="lock-order")


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan strongly-connected components (iterative)."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    for v in graph:
        if v not in idx:
            strong(v)
    return out


DEFAULT_SUBDIRS = (os.path.join("distkeras_tpu", "runtime"),
                   os.path.join("distkeras_tpu", "observability"))


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    if sources is None:
        sources = load_sources(python_files(root, DEFAULT_SUBDIRS))
    return check(sources, root)
