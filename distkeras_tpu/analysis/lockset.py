"""Dynamic lockset checker (pass 7 of ``distkeras-lint``) — ISSUE 14.

Eraser-style (Savage et al.) runtime validation of the SAME contract the
static guarded-by pass checks lexically: which lock protects which
attribute.  Opt-in via ``DKT_LOCKSET=1`` (the instrumentation patches
``__setattr__`` on the watched classes — never the production default).

Mechanics:

- :class:`TrackingLock` wraps each watched instance's ``threading``
  locks (discovered after ``__init__``), maintaining a per-thread
  **held set** of lock node names (``SocketParameterServer._lock``, the
  manifest vocabulary);
- a patched ``__setattr__`` observes every attribute write on watched
  instances:

  * an attribute DECLARED in
    :data:`~distkeras_tpu.analysis.lock_manifest.GUARDED_BY` with a
    guard must be written with that guard held — once the attribute has
    been touched by more than one thread (the Eraser init-phase
    exemption covers construction and pre-thread setup);
  * an UNDECLARED attribute written by multiple threads runs the
    classic candidate-set intersection: ``C(v) &= held`` at every
    post-sharing write; ``C(v) = {}`` means no single lock protected
    every write — a race candidate the static pass could not see
    (reads, container mutation, reflection all surface here);

- violations become ordinary :class:`Finding` records (rule
  ``lockset``) pinned to the writing source line, flowing through
  ``distkeras-lint --json`` like any static pass.

:func:`stress` is the built-in harness: a sparse+adaptive+replicated
hub, a standby, and a small client fleet hammering commit / pull /
sparse / replication / health concurrently under instrumentation.
``distkeras-lint --pass lockset`` with ``DKT_LOCKSET=1`` runs it; the
slow-marked cell in ``tests/test_analysis.py`` gates it in CI.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from distkeras_tpu.analysis import lock_manifest
from distkeras_tpu.analysis.core import Finding, rel, repo_root

RULE = "lockset"

_TOP = None  # candidate-set "all locks" sentinel


def enabled() -> bool:
    """The dynamic checker's opt-in gate (``DKT_LOCKSET=1``)."""
    return os.environ.get("DKT_LOCKSET", "") not in ("", "0")


class _Held(threading.local):
    def __init__(self):
        self.names: Dict[str, int] = {}


class LocksetChecker:
    """Shared state of one instrumentation session: per-thread held
    sets, per-attribute ownership/candidates, collected findings."""

    def __init__(self, guarded_by: Optional[Dict[str, Tuple[Optional[str],
                                                            str]]] = None,
                 root: Optional[str] = None):
        self.table = dict(lock_manifest.GUARDED_BY
                          if guarded_by is None else guarded_by)
        self.root = root or repo_root()
        self._held = _Held()
        self._lock = threading.Lock()
        #: (id(obj), attr) -> [owner_thread_id or None(shared), candidates]
        self._state: Dict[Tuple[int, str], List[Any]] = {}
        self._reported: Set[Tuple[str, str]] = set()
        self.findings: List[Finding] = []
        self.writes_checked = 0

    # -- held-set maintenance (called by TrackingLock) -------------------------
    def push(self, name: str) -> None:
        h = self._held.names
        h[name] = h.get(name, 0) + 1

    def pop(self, name: str) -> None:
        h = self._held.names
        n = h.get(name, 0) - 1
        if n <= 0:
            h.pop(name, None)
        else:
            h[name] = n

    def held(self) -> Set[str]:
        return set(self._held.names)

    # -- write observation -----------------------------------------------------
    def observe_write(self, obj: Any, attr: str) -> None:
        key = self._node_name(type(obj), attr)
        entry = self.table.get(key)
        if entry is not None and entry[0] is None:
            return  # declared by-design unguarded
        tid = threading.get_ident()
        sid = (id(obj), attr)
        held = self.held()
        with self._lock:
            self.writes_checked += 1
            st = self._state.get(sid)
            if st is None:
                # [exclusive_owner, candidates, post-sharing writer ids]
                self._state[sid] = [tid, _TOP, set()]
                return
            if st[0] == tid and st[0] is not None:
                return  # still exclusive to its first thread
            st[0] = None  # shared from here on
            st[2].add(tid)
            if len(st[2]) < 2:
                # ownership HANDOFF (constructed by one thread, owned by
                # exactly one other — daemon-loop state) is not sharing:
                # require two distinct post-sharing writers before any
                # verdict, the refinement that kills Eraser's classic
                # init-then-handoff false positive
                if entry is None:
                    st[1] = set(held) if st[1] is _TOP else (st[1] & held)
                return
            if entry is not None:
                guard = entry[0]
                if guard not in held:
                    self._report(key, guard, held)
                return
            # undeclared: Eraser candidate intersection
            st[1] = set(held) if st[1] is _TOP else (st[1] & held)
            if not st[1]:
                self._report(key, None, held)

    def _report(self, key: str, guard: Optional[str],
                held: Set[str]) -> None:
        dedup = (key, guard or "")
        if dedup in self._reported:
            return
        self._reported.add(dedup)
        path, line = _caller_site()
        if guard is not None:
            msg = (f"{key} is declared guarded by {guard} but a "
                   f"post-sharing write ran with held set "
                   f"{sorted(held) or '{}'} — dynamic guarded-by violation")
        else:
            msg = (f"{key} is written by multiple threads and its lockset "
                   f"went EMPTY (no single lock was held across every "
                   f"write) — undeclared race candidate; declare a guard "
                   f"in lock_manifest.GUARDED_BY or fix the locking")
        self.findings.append(
            Finding(RULE, rel(path, self.root), line, msg))

    def _node_name(self, cls: type, attr: str) -> str:
        # prefer the manifest's own vocabulary (the class that declares
        # the attribute guarded); otherwise the concrete class is a
        # stable, readable node name for an undeclared attribute
        for c in cls.__mro__:
            if f"{c.__name__}.{attr}" in self.table:
                return f"{c.__name__}.{attr}"
        return f"{cls.__name__}.{attr}"

    def lock_name(self, cls: type, attr: str) -> str:
        known = set(lock_manifest.LOCK_ORDER)
        known.update(g for g, _ in self.table.values() if g)
        for c in cls.__mro__:
            if f"{c.__name__}.{attr}" in known:
                return f"{c.__name__}.{attr}"
        return f"{cls.__name__}.{attr}"


def _caller_site() -> Tuple[str, int]:
    """First stack frame outside this module (the write site)."""
    f = sys._getframe(2)
    here = os.path.abspath(__file__)
    while f is not None and os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:  # pragma: no cover - defensive
        return "<unknown>", 0
    return f.f_code.co_filename, f.f_lineno


class TrackingLock:
    """Transparent lock proxy that records acquire/release in the
    checker's per-thread held set under the lock's node name."""

    def __init__(self, inner: Any, name: str, checker: LocksetChecker):
        self._inner = inner
        self._name = name
        self._checker = checker

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._checker.push(self._name)
        return got

    def release(self):
        self._inner.release()
        self._checker.pop(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, item):  # Condition.wait/notify, locked(), ...
        return getattr(self._inner, item)


_LOCK_TYPES = tuple(t for t in (type(threading.Lock()),
                                type(threading.RLock()),
                                threading.Condition) if isinstance(t, type))


@contextlib.contextmanager
def instrument(*classes: type,
               checker: Optional[LocksetChecker] = None,
               guarded_by: Optional[Dict[str, Tuple[Optional[str],
                                                    str]]] = None):
    """Context manager: watch every instance of ``classes`` constructed
    inside the block — wrap their lock attributes, observe their writes
    — and restore the classes on exit.  Yields the
    :class:`LocksetChecker` holding the findings."""
    chk = checker or LocksetChecker(guarded_by=guarded_by)
    saved: List[Tuple[type, Dict[str, Any]]] = []

    def make_setattr(orig):
        def _setattr(self, name, value, _orig=orig):
            _orig(self, name, value)
            if not isinstance(value, (_LOCK_TYPES + (TrackingLock,))):
                chk.observe_write(self, name)
        return _setattr

    def make_init(orig):
        def _init(self, *a, _orig=orig, **kw):
            _orig(self, *a, **kw)
            for attr, val in list(self.__dict__.items()):
                if isinstance(val, _LOCK_TYPES):
                    object.__setattr__(
                        self, attr,
                        TrackingLock(val, chk.lock_name(type(self), attr),
                                     chk))
        return _init

    try:
        for cls in classes:
            if any(other is not cls and other in cls.__mro__
                   for other in classes):
                # an instrumented ancestor's patched __setattr__/__init__
                # is inherited — patching the subclass too would wrap the
                # wrapper and observe every write twice.  (Caveat: a
                # subclass __init__ that creates ADDITIONAL locks after
                # super().__init__ needs its own entry in the list AND
                # its ancestor removed; none of the watched hub classes
                # do.)
                continue
            saved.append((cls, {
                "__setattr__": cls.__dict__.get("__setattr__"),
                "__init__": cls.__dict__.get("__init__"),
            }))
            cls.__setattr__ = make_setattr(cls.__setattr__)
            cls.__init__ = make_init(cls.__init__)
        yield chk
    finally:
        for cls, attrs in saved:
            for name, val in attrs.items():
                if val is None:
                    with contextlib.suppress(AttributeError):
                        delattr(cls, name)
                else:
                    setattr(cls, name, val)


# -- the built-in stress harness -----------------------------------------------

def stress(duration: float = 2.0, workers: int = 4,
           root: Optional[str] = None) -> List[Finding]:
    """Hammer the Python hub's commit / pull / sparse / replication /
    health paths concurrently under lockset instrumentation and return
    the dynamic findings.  Deterministic shape, wall-bounded."""
    import numpy as np

    from distkeras_tpu.observability import health as health_mod
    from distkeras_tpu.runtime.parameter_server import (
        AdaptiveRateController, DeltaParameterServer, HubSnapshotter,
        PSClient, ReplicationFeed, SocketParameterServer, _AdaptiveCombiner)

    import shutil
    import tempfile

    templates = [np.zeros((8, 4), np.float32), np.zeros((16, 4), np.float32)]
    health_mod.reset_default()
    # shm rings under lockset instrumentation too (ISSUE 18): worker 0
    # attaches via the 'Z' handshake so the ring write/read paths and the
    # hub-side connection swap run alongside the TCP traffic
    shm_dir = tempfile.mkdtemp(
        prefix="dklockset-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    with instrument(SocketParameterServer, DeltaParameterServer,
                    ReplicationFeed, _AdaptiveCombiner,
                    AdaptiveRateController, HubSnapshotter,
                    PSClient, checker=LocksetChecker(root=root)) as chk:
        hub = DeltaParameterServer([t.copy() for t in templates],
                                   host="127.0.0.1", port=0,
                                   idle_timeout=None,
                                   sparse_leaves=(1,), adaptive=True,
                                   shm_dir=shm_dir)
        hub.start()
        standby = DeltaParameterServer([t.copy() for t in templates],
                                       host="127.0.0.1", port=0,
                                       idle_timeout=None,
                                       replica_of=("127.0.0.1", hub.port))
        standby.start()
        standby.wait_synced(5.0)
        stop = threading.Event()
        errors: List[BaseException] = []

        def worker(i: int) -> None:
            try:
                cli = PSClient("127.0.0.1", hub.port, templates,
                               timeout=10.0, max_reconnects=2,
                               sparse_leaves=(1,), adaptive=(i % 2 == 0),
                               shm=(i == 0))
                delta = [np.full_like(t, 1e-3) for t in templates]
                step = 0
                while not stop.is_set():
                    if i % 2 == 0:
                        cli.pull()
                        cli.commit(delta)
                    else:
                        ids = np.unique(np.array(
                            [(step + j) % 16 for j in range(3)], np.int64))
                        cli.pull_nowait(sparse_rows=[ids])
                        cli.wait_weights()
                        # full-shape deltas: the client slices the
                        # touched rows out itself (sparse_rows)
                        cli.commit_nowait(
                            [np.zeros((8, 4), np.float32),
                             np.full((16, 4), 1e-3, np.float32)],
                            sparse_rows=[ids])
                        cli.drain()
                    if step % 5 == 0:
                        cli.report_health({"worker": str(i),
                                           "windows_total": step,
                                           "window_wall_ms": 1.0})
                        cli.drain()
                    step += 1
                cli.close()
            except BaseException as e:  # surfaced to the caller
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(workers)]
        for t in threads:
            t.start()
        stop.wait(duration)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        standby.stop()
        hub.stop()
        shutil.rmtree(shm_dir, ignore_errors=True)
        if errors:
            chk.findings.append(Finding(
                RULE, "distkeras_tpu/analysis/lockset.py", 1,
                f"stress harness worker raised {type(errors[0]).__name__}: "
                f"{errors[0]} — the run did not exercise the full surface"))
    return chk.findings


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, Any]] = None) -> List[Finding]:
    """The CLI pass: inert unless ``DKT_LOCKSET=1`` (dynamic checking is
    opt-in; the static guarded-by pass carries the always-on gate)."""
    if not enabled():
        return []
    return stress(root=root)
