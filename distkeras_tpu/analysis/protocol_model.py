"""Wire-protocol model checker (pass 6 of ``distkeras-lint``) — ISSUE 14.

The PS wire protocol is a set of client<->hub action sequences that PR
12's parity pass only checks for *existence* (every byte handled
somewhere).  This pass adds a declared **transition model** and checks
it two ways:

1. **Static cross-check** against the Python hub's dispatch
   (``SocketParameterServer._handle_connection``):

   - an action byte the hub *admits* (compares against ``action``) that
     the model does not declare is *admitted-but-unmodeled* — the model
     is the contract, so undeclared arms are protocol drift;
   - a modeled request the hub does not admit is
     *modeled-but-unhandled* — a client following the contract would
     desync the stream;
   - a modeled reply the handler provably never produces (neither the
     ``ACTION_*`` constant nor its known encoder appears in the handler
     body) is *modeled-but-unproduced*;
   - model keys must be registered ``ACTION_*`` names (a typo'd key can
     never match and would silently weaken the contract).

2. **Bounded exhaustive exploration** of 2-client x hub interleavings
   (:func:`explore_sessions`): every interleaving of every bounded
   action script, with pipelining up to ``max_inflight``, checking

   - **desync**: a reply kind that does not match the oldest
     outstanding request's declared reply;
   - **deadlock**: a reachable non-final state with no enabled event;

   and of the standby/promotion state machine
   (:func:`explore_standby`): sync-then-delta ``R`` feed, feed loss,
   retry budget, commit-triggered promotion — checking that promotion
   is **reachable**, that no commit is ever acked by an unpromoted
   standby, and that the machine cannot deadlock.

The model is data (:data:`REQUESTS`, :data:`STANDBY_RULES`) so fixture
tests can seed violations; the shipped tables are the contract the real
hubs are checked against.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)
from distkeras_tpu.analysis.wire_parity import parse_action_registry

SELF_PATH = "distkeras_tpu/analysis/protocol_model.py"

#: The declared protocol: client-initiated action -> the reply kind the
#: client must receive (None = no reply, connection closes).  ``R`` is
#: the replica hello: the hub replies with an ``R`` sync frame and the
#: connection leaves the request/reply regime (handoff to the feed).
REQUESTS: Dict[str, Optional[str]] = {
    "ACTION_TRACE": "ACTION_TRACE",
    "ACTION_PULL": "ACTION_WEIGHTS",
    "ACTION_SPARSE_PULL": "ACTION_SPARSE_WEIGHTS",
    "ACTION_COMMIT": "ACTION_ACK",
    "ACTION_QCOMMIT": "ACTION_ACK",
    "ACTION_SPARSE_COMMIT": "ACTION_ACK",
    "ACTION_SPARSE_QCOMMIT": "ACTION_ACK",
    "ACTION_HEALTH": "ACTION_ACK",
    "ACTION_PING": "ACTION_ACK",
    "ACTION_RECONNECT": "ACTION_RETRY",
    "ACTION_BYE": None,
    "ACTION_REPL": "ACTION_REPL",
    # shm attach (ISSUE 18): the client's Z request; the hub replies Z
    # with the ring paths (or an empty decline).  The full three-step
    # never-torn handshake lives in :data:`SHM_RULES`/:func:`explore_shm`
    "ACTION_SHM": "ACTION_SHM",
}

#: Actions that advance the hub's commit clock when served.
CLOCK_BUMPERS: FrozenSet[str] = frozenset({
    "ACTION_COMMIT", "ACTION_QCOMMIT",
    "ACTION_SPARSE_COMMIT", "ACTION_SPARSE_QCOMMIT"})

#: How the handler source proves it PRODUCES each reply kind: any of the
#: listed tokens (an ``ACTION_*`` constant reference, an encoder helper,
#: the feed class that owns the ``R`` stream) appearing in the handler
#: body counts.
REPLY_PRODUCERS: Dict[str, Tuple[str, ...]] = {
    "ACTION_WEIGHTS": ("ACTION_WEIGHTS",),
    "ACTION_ACK": ("ACTION_ACK",),
    "ACTION_SPARSE_WEIGHTS": ("ACTION_SPARSE_WEIGHTS",),
    # the T reply is the clock-sync timestamp, or (for a job-scoped
    # announce, ISSUE 19) the admission verdict — either encoder in the
    # handler body proves production
    "ACTION_TRACE": ("encode_time_payload", "encode_admission_payload"),
    "ACTION_RETRY": ("encode_retry_payload",),
    "ACTION_REPL": ("ReplicationFeed", "attach"),
    "ACTION_SHM": ("ACTION_SHM",),
}

#: The standby/promotion contract (ISSUE 7 semantics) as checkable
#: flags — fixture tests flip these to seed violations.
STANDBY_RULES: Dict[str, Any] = {
    # a full R sync is what arms the standby with real job state
    "sync_sets_synced": True,
    # a commit landing while the feed is DOWN (primary presumed dead)
    # promotes the standby before the commit is applied/acked
    "commit_promotes": True,
    # a commit while the feed is still UP is refused and severs the feed
    # as a liveness probe (split-brain guard)
    "commit_probe_severs": True,
    # a never-synced standby must never promote (it holds seed weights)
    "never_synced_promotes": False,
    # feed-loss retries exhausted on a synced standby promote it
    "loss_exhaustion_promotes": True,
    # an ack may only leave a standby AFTER promotion
    "ack_requires_promoted": True,
    # a REPL_SPARSE row-delta frame may only be sent to a standby whose
    # hello announced REPL_CAP_SPARSE (attach-time capability, ISSUE 15);
    # a legacy standby keeps receiving the dense-materialized delta
    # stream — never a frame kind it cannot parse (a torn stream)
    "sparse_delta_requires_cap": True,
}

#: The fleet join/drain/admission contract (ISSUE 19) as checkable
#: flags.  A job-scoped session announces its namespace on the existing
#: ``T`` trace frame (``job_ns`` key); the hub's admission verdict rides
#: the ``T`` reply.  Planned preemption is SIGTERM-with-a-deadline: the
#: worker finishes its in-flight commits, flushes residuals, sends
#: ``B``, and only then does the controller detach it — membership
#: churn is exactly where interleaving bugs live, so the machine is
#: model-checked before (and independent of) the code.  Fixture tests
#: flip these to seed drain-while-commit-in-flight and
#: admission-reject-races-attach violations.
FLEET_RULES: Dict[str, Any] = {
    # the hub decides admission on the T announce, BEFORE serving any
    # pull/commit on that connection — a verdict raced by an attach
    # would let a to-be-rejected job observe (or move) center state
    "admission_before_attach": True,
    # a rejected connection is never served: any subsequent pull or
    # commit is refused with a protocol error, not silently applied
    "reject_never_serves": True,
    # a draining worker sends BYE only after every in-flight commit is
    # acked (and the int8 residual flush commit, if any, is one of
    # them) — zero acked-commit loss across the drain
    "drain_completes_inflight": True,
    # a respawned replacement pulls the CURRENT center before its first
    # commit — it must never commit a delta computed against the
    # weights its predecessor died holding
    "respawn_pulls_current_center": True,
    # the controller detaches (membership-shrinks) a worker only after
    # observing its drain complete — never mid-commit
    "retire_after_drain_only": True,
}


#: The shm attach/decline/detach contract (ISSUE 18) as checkable flags.
#: The handshake is three TCP frames — client ``Z`` request, hub ``Z``
#: reply (ring paths, or an empty decline), client ``Z`` confirm
#: (mapped / abort) — and only after a positive confirm does EITHER end
#: leave the socket for the ring.  Because TCP is FIFO and the client
#: sends nothing on the socket after a positive confirm, the switch
#: point is totally ordered on both ends: there is never a frame in
#: flight on the transport the peer is not reading.  Fixture tests flip
#: these to seed torn-attach / dead-ring-peer violations.
SHM_RULES: Dict[str, Any] = {
    # the hub's Z reply (offer or decline) travels on the SOCKET — a hub
    # that jumps to the ring before replying strands the client, which
    # is still parked in recv() on TCP
    "reply_before_switch": True,
    # the hub switches to the ring only after the client's positive
    # confirm frame — an offer the client failed to mmap must leave the
    # hub serving TCP
    "switch_requires_confirm": True,
    # a declined attach (hub not shm-capable / no shm_dir) leaves both
    # ends on TCP, byte-identical to a legacy session
    "decline_keeps_tcp": True,
    # a client-side mmap failure aborts the attach (confirm=0); both
    # ends stay on TCP
    "abort_keeps_tcp": True,
    # a legacy hub drops the connection on the unknown Z byte; the
    # client treats that exactly like a decline and redials plain TCP
    "legacy_close_is_decline": True,
    # closing/severing either attached end marks BOTH rings closed so a
    # peer parked in the busy-then-wait read loop wakes and errors out
    # instead of spinning against a dead producer forever
    "sever_wakes_ring_peer": True,
}


# -- static cross-check --------------------------------------------------------

def _handler_fn(ps_src: SourceFile,
                name: str = "_handle_connection") -> Optional[ast.FunctionDef]:
    for node in ast.walk(ps_src.tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def admitted_actions(ps_src: SourceFile) -> Dict[str, int]:
    """``ACTION_*`` names the Python hub's dispatch compares the incoming
    action byte against (``action == net.ACTION_X`` / ``action in
    (...)``), with the comparison line."""
    out: Dict[str, int] = {}
    fn = _handler_fn(ps_src)
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        names = [n for n in ast.walk(node.left)
                 if isinstance(n, ast.Name)]
        if not any(n.id == "action" for n in names):
            continue
        for comp in node.comparators:
            for sub in ast.walk(comp):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("ACTION_"):
                    out.setdefault(sub.attr, sub.lineno)
                elif isinstance(sub, ast.Name) \
                        and sub.id.startswith("ACTION_"):
                    out.setdefault(sub.id, sub.lineno)
    return out


def handler_mentions(ps_src: SourceFile) -> Set[str]:
    """Every name/attribute token in the handler body — the vocabulary
    the reply-production check matches producers against."""
    fn = _handler_fn(ps_src)
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out


def check_model_vs_dispatch(net_src: SourceFile, ps_src: SourceFile,
                            root: str,
                            requests: Optional[Dict[str, Optional[str]]]
                            = None) -> List[Finding]:
    requests = dict(REQUESTS if requests is None else requests)
    findings: List[Finding] = []
    registry = parse_action_registry(net_src)
    admitted = admitted_actions(ps_src)
    mentions = handler_mentions(ps_src)
    ps_rel = rel(ps_src.path, root)
    net_rel = rel(net_src.path, root)

    for name in sorted(requests):
        if name not in registry:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"model key {name} is not a registered ACTION_* in "
                f"{net_rel} — a typo'd key never matches anything"))
    for name, line in sorted(admitted.items()):
        if name in registry and name not in requests:
            findings.append(Finding(
                "protocol", ps_rel, line,
                f"{name} is admitted by the hub dispatch but not declared "
                f"in protocol_model.REQUESTS — admitted-but-unmodeled "
                f"protocol drift"))
    for name in sorted(requests):
        if name in registry and name not in admitted:
            b, line = registry[name]
            findings.append(Finding(
                "protocol", net_rel, line,
                f"{name} (byte '{b}') is modeled as a client request but "
                f"the Python hub dispatch never admits it — "
                f"modeled-but-unhandled"))
    for name, reply in sorted(requests.items()):
        if reply is None:
            continue
        producers = REPLY_PRODUCERS.get(reply, (reply,))
        if not any(tok in mentions for tok in producers):
            findings.append(Finding(
                "protocol", ps_rel, 1,
                f"model declares reply {reply} for {name} but the handler "
                f"body references none of {sorted(producers)} — "
                f"modeled-but-unproduced"))
    modeled = set(requests) | {r for r in requests.values() if r}
    for name, (b, line) in sorted(registry.items()):
        if name not in modeled:
            findings.append(Finding(
                "protocol", net_rel, line,
                f"registered action {name} (byte '{b}') appears nowhere in "
                f"the protocol model — declare it as a request or reply in "
                f"protocol_model.REQUESTS"))
    return findings


# -- bounded exhaustive exploration: 2 clients x hub ---------------------------

#: the per-client action alphabet the session exploration draws scripts
#: from — the request/reply core (T/G handshakes and the R handoff leave
#: the regime and are covered by the standby model / static checks)
SESSION_ALPHABET = ("ACTION_PULL", "ACTION_COMMIT", "ACTION_HEALTH",
                    "ACTION_PING", "ACTION_BYE")


def explore_sessions(requests: Optional[Dict[str, Optional[str]]] = None,
                     hub_replies: Optional[Dict[str, Optional[str]]] = None,
                     max_sends: int = 3, max_inflight: int = 2,
                     clients: int = 2, clock_cap: int = 6,
                     alphabet: Sequence[str] = SESSION_ALPHABET
                     ) -> List[Finding]:
    """Exhaustively interleave every bounded client script against the
    hub.  ``requests`` is what CLIENTS expect (the model); ``hub_replies``
    is what the hub produces (defaults to the same table — fixtures pass
    a skewed or arm-missing table to seed desync/deadlock).

    Client state: (sends left, expected-reply FIFO, closed).  Events:
    a client sends any alphabet action (pipelined up to ``max_inflight``),
    the hub serves a client's oldest queued request (atomic:
    reply enqueued, clock bumped), a client consumes its oldest reply.
    """
    requests = dict(REQUESTS if requests is None else requests)
    hub = dict(requests if hub_replies is None else hub_replies)
    findings: List[Finding] = []

    # state: (clock, per-client (sends_left, reqq, replyq, expq, closed))
    init_client = (max_sends, (), (), (), False)
    init = (0, tuple(init_client for _ in range(clients)))
    seen = {init}
    frontier: List[Tuple[Any, Tuple[str, ...]]] = [(init, ())]
    while frontier:
        (clock, cls), trace = frontier.pop()
        moved = False
        done = all(c[4] or (c[0] == 0 and not c[1] and not c[2] and not c[3])
                   for c in cls)
        for ci, (left, reqq, replyq, expq, closed) in enumerate(cls):
            # client sends (branch over the whole alphabet)
            if not closed and left > 0 and len(expq) < max_inflight:
                for act in alphabet:
                    if act not in requests:
                        continue
                    exp = requests[act]
                    nc = (left - 1, reqq + (act,), replyq,
                          expq + ((exp,) if exp is not None else ()),
                          closed or act == "ACTION_BYE")
                    _push(seen, frontier, clock, cls, ci, nc,
                          trace + (f"c{ci} sends {act}",))
                moved = True
            # hub serves the oldest queued request
            if reqq:
                act = reqq[0]
                if act in hub:
                    reply = hub[act]
                    nclock = min(clock_cap, clock + 1) \
                        if act in CLOCK_BUMPERS else clock
                    nc = (left, reqq[1:],
                          replyq + ((reply,) if reply is not None else ()),
                          expq, closed)
                    _push(seen, frontier, nclock, cls, ci, nc,
                          trace + (f"hub serves c{ci} {act}",))
                    moved = True
                # an arm the hub lacks: the request sits unserved forever
                # (surfaces below as a deadlock when nothing else moves)
            # client consumes the oldest reply
            if replyq:
                got = replyq[0]
                if not expq:
                    findings.append(_session_finding(
                        f"client {ci} received {got} with no request "
                        f"outstanding", trace))
                    moved = True  # diagnosed, not deadlocked
                    continue
                want = expq[0]
                if got != want:
                    findings.append(_session_finding(
                        f"desync: client {ci} expected {want} for its "
                        f"oldest request but the hub produced {got}",
                        trace + (f"c{ci} recv {got}",)))
                    moved = True  # diagnosed, not deadlocked
                    continue
                nc = (left, reqq, replyq[1:], expq[1:], closed)
                _push(seen, frontier, clock, cls, ci, nc,
                      trace + (f"c{ci} recv {got}",))
                moved = True
        if not moved and not done:
            findings.append(_session_finding(
                "deadlock: no event enabled but clients still have "
                "unserved requests or unmatched replies", trace))
        if len(findings) >= 8:
            break  # enough counterexamples; keep the report readable
    return findings


def _push(seen, frontier, clock, cls, ci, nc, trace) -> None:
    state = (clock, cls[:ci] + (nc,) + cls[ci + 1:])
    if state not in seen:
        seen.add(state)
        frontier.append((state, trace))


def _session_finding(msg: str, trace: Tuple[str, ...]) -> Finding:
    tail = " -> ".join(trace[-6:])
    return Finding("protocol", SELF_PATH, 1,
                   f"{msg} (trace: {tail})")


# -- bounded exploration: standby / promotion ----------------------------------

def explore_standby(rules: Optional[Dict[str, Any]] = None,
                    retries: int = 2, max_commits: int = 3
                    ) -> List[Finding]:
    """Exhaustive walk of the standby lifecycle: R sync-then-delta feed
    (dense AND row-sparse frames, per the standby's attach-time
    capability), feed loss + bounded retries, worker commits racing all
    of it.  Checks promotion reachability, the acked-while-standby
    invariant, the sparse-frame-capability invariant (a legacy standby
    is never sent a REPL_SPARSE frame — ISSUE 15's never-a-torn-stream
    rule), and deadlock freedom.  Both capability generations are
    explored."""
    rules = dict(STANDBY_RULES if rules is None else rules)
    findings: List[Finding] = []
    for sparse_cap in (False, True):
        findings.extend(_explore_standby_cap(rules, sparse_cap, retries,
                                             max_commits))
        if len(findings) >= 8:
            break
    return findings


def _explore_standby_cap(rules: Dict[str, Any], sparse_cap: bool,
                         retries: int, max_commits: int) -> List[Finding]:
    findings: List[Finding] = []
    # state: (synced, feed_up, failures, promoted, commits_left);
    # sparse_cap is attach-time immutable, so it parameterizes the walk
    init = (False, True, 0, False, max_commits)
    seen = {init}
    frontier: List[Tuple[Tuple, Tuple[str, ...]]] = [(init, ())]
    promoted_reachable = False
    while frontier:
        state, trace = frontier.pop()
        synced, feed_up, failures, promoted, commits_left = state
        if promoted:
            promoted_reachable = True
        events: List[Tuple[str, Tuple, Optional[bool]]] = []
        if feed_up and not promoted:
            if rules["sync_sets_synced"]:
                events.append(("feed_sync",
                               (True, feed_up, 0, promoted, commits_left),
                               None))
            else:
                events.append(("feed_sync", state, None))
            if synced:
                events.append(("feed_delta", state, None))
                # the primary frames a row-sparse commit REPL_SPARSE only
                # toward capable replicas; with the rule intact the event
                # is simply not enabled for a legacy standby (it receives
                # the densified REPL_DELTA above instead)
                if sparse_cap or not rules["sparse_delta_requires_cap"]:
                    events.append(("feed_sparse_delta", state, None))
            events.append(("feed_loss",
                           (synced, False, failures, promoted, commits_left),
                           None))
        if not feed_up and not promoted:
            if failures <= retries:
                events.append(("feed_retry_fail",
                               (synced, False, failures + 1, promoted,
                                commits_left), None))
            else:
                promote = (synced and rules["loss_exhaustion_promotes"]) \
                    or (not synced and rules["never_synced_promotes"])
                if promote:
                    events.append(("promote_on_loss",
                                   (synced, False, failures, True,
                                    commits_left), None))
                else:
                    # never-synced standby keeps retrying forever (capped
                    # backoff) — model as a self-loop retry
                    events.append(("feed_retry_fail", state, None))
            events.append(("feed_reconnect",
                           (synced, True, failures, promoted, commits_left),
                           None))
        if commits_left > 0:
            if not synced and not promoted:
                events.append(("commit_refused_unsynced", state, False))
            elif promoted:
                events.append(("commit_acked",
                               (synced, feed_up, failures, promoted,
                                commits_left - 1), True))
            elif feed_up and rules["commit_probe_severs"]:
                events.append(("commit_refused_probe",
                               (synced, False, failures, promoted,
                                commits_left), False))
            elif rules["commit_promotes"]:
                events.append(("commit_acked_after_promote",
                               (synced, feed_up, failures, True,
                                commits_left - 1), True))
            else:
                events.append(("commit_acked",
                               (synced, feed_up, failures, promoted,
                                commits_left - 1), True))
        if promoted and commits_left == 0:
            continue  # final: promoted, every commit served
        if not events:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"standby deadlock: no event enabled in state "
                f"synced={synced} feed_up={feed_up} promoted={promoted} "
                f"(trace: {' -> '.join(trace[-6:])})"))
            continue
        for name, nstate, acked in events:
            if acked and rules["ack_requires_promoted"] and not nstate[3]:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"acked-commit-while-standby: event {name} acks a "
                    f"commit but the hub is neither primary nor promoted "
                    f"(trace: {' -> '.join(trace[-5:] + (name,))})"))
                continue
            if name == "feed_sparse_delta" and not sparse_cap:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"sparse-frame-to-legacy-standby: a REPL_SPARSE frame "
                    f"reaches a standby that never announced "
                    f"REPL_CAP_SPARSE — a torn stream on the dense-R "
                    f"fallback path "
                    f"(trace: {' -> '.join(trace[-5:] + (name,))})"))
                continue
            if nstate not in seen:
                seen.add(nstate)
                frontier.append((nstate, trace + (name,)))
        if len(findings) >= 8:
            return findings
    if not promoted_reachable:
        findings.append(Finding(
            "protocol", SELF_PATH, 1,
            "unreachable-promotion: no interleaving of feed "
            "sync/loss/retry and worker commits ever promotes the "
            "standby — failover is impossible under these rules"))
    return findings


# -- bounded exploration: shm attach / decline / detach ------------------------

def explore_shm(rules: Optional[Dict[str, Any]] = None) -> List[Finding]:
    """Exhaustive walk of the shm attach handshake (ISSUE 18) across all
    three hub generations — shm-capable, capable-but-declining, and
    legacy (drops the unknown ``Z`` byte) — and both client mmap
    outcomes.  Checks:

    - **torn-attach**: after the handshake settles, a data exchange with
      the two ends on different transports (one writing the ring the
      other never reads, or writing a socket the other abandoned);
    - **stranded-reply**: the hub's offer/decline sent on the ring
      before the client mapped it (the client is parked in TCP recv);
    - **dead-ring-peer**: a severed attached session whose surviving
      end never wakes from the ring park loop;
    - deadlock freedom: every explored path reaches a settled state.
    """
    rules = dict(SHM_RULES if rules is None else rules)
    findings: List[Finding] = []
    for hub_gen in ("capable", "declining", "legacy"):
        findings.extend(_explore_shm_gen(rules, hub_gen))
        if len(findings) >= 8:
            break
    return findings


def _explore_shm_gen(rules: Dict[str, Any], hub_gen: str) -> List[Finding]:
    findings: List[Finding] = []
    # state: (phase, client_tr, hub_tr); transports are "tcp" | "shm";
    # phases walk idle -> requested -> offered -> confirmed -> settled
    # (decline/abort/legacy-close settle early).  hub_gen is immutable
    # per walk, so it parameterizes the exploration like sparse_cap does
    # for the standby machine.
    init = ("idle", "tcp", "tcp")
    seen = {init}
    frontier: List[Tuple[Tuple[str, str, str], Tuple[str, ...]]] = [(init, ())]
    settled_reachable = False
    while frontier:
        state, trace = frontier.pop()
        phase, client_tr, hub_tr = state
        events: List[Tuple[str, Tuple[str, str, str]]] = []
        if phase == "idle":
            events.append(("client_sends_Z", ("requested", client_tr, hub_tr)))
        elif phase == "requested":
            if hub_gen == "capable":
                # a hub violating reply_before_switch moves to the ring
                # BEFORE its offer frame leaves — the offer then travels
                # on a ring the client has not mapped
                offer_hub_tr = hub_tr if rules["reply_before_switch"] else "shm"
                if offer_hub_tr == "shm" and client_tr == "tcp":
                    findings.append(Finding(
                        "protocol", SELF_PATH, 1,
                        f"stranded-reply: the hub's Z offer is sent on the "
                        f"ring before the client mapped it — the client is "
                        f"parked in TCP recv forever "
                        f"(trace: {' -> '.join(trace + ('hub_offers',))})"))
                else:
                    # a hub violating switch_requires_confirm flips to
                    # the ring at offer time instead of waiting for the
                    # client's mapped-confirm
                    post = offer_hub_tr if rules["switch_requires_confirm"] \
                        else "shm"
                    events.append(("hub_offers", ("offered", client_tr, post)))
            elif hub_gen == "declining":
                post = "tcp" if rules["decline_keeps_tcp"] else "shm"
                events.append(("hub_declines", ("settled", client_tr, post)))
            else:  # legacy: unknown action byte -> connection dropped
                if rules["legacy_close_is_decline"]:
                    events.append(("client_redials_tcp",
                                   ("settled", "tcp", "tcp")))
                else:
                    findings.append(Finding(
                        "protocol", SELF_PATH, 1,
                        f"torn-attach: a legacy hub dropped the Z request "
                        f"and the client neither redials nor degrades — "
                        f"the session is dead "
                        f"(trace: {' -> '.join(trace + ('legacy_close',))})"))
        elif phase == "offered":
            # client maps the rings and sends confirm=1, then moves to
            # the ring itself (it sends nothing further on the socket)
            events.append(("client_mmap_ok", ("confirmed", "shm", hub_tr)))
            abort_hub_tr = "tcp" if rules["abort_keeps_tcp"] else "shm"
            events.append(("client_mmap_fail",
                           ("settled", "tcp",
                            abort_hub_tr if rules["switch_requires_confirm"]
                            else hub_tr)))
        elif phase == "confirmed":
            # the hub consumes the confirm frame (FIFO: it is the last
            # TCP frame this client ever sends) and switches
            post = "shm" if rules["switch_requires_confirm"] else hub_tr
            events.append(("hub_receives_confirm", ("settled", client_tr,
                                                    post)))
        elif phase == "settled":
            settled_reachable = True
            if client_tr != hub_tr:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"torn-attach: handshake settled with client on "
                    f"{client_tr} and hub on {hub_tr} — every subsequent "
                    f"frame is written to a transport the peer never reads "
                    f"(trace: {' -> '.join(trace)})"))
                continue
            if client_tr == "shm":
                # detach: either end severs; the ring closed flags must
                # wake the surviving end's park loop
                if not rules["sever_wakes_ring_peer"]:
                    findings.append(Finding(
                        "protocol", SELF_PATH, 1,
                        f"dead-ring-peer: an attached end died but the "
                        f"surviving peer's ring park loop is never woken "
                        f"(no closed-flag publication) "
                        f"(trace: {' -> '.join(trace + ('peer_severs',))})"))
            continue  # settled states are final
        if not events and phase != "settled" and not findings:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"shm-attach deadlock: no event enabled in phase {phase} "
                f"under hub generation {hub_gen} "
                f"(trace: {' -> '.join(trace[-6:])})"))
        for name, nstate in events:
            if nstate not in seen:
                seen.add(nstate)
                frontier.append((nstate, trace + (name,)))
        if len(findings) >= 8:
            return findings
    if not settled_reachable and not findings:
        findings.append(Finding(
            "protocol", SELF_PATH, 1,
            f"shm-attach unreachable-settle: no interleaving under hub "
            f"generation {hub_gen} ever settles the handshake"))
    return findings


# -- bounded exploration: fleet join / drain / admission -----------------------

def explore_fleet(rules: Optional[Dict[str, Any]] = None,
                  max_commits: int = 2) -> List[Finding]:
    """Exhaustive walk of one job-scoped session's lifecycle against the
    hub + controller (ISSUE 19): T announce -> admission verdict ->
    attach -> pipelined commits -> preemption notice -> drain -> BYE ->
    detach, plus the respawned-replacement generation.  Checks:

    - **admission-races-attach**: a pull/commit served before the
      admission verdict settles;
    - **post-reject-served**: a rejected session later served;
    - **acked-commit-loss**: BYE leaves the worker while commits are
      still in flight — the drain discards work the client believes
      (or will believe) acked;
    - **retire-before-drain**: the controller detaches a worker whose
      drain has not completed;
    - **respawn-blind-commit**: a respawned replacement commits before
      pulling the current center;
    - deadlock freedom: every explored path reaches a final state.

    Both generations (fresh join, post-preemption respawn) are explored;
    the respawn generation differs only in that its blind-commit
    temptation is real (it holds its predecessor's stale weights).
    """
    rules = dict(FLEET_RULES if rules is None else rules)
    findings: List[Finding] = []
    for respawn in (False, True):
        findings.extend(_explore_fleet_gen(rules, respawn, max_commits))
        if len(findings) >= 8:
            break
    return findings


def _explore_fleet_gen(rules: Dict[str, Any], respawn: bool,
                       max_commits: int) -> List[Finding]:
    findings: List[Finding] = []
    # state: (phase, inflight, pulled, commits_left, draining);
    # phases walk announced -> {admitted, rejected}; admitted ->
    # active -> (drain) -> detached; rejected -> closed.  ``respawn``
    # is immutable per walk (it parameterizes the generation the same
    # way sparse_cap does for the standby machine).
    init = ("announced", 0, False, max_commits, False)
    seen = {init}
    frontier: List[Tuple[Tuple, Tuple[str, ...]]] = [(init, ())]
    detached_reachable = False
    while frontier:
        state, trace = frontier.pop()
        phase, inflight, pulled, commits_left, draining = state
        events: List[Tuple[str, Tuple]] = []
        if phase == "announced":
            events.append(("hub_admits",
                           ("admitted", inflight, pulled, commits_left,
                            draining)))
            events.append(("hub_rejects",
                           ("rejected", inflight, pulled, commits_left,
                            draining)))
            if not rules["admission_before_attach"]:
                # a hub that attaches before the verdict settles serves
                # a pull against a center the job may be refused
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"admission-races-attach: a pull is served while the "
                    f"admission verdict is still pending — a "
                    f"to-be-rejected job observed center state "
                    f"(trace: {' -> '.join(trace + ('serve_before_verdict',))})"))
        elif phase == "rejected":
            if rules["reject_never_serves"]:
                events.append(("reject_refused_close",
                               ("closed", 0, pulled, 0, draining)))
            else:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"post-reject-served: a commit from a REJECTED session "
                    f"is applied to the center — admission control is "
                    f"advisory only "
                    f"(trace: {' -> '.join(trace + ('serve_after_reject',))})"))
        elif phase == "admitted":
            events.append(("first_pull",
                           ("active", inflight, True, commits_left,
                            draining)))
            if respawn and not rules["respawn_pulls_current_center"]:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"respawn-blind-commit: a respawned replacement "
                    f"commits a delta computed against its predecessor's "
                    f"stale weights — it must pull the current center "
                    f"first "
                    f"(trace: {' -> '.join(trace + ('commit_blind',))})"))
        elif phase == "active":
            if commits_left > 0 and inflight < 2 and not draining:
                events.append(("commit_sent",
                               ("active", inflight + 1, pulled,
                                commits_left - 1, draining)))
            if inflight > 0:
                events.append(("commit_acked",
                               ("active", inflight - 1, pulled,
                                commits_left, draining)))
            if not draining:
                events.append(("preemption_notice",
                               ("active", inflight, pulled, commits_left,
                                True)))
            if draining:
                if inflight == 0 or not rules["drain_completes_inflight"]:
                    if inflight > 0:
                        findings.append(Finding(
                            "protocol", SELF_PATH, 1,
                            f"acked-commit-loss: BYE leaves the worker "
                            f"with {inflight} commit(s) still in flight — "
                            f"the drain discards work the hub may ack "
                            f"into a torn session "
                            f"(trace: "
                            f"{' -> '.join(trace + ('bye_with_inflight',))})"))
                    else:
                        events.append(("drained_bye",
                                       ("detached", 0, pulled, 0, True)))
                if not rules["retire_after_drain_only"] and inflight > 0:
                    findings.append(Finding(
                        "protocol", SELF_PATH, 1,
                        f"retire-before-drain: the controller detaches a "
                        f"worker whose in-flight commit was never acked — "
                        f"membership shrinks mid-commit "
                        f"(trace: "
                        f"{' -> '.join(trace + ('force_detach',))})"))
        elif phase in ("detached", "closed"):
            detached_reachable = True
            continue  # final
        if not events and phase not in ("detached", "closed") \
                and not findings:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"fleet deadlock: no event enabled in phase {phase} "
                f"(inflight={inflight}, draining={draining}) "
                f"(trace: {' -> '.join(trace[-6:])})"))
        for name, nstate in events:
            if nstate not in seen:
                seen.add(nstate)
                frontier.append((nstate, trace + (name,)))
        if len(findings) >= 8:
            return findings
    if not detached_reachable and not findings:
        findings.append(Finding(
            "protocol", SELF_PATH, 1,
            f"fleet unreachable-detach: no interleaving (respawn="
            f"{respawn}) ever completes the join/drain lifecycle"))
    return findings


# -- the pass ------------------------------------------------------------------

def check(net_src: SourceFile, ps_src: SourceFile, root: str,
          sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    findings = check_model_vs_dispatch(net_src, ps_src, root)
    # the exhaustive explorations are cheap (bounded, memoized) and run
    # in the static gate — a model edit that desyncs or deadlocks fails
    # the same run that introduced it
    findings.extend(explore_sessions())
    findings.extend(explore_standby())
    findings.extend(explore_shm())
    findings.extend(explore_fleet())
    return apply_annotations(findings, sources or {}, root, rule="protocol")


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    net_path = os.path.join(root, "distkeras_tpu", "runtime", "networking.py")
    ps_path = os.path.join(root, "distkeras_tpu", "runtime",
                           "parameter_server.py")
    if not (os.path.exists(net_path) and os.path.exists(ps_path)):
        return []  # partial checkout; the repo gate runs on the real tree
    if sources is None:
        sources = load_sources(python_files(
            root, (os.path.join("distkeras_tpu", "runtime"),)))
    net_src = sources.get(net_path) or SourceFile(net_path)
    ps_src = sources.get(ps_path) or SourceFile(ps_path)
    return check(net_src, ps_src, root, sources)
