"""Wire-protocol model checker (pass 6 of ``distkeras-lint``) — ISSUE 14.

The PS wire protocol is a set of client<->hub action sequences that PR
12's parity pass only checks for *existence* (every byte handled
somewhere).  This pass adds a declared **transition model** and checks
it two ways:

1. **Static cross-check** against the Python hub's dispatch
   (``SocketParameterServer._handle_connection``):

   - an action byte the hub *admits* (compares against ``action``) that
     the model does not declare is *admitted-but-unmodeled* — the model
     is the contract, so undeclared arms are protocol drift;
   - a modeled request the hub does not admit is
     *modeled-but-unhandled* — a client following the contract would
     desync the stream;
   - a modeled reply the handler provably never produces (neither the
     ``ACTION_*`` constant nor its known encoder appears in the handler
     body) is *modeled-but-unproduced*;
   - model keys must be registered ``ACTION_*`` names (a typo'd key can
     never match and would silently weaken the contract).

2. **Bounded exhaustive exploration** of 2-client x hub interleavings
   (:func:`explore_sessions`): every interleaving of every bounded
   action script, with pipelining up to ``max_inflight``, checking

   - **desync**: a reply kind that does not match the oldest
     outstanding request's declared reply;
   - **deadlock**: a reachable non-final state with no enabled event;

   and of the standby/promotion state machine
   (:func:`explore_standby`): sync-then-delta ``R`` feed, feed loss,
   retry budget, commit-triggered promotion — checking that promotion
   is **reachable**, that no commit is ever acked by an unpromoted
   standby, and that the machine cannot deadlock.

The model is data (:data:`REQUESTS`, :data:`STANDBY_RULES`) so fixture
tests can seed violations; the shipped tables are the contract the real
hubs are checked against.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)
from distkeras_tpu.analysis.wire_parity import parse_action_registry

SELF_PATH = "distkeras_tpu/analysis/protocol_model.py"

#: The declared protocol: client-initiated action -> the reply kind the
#: client must receive (None = no reply, connection closes).  ``R`` is
#: the replica hello: the hub replies with an ``R`` sync frame and the
#: connection leaves the request/reply regime (handoff to the feed).
REQUESTS: Dict[str, Optional[str]] = {
    "ACTION_TRACE": "ACTION_TRACE",
    "ACTION_PULL": "ACTION_WEIGHTS",
    "ACTION_SPARSE_PULL": "ACTION_SPARSE_WEIGHTS",
    "ACTION_COMMIT": "ACTION_ACK",
    "ACTION_QCOMMIT": "ACTION_ACK",
    "ACTION_SPARSE_COMMIT": "ACTION_ACK",
    "ACTION_SPARSE_QCOMMIT": "ACTION_ACK",
    "ACTION_HEALTH": "ACTION_ACK",
    "ACTION_PING": "ACTION_ACK",
    "ACTION_RECONNECT": "ACTION_RETRY",
    "ACTION_BYE": None,
    "ACTION_REPL": "ACTION_REPL",
}

#: Actions that advance the hub's commit clock when served.
CLOCK_BUMPERS: FrozenSet[str] = frozenset({
    "ACTION_COMMIT", "ACTION_QCOMMIT",
    "ACTION_SPARSE_COMMIT", "ACTION_SPARSE_QCOMMIT"})

#: How the handler source proves it PRODUCES each reply kind: any of the
#: listed tokens (an ``ACTION_*`` constant reference, an encoder helper,
#: the feed class that owns the ``R`` stream) appearing in the handler
#: body counts.
REPLY_PRODUCERS: Dict[str, Tuple[str, ...]] = {
    "ACTION_WEIGHTS": ("ACTION_WEIGHTS",),
    "ACTION_ACK": ("ACTION_ACK",),
    "ACTION_SPARSE_WEIGHTS": ("ACTION_SPARSE_WEIGHTS",),
    "ACTION_TRACE": ("encode_time_payload",),
    "ACTION_RETRY": ("encode_retry_payload",),
    "ACTION_REPL": ("ReplicationFeed", "attach"),
}

#: The standby/promotion contract (ISSUE 7 semantics) as checkable
#: flags — fixture tests flip these to seed violations.
STANDBY_RULES: Dict[str, Any] = {
    # a full R sync is what arms the standby with real job state
    "sync_sets_synced": True,
    # a commit landing while the feed is DOWN (primary presumed dead)
    # promotes the standby before the commit is applied/acked
    "commit_promotes": True,
    # a commit while the feed is still UP is refused and severs the feed
    # as a liveness probe (split-brain guard)
    "commit_probe_severs": True,
    # a never-synced standby must never promote (it holds seed weights)
    "never_synced_promotes": False,
    # feed-loss retries exhausted on a synced standby promote it
    "loss_exhaustion_promotes": True,
    # an ack may only leave a standby AFTER promotion
    "ack_requires_promoted": True,
    # a REPL_SPARSE row-delta frame may only be sent to a standby whose
    # hello announced REPL_CAP_SPARSE (attach-time capability, ISSUE 15);
    # a legacy standby keeps receiving the dense-materialized delta
    # stream — never a frame kind it cannot parse (a torn stream)
    "sparse_delta_requires_cap": True,
}


# -- static cross-check --------------------------------------------------------

def _handler_fn(ps_src: SourceFile,
                name: str = "_handle_connection") -> Optional[ast.FunctionDef]:
    for node in ast.walk(ps_src.tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def admitted_actions(ps_src: SourceFile) -> Dict[str, int]:
    """``ACTION_*`` names the Python hub's dispatch compares the incoming
    action byte against (``action == net.ACTION_X`` / ``action in
    (...)``), with the comparison line."""
    out: Dict[str, int] = {}
    fn = _handler_fn(ps_src)
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        names = [n for n in ast.walk(node.left)
                 if isinstance(n, ast.Name)]
        if not any(n.id == "action" for n in names):
            continue
        for comp in node.comparators:
            for sub in ast.walk(comp):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("ACTION_"):
                    out.setdefault(sub.attr, sub.lineno)
                elif isinstance(sub, ast.Name) \
                        and sub.id.startswith("ACTION_"):
                    out.setdefault(sub.id, sub.lineno)
    return out


def handler_mentions(ps_src: SourceFile) -> Set[str]:
    """Every name/attribute token in the handler body — the vocabulary
    the reply-production check matches producers against."""
    fn = _handler_fn(ps_src)
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out


def check_model_vs_dispatch(net_src: SourceFile, ps_src: SourceFile,
                            root: str,
                            requests: Optional[Dict[str, Optional[str]]]
                            = None) -> List[Finding]:
    requests = dict(REQUESTS if requests is None else requests)
    findings: List[Finding] = []
    registry = parse_action_registry(net_src)
    admitted = admitted_actions(ps_src)
    mentions = handler_mentions(ps_src)
    ps_rel = rel(ps_src.path, root)
    net_rel = rel(net_src.path, root)

    for name in sorted(requests):
        if name not in registry:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"model key {name} is not a registered ACTION_* in "
                f"{net_rel} — a typo'd key never matches anything"))
    for name, line in sorted(admitted.items()):
        if name in registry and name not in requests:
            findings.append(Finding(
                "protocol", ps_rel, line,
                f"{name} is admitted by the hub dispatch but not declared "
                f"in protocol_model.REQUESTS — admitted-but-unmodeled "
                f"protocol drift"))
    for name in sorted(requests):
        if name in registry and name not in admitted:
            b, line = registry[name]
            findings.append(Finding(
                "protocol", net_rel, line,
                f"{name} (byte '{b}') is modeled as a client request but "
                f"the Python hub dispatch never admits it — "
                f"modeled-but-unhandled"))
    for name, reply in sorted(requests.items()):
        if reply is None:
            continue
        producers = REPLY_PRODUCERS.get(reply, (reply,))
        if not any(tok in mentions for tok in producers):
            findings.append(Finding(
                "protocol", ps_rel, 1,
                f"model declares reply {reply} for {name} but the handler "
                f"body references none of {sorted(producers)} — "
                f"modeled-but-unproduced"))
    modeled = set(requests) | {r for r in requests.values() if r}
    for name, (b, line) in sorted(registry.items()):
        if name not in modeled:
            findings.append(Finding(
                "protocol", net_rel, line,
                f"registered action {name} (byte '{b}') appears nowhere in "
                f"the protocol model — declare it as a request or reply in "
                f"protocol_model.REQUESTS"))
    return findings


# -- bounded exhaustive exploration: 2 clients x hub ---------------------------

#: the per-client action alphabet the session exploration draws scripts
#: from — the request/reply core (T/G handshakes and the R handoff leave
#: the regime and are covered by the standby model / static checks)
SESSION_ALPHABET = ("ACTION_PULL", "ACTION_COMMIT", "ACTION_HEALTH",
                    "ACTION_PING", "ACTION_BYE")


def explore_sessions(requests: Optional[Dict[str, Optional[str]]] = None,
                     hub_replies: Optional[Dict[str, Optional[str]]] = None,
                     max_sends: int = 3, max_inflight: int = 2,
                     clients: int = 2, clock_cap: int = 6,
                     alphabet: Sequence[str] = SESSION_ALPHABET
                     ) -> List[Finding]:
    """Exhaustively interleave every bounded client script against the
    hub.  ``requests`` is what CLIENTS expect (the model); ``hub_replies``
    is what the hub produces (defaults to the same table — fixtures pass
    a skewed or arm-missing table to seed desync/deadlock).

    Client state: (sends left, expected-reply FIFO, closed).  Events:
    a client sends any alphabet action (pipelined up to ``max_inflight``),
    the hub serves a client's oldest queued request (atomic:
    reply enqueued, clock bumped), a client consumes its oldest reply.
    """
    requests = dict(REQUESTS if requests is None else requests)
    hub = dict(requests if hub_replies is None else hub_replies)
    findings: List[Finding] = []

    # state: (clock, per-client (sends_left, reqq, replyq, expq, closed))
    init_client = (max_sends, (), (), (), False)
    init = (0, tuple(init_client for _ in range(clients)))
    seen = {init}
    frontier: List[Tuple[Any, Tuple[str, ...]]] = [(init, ())]
    while frontier:
        (clock, cls), trace = frontier.pop()
        moved = False
        done = all(c[4] or (c[0] == 0 and not c[1] and not c[2] and not c[3])
                   for c in cls)
        for ci, (left, reqq, replyq, expq, closed) in enumerate(cls):
            # client sends (branch over the whole alphabet)
            if not closed and left > 0 and len(expq) < max_inflight:
                for act in alphabet:
                    if act not in requests:
                        continue
                    exp = requests[act]
                    nc = (left - 1, reqq + (act,), replyq,
                          expq + ((exp,) if exp is not None else ()),
                          closed or act == "ACTION_BYE")
                    _push(seen, frontier, clock, cls, ci, nc,
                          trace + (f"c{ci} sends {act}",))
                moved = True
            # hub serves the oldest queued request
            if reqq:
                act = reqq[0]
                if act in hub:
                    reply = hub[act]
                    nclock = min(clock_cap, clock + 1) \
                        if act in CLOCK_BUMPERS else clock
                    nc = (left, reqq[1:],
                          replyq + ((reply,) if reply is not None else ()),
                          expq, closed)
                    _push(seen, frontier, nclock, cls, ci, nc,
                          trace + (f"hub serves c{ci} {act}",))
                    moved = True
                # an arm the hub lacks: the request sits unserved forever
                # (surfaces below as a deadlock when nothing else moves)
            # client consumes the oldest reply
            if replyq:
                got = replyq[0]
                if not expq:
                    findings.append(_session_finding(
                        f"client {ci} received {got} with no request "
                        f"outstanding", trace))
                    moved = True  # diagnosed, not deadlocked
                    continue
                want = expq[0]
                if got != want:
                    findings.append(_session_finding(
                        f"desync: client {ci} expected {want} for its "
                        f"oldest request but the hub produced {got}",
                        trace + (f"c{ci} recv {got}",)))
                    moved = True  # diagnosed, not deadlocked
                    continue
                nc = (left, reqq, replyq[1:], expq[1:], closed)
                _push(seen, frontier, clock, cls, ci, nc,
                      trace + (f"c{ci} recv {got}",))
                moved = True
        if not moved and not done:
            findings.append(_session_finding(
                "deadlock: no event enabled but clients still have "
                "unserved requests or unmatched replies", trace))
        if len(findings) >= 8:
            break  # enough counterexamples; keep the report readable
    return findings


def _push(seen, frontier, clock, cls, ci, nc, trace) -> None:
    state = (clock, cls[:ci] + (nc,) + cls[ci + 1:])
    if state not in seen:
        seen.add(state)
        frontier.append((state, trace))


def _session_finding(msg: str, trace: Tuple[str, ...]) -> Finding:
    tail = " -> ".join(trace[-6:])
    return Finding("protocol", SELF_PATH, 1,
                   f"{msg} (trace: {tail})")


# -- bounded exploration: standby / promotion ----------------------------------

def explore_standby(rules: Optional[Dict[str, Any]] = None,
                    retries: int = 2, max_commits: int = 3
                    ) -> List[Finding]:
    """Exhaustive walk of the standby lifecycle: R sync-then-delta feed
    (dense AND row-sparse frames, per the standby's attach-time
    capability), feed loss + bounded retries, worker commits racing all
    of it.  Checks promotion reachability, the acked-while-standby
    invariant, the sparse-frame-capability invariant (a legacy standby
    is never sent a REPL_SPARSE frame — ISSUE 15's never-a-torn-stream
    rule), and deadlock freedom.  Both capability generations are
    explored."""
    rules = dict(STANDBY_RULES if rules is None else rules)
    findings: List[Finding] = []
    for sparse_cap in (False, True):
        findings.extend(_explore_standby_cap(rules, sparse_cap, retries,
                                             max_commits))
        if len(findings) >= 8:
            break
    return findings


def _explore_standby_cap(rules: Dict[str, Any], sparse_cap: bool,
                         retries: int, max_commits: int) -> List[Finding]:
    findings: List[Finding] = []
    # state: (synced, feed_up, failures, promoted, commits_left);
    # sparse_cap is attach-time immutable, so it parameterizes the walk
    init = (False, True, 0, False, max_commits)
    seen = {init}
    frontier: List[Tuple[Tuple, Tuple[str, ...]]] = [(init, ())]
    promoted_reachable = False
    while frontier:
        state, trace = frontier.pop()
        synced, feed_up, failures, promoted, commits_left = state
        if promoted:
            promoted_reachable = True
        events: List[Tuple[str, Tuple, Optional[bool]]] = []
        if feed_up and not promoted:
            if rules["sync_sets_synced"]:
                events.append(("feed_sync",
                               (True, feed_up, 0, promoted, commits_left),
                               None))
            else:
                events.append(("feed_sync", state, None))
            if synced:
                events.append(("feed_delta", state, None))
                # the primary frames a row-sparse commit REPL_SPARSE only
                # toward capable replicas; with the rule intact the event
                # is simply not enabled for a legacy standby (it receives
                # the densified REPL_DELTA above instead)
                if sparse_cap or not rules["sparse_delta_requires_cap"]:
                    events.append(("feed_sparse_delta", state, None))
            events.append(("feed_loss",
                           (synced, False, failures, promoted, commits_left),
                           None))
        if not feed_up and not promoted:
            if failures <= retries:
                events.append(("feed_retry_fail",
                               (synced, False, failures + 1, promoted,
                                commits_left), None))
            else:
                promote = (synced and rules["loss_exhaustion_promotes"]) \
                    or (not synced and rules["never_synced_promotes"])
                if promote:
                    events.append(("promote_on_loss",
                                   (synced, False, failures, True,
                                    commits_left), None))
                else:
                    # never-synced standby keeps retrying forever (capped
                    # backoff) — model as a self-loop retry
                    events.append(("feed_retry_fail", state, None))
            events.append(("feed_reconnect",
                           (synced, True, failures, promoted, commits_left),
                           None))
        if commits_left > 0:
            if not synced and not promoted:
                events.append(("commit_refused_unsynced", state, False))
            elif promoted:
                events.append(("commit_acked",
                               (synced, feed_up, failures, promoted,
                                commits_left - 1), True))
            elif feed_up and rules["commit_probe_severs"]:
                events.append(("commit_refused_probe",
                               (synced, False, failures, promoted,
                                commits_left), False))
            elif rules["commit_promotes"]:
                events.append(("commit_acked_after_promote",
                               (synced, feed_up, failures, True,
                                commits_left - 1), True))
            else:
                events.append(("commit_acked",
                               (synced, feed_up, failures, promoted,
                                commits_left - 1), True))
        if promoted and commits_left == 0:
            continue  # final: promoted, every commit served
        if not events:
            findings.append(Finding(
                "protocol", SELF_PATH, 1,
                f"standby deadlock: no event enabled in state "
                f"synced={synced} feed_up={feed_up} promoted={promoted} "
                f"(trace: {' -> '.join(trace[-6:])})"))
            continue
        for name, nstate, acked in events:
            if acked and rules["ack_requires_promoted"] and not nstate[3]:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"acked-commit-while-standby: event {name} acks a "
                    f"commit but the hub is neither primary nor promoted "
                    f"(trace: {' -> '.join(trace[-5:] + (name,))})"))
                continue
            if name == "feed_sparse_delta" and not sparse_cap:
                findings.append(Finding(
                    "protocol", SELF_PATH, 1,
                    f"sparse-frame-to-legacy-standby: a REPL_SPARSE frame "
                    f"reaches a standby that never announced "
                    f"REPL_CAP_SPARSE — a torn stream on the dense-R "
                    f"fallback path "
                    f"(trace: {' -> '.join(trace[-5:] + (name,))})"))
                continue
            if nstate not in seen:
                seen.add(nstate)
                frontier.append((nstate, trace + (name,)))
        if len(findings) >= 8:
            return findings
    if not promoted_reachable:
        findings.append(Finding(
            "protocol", SELF_PATH, 1,
            "unreachable-promotion: no interleaving of feed "
            "sync/loss/retry and worker commits ever promotes the "
            "standby — failover is impossible under these rules"))
    return findings


# -- the pass ------------------------------------------------------------------

def check(net_src: SourceFile, ps_src: SourceFile, root: str,
          sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    findings = check_model_vs_dispatch(net_src, ps_src, root)
    # the exhaustive explorations are cheap (bounded, memoized) and run
    # in the static gate — a model edit that desyncs or deadlocks fails
    # the same run that introduced it
    findings.extend(explore_sessions())
    findings.extend(explore_standby())
    return apply_annotations(findings, sources or {}, root, rule="protocol")


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    net_path = os.path.join(root, "distkeras_tpu", "runtime", "networking.py")
    ps_path = os.path.join(root, "distkeras_tpu", "runtime",
                           "parameter_server.py")
    if not (os.path.exists(net_path) and os.path.exists(ps_path)):
        return []  # partial checkout; the repo gate runs on the real tree
    if sources is None:
        sources = load_sources(python_files(
            root, (os.path.join("distkeras_tpu", "runtime"),)))
    net_src = sources.get(net_path) or SourceFile(net_path)
    ps_src = sources.get(ps_path) or SourceFile(ps_path)
    return check(net_src, ps_src, root, sources)
