"""Telemetry-name registry pass (pass 4 of ``distkeras-lint``).

Collects metric/span name string literals from Python AND C++ sources and
fails on any name absent from :data:`~distkeras_tpu.analysis.
telemetry_registry.TELEMETRY_NAMES`.  Two collectors:

- **call sites**: the first string argument of every
  ``counter``/``gauge``/``histogram``/``span``/``start_span``/
  ``record_span`` call in the package (and ``bench.py``) — covers every
  direct emission regardless of namespace;
- **namespace sweep**: every string literal shaped like a project
  telemetry name (``ps_*``, ``ps.*``, ``worker.*``, ``health.*``) in the
  package and in ``native/*.cpp`` — covers indirect tables such as
  ``runtime/native.py``'s stat-key -> registry-name map and any names a
  future C++ hub emits directly.

Suppress a deliberately-out-of-registry literal (e.g. a fixture in a
docstring) with ``# lint: telemetry-ok <reason>`` on its line.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from distkeras_tpu.analysis.core import (RULES, Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)
from distkeras_tpu.analysis.telemetry_registry import TELEMETRY_NAMES

#: rules whose passes honor ``# lint: <rule>-ok`` annotations — the
#: unused-import sweep uses the standard ``# noqa`` instead, so an
#: ``unused-import-ok`` annotation is as inert as a typo'd rule id
OWNED_RULES = frozenset(RULES) - {"unused-import"}

_EMITTERS = {"counter", "gauge", "histogram", "span", "start_span",
             "record_span"}

#: full-match shape of a project telemetry name
NAMESPACE_RE = re.compile(
    r"^(?:ps_[a-z0-9_]+|ps\.[a-z0-9_]+|worker\.[a-z0-9_]+"
    r"|health\.[a-z0-9_]+)$")

#: the same shape, as a scan over C++ string literals
_CPP_LITERAL_RE = re.compile(
    r"\"((?:ps_[a-z0-9_]+|ps\.[a-z0-9_]+|worker\.[a-z0-9_]+"
    r"|health\.[a-z0-9_]+))\"")


def collect_python(src: SourceFile) -> List[Tuple[str, int, str]]:
    """(name, line, how) literals from one Python source."""
    out: List[Tuple[str, int, str]] = []
    seen_call_sites = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in _EMITTERS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    out.append((arg.value, arg.lineno, f"{fname}() call"))
                    seen_call_sites.add(id(arg))
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in seen_call_sites \
                and NAMESPACE_RE.match(node.value):
            out.append((node.value, node.lineno, "namespace literal"))
    return out


def collect_cpp(text: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for i, line in enumerate(text.splitlines(), start=1):
        for m in _CPP_LITERAL_RE.finditer(line):
            out.append((m.group(1), i, "C++ literal"))
    return out


def check(sources: Dict[str, SourceFile], cpp_files: Dict[str, str],
          root: str,
          registry: Optional[Set[str]] = None) -> List[Finding]:
    registry = TELEMETRY_NAMES if registry is None else set(registry)
    findings: List[Finding] = []
    for path, src in sorted(sources.items()):
        for name, line, how in collect_python(src):
            if _is_telemetry_shaped(name, how) and name not in registry:
                findings.append(_finding(path, line, name, how, root))
    for path, text in sorted(cpp_files.items()):
        for name, line, how in collect_cpp(text):
            if name not in registry:
                findings.append(_finding(path, line, name, how, root))
    # annotation-rule hygiene rides THIS pass because it scans the widest
    # Python source set: an annotation with a typo'd or unowned rule id
    # ("# lint: telemtry-ok ...", "# lint: unused-import-ok ...") would
    # otherwise be silently inert — never honored, never reported
    for path, src in sorted(sources.items()):
        for line, (arule, _reason) in sorted(src.annotations.items()):
            if arule not in OWNED_RULES:
                findings.append(Finding(
                    "telemetry", rel(path, root), line,
                    f"annotation names unknown lint rule '{arule}' — "
                    f"no pass honors '# lint: {arule}-ok' (valid rules: "
                    f"{', '.join(sorted(OWNED_RULES))}; unused imports "
                    f"use '# noqa: F401')"))
    return apply_annotations(findings, sources, root, rule="telemetry")


def _is_telemetry_shaped(name: str, how: str) -> bool:
    """Call-site first-args are always telemetry names; bare literals
    only count when they match the project namespace shape."""
    if how.endswith("call"):
        # metric/span constructors take ONLY telemetry names first; any
        # shape is checked so a typo in an un-namespaced name
        # (``trainer_epoc_seconds``) is caught too
        return bool(re.match(r"^[a-z][a-z0-9_.]+$", name))
    return bool(NAMESPACE_RE.match(name))


def _finding(path: str, line: int, name: str, how: str,
             root: str) -> Finding:
    return Finding(
        "telemetry", rel(path, root), line,
        f"telemetry name \"{name}\" ({how}) is not in "
        f"analysis/telemetry_registry.py — a typo here is a silently "
        f"missing series; register the name or fix the literal")


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    if sources is None:
        sources = load_sources(python_files(root, ("distkeras_tpu",),
                                            extra=("bench.py",)))
    cpp_files: Dict[str, str] = {}
    for path in sorted(glob.glob(os.path.join(root, "native", "*.cpp"))):
        with open(path, encoding="utf-8") as f:
            cpp_files[path] = f.read()
    return check(sources, cpp_files, root)
