"""Canonical telemetry-name registry (the spec for pass 4).

Every metric and span name the project may emit, in one place.  The
telemetry pass collects name string literals from both Python and C++
sources — call sites of ``obs.counter``/``gauge``/``histogram`` and
``span``/``start_span``/``record_span``, plus every namespace-shaped
literal (``ps_*``, ``ps.*``, ``worker.*``, ``health.*``) anywhere in the
tree — and fails on any name not listed here.  A typo'd name today
creates a silently-missing series that ``fleet_report`` coverage cannot
distinguish from "telemetry off"; against this registry it is a failed
test instead.

Adding a metric means adding its name here FIRST — the registry is the
reviewable diff of the telemetry namespace, the same way
``lock_manifest.LOCK_ORDER`` is for lock nesting.
"""

from __future__ import annotations

#: Prometheus-style counters/gauges/histograms (snake_case) and dotted
#: span/series names, grouped by plane.
TELEMETRY_NAMES = frozenset({
    # -- hub counters/gauges/histograms (both hub implementations emit
    #    these; runtime/native.py maps the C++ stat keys onto them) ------------
    "ps_commits_total", "ps_pulls_total",
    "ps_commit_bytes_total", "ps_pull_bytes_total",
    "ps_fenced_commits_total", "ps_idle_evictions_total",
    "ps_commit_log_dropped_total",
    "ps_live_workers", "ps_staleness", "ps_commit_staleness",
    "ps_rpc_seconds",
    "ps_snapshots_total", "ps_snapshot_sets_total",
    # replication / HA
    "ps_replicas_attached_total", "ps_replicas_connected",
    "ps_replica_disconnects_total", "ps_replica_frames_total",
    "ps_replica_clock", "ps_replication_lag", "ps_promotions_total",
    # adaptive aggregation
    "ps_merged_commits_total", "ps_merge_queue_depth",
    "ps_rate_scaled_commits_total", "ps_backpressure_hints_total",
    # sharded client
    "ps_stripe_losses_total",
    # -- hub/client dotted series (histograms + span names) --------------------
    "ps.commit", "ps.pull", "ps.evict", "ps.merge", "ps.promote",
    "ps.reconnect", "ps.replica_attach", "ps.snapshot", "ps.snapshot_set",
    "ps.handle_commit", "ps.handle_pull",
    "ps.commit_bytes", "ps.commit_latency_ms", "ps.pull_latency_ms",
    "ps.pull_stall_ms", "ps.inflight_depth", "ps.serialize_ms",
    "ps.snapshot_ms", "ps.snapshot_set_ms", "ps.snapshot_fence_ms",
    "ps.reconnect_ms", "ps.reconnects",
    "ps.failover", "ps.failovers", "ps.failover_ms",
    "ps.replicate_ms", "ps.merge_batch",
    "ps.retry_after_ms", "ps.retry_after_wait_ms",
    "ps.backpressure_waits", "ps.stripe_lost",
    "ps.sparse_rows_pulled", "ps.sparse_rows_committed",
    "ps.sparse_wire_bytes_saved",
    # hyperscale embedding tier (ISSUE 15): hub hot-set estimate, client
    # hot-tier cache standing, sparse replication savings
    "ps.sparse_hot_rows",
    "ps_sparse_cache_hits_total", "ps_sparse_cache_misses_total",
    "ps.repl_sparse_bytes_saved",
    # self-scaling fleet + multi-job admission (ISSUE 19): controller
    # decisions, job namespace admission verdicts, live job count
    "ps_fleet_spawns_total", "ps_fleet_retires_total",
    "ps_fleet_preemptions_total", "ps_fleet_target_size",
    "ps_jobs_admitted_total", "ps_jobs_rejected_total", "ps_active_jobs",
    # -- worker / health planes ------------------------------------------------
    "worker.restarts", "worker.preemptions",
    "health.event",
    # -- transport -------------------------------------------------------------
    "net_tx_frames_total", "net_tx_bytes_total",
    "net_rx_frames_total", "net_rx_bytes_total",
    # zero-copy shm transport + batched receive (ISSUE 18): frames moved
    # over shared-memory rings, producer parks on a full ring, and the
    # frames-per-syscall-batch histogram of the hub's batched receive
    "ps.shm_frames_total", "ps.shm_ring_full_waits", "ps_recv_batch_depth",
    # -- trainer / engine / data planes ----------------------------------------
    "trainer_epochs_total", "trainer_epoch_seconds",
    "trainer_samples_total", "trainer_samples_per_sec_per_chip",
    "trainer_window_loss", "trainer.epoch",
    "engine_steps_total", "engine_epoch_seconds", "engine_samples_per_sec",
    "engine.run_epoch",
    "async_windows_total", "async_window_wall_seconds",
    "async_window_device_seconds",
    "async_workers_started_total", "async_workers_finished_total",
    "async.window",
    "data_loads_total", "data_load_seconds", "data.load",
    "moe_steps_total",
    "punchcard_jobs_total", "punchcard.job",
})
