"""Unused-import (F401) pass — the ONE implementation the per-package
test cells delegate to (previously copy-pasted across
``tests/test_observability.py`` and the named runtime cells).

Runs real ``ruff`` when the container has it; otherwise an AST sweep:
imported names never referenced in the module body (``__all__`` strings
and docstring mentions count, and a ``# noqa``/``# noqa: ... F401`` on
the import line is honored — the re-export idiom
``runtime/__init__.py`` uses, which real ruff also skips).  Each file is
additionally compile-checked.
"""

from __future__ import annotations

import ast
import os
import re
import shutil
import subprocess
from typing import Dict, List, Optional, Sequence

from distkeras_tpu.analysis.core import Finding, SourceFile, rel, repo_root

#: the sweep's package vocabulary — mirrors the historical parametrized
#: test cells so scoping can never silently drop a tree
PACKAGES = ("observability", "runtime", ".", "tests", "data", "parallel",
            "models", "ops", "examples", "bench", "analysis")

_NOQA_RE = re.compile(r"#\s*noqa(?!:)|#\s*noqa:[^#]*\bF401\b")


def unused_imports(path: str, source: Optional[str] = None,
                   tree: Optional[ast.AST] = None) -> Dict[str, int]:
    """name -> line of imports never referenced in the module body."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    if tree is None:
        tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    imported: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) \
                and _NOQA_RE.search(lines[node.lineno - 1]):
            continue
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directive, never "used"
            for a in node.names:
                if a.name != "*":
                    imported[a.asname or a.name] = node.lineno
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)  # __all__ entries / docstring mentions
    return {name: line for name, line in imported.items()
            if name not in used}


def package_files(root: str, package: str) -> List[str]:
    """The file set of one historical test cell.  Missing trees yield an
    empty set (``--root`` may point at a partial checkout); the REPO's
    coverage is pinned by the named test cells, which assert non-empty."""
    if package == "tests":
        d = os.path.join(root, "tests")
        if not os.path.isdir(d):
            return []
        return [os.path.join(d, f) for f in sorted(os.listdir(d))
                if f.endswith(".py")]
    if package == "bench":
        p = os.path.join(root, "bench.py")
        return [p] if os.path.exists(p) else []
    if package == "examples":
        files: List[str] = []
        for d in (os.path.join(root, "distkeras_tpu", "examples"),
                  os.path.join(root, "examples")):
            if os.path.isdir(d):
                files.extend(os.path.join(d, f)
                             for f in sorted(os.listdir(d))
                             if f.endswith(".py"))
        return files
    pkg = os.path.normpath(os.path.join(root, "distkeras_tpu", package))
    if not os.path.isdir(pkg):
        return []
    return [os.path.join(pkg, f) for f in sorted(os.listdir(pkg))
            if f.endswith(".py")]


def check_files(paths: Sequence[str], root: str,
                sources: Optional[Dict[str, SourceFile]] = None
                ) -> List[Finding]:
    """AST F401 sweep + compile check over explicit files.  ``sources``
    (path -> already-parsed SourceFile) lets the gate reuse one parse of
    the tree across passes; files not in it are read and parsed here."""
    findings: List[Finding] = []
    for path in paths:
        cached = sources.get(path) if sources else None
        if cached is not None:
            source, tree = cached.text, cached.tree
        else:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = None
        compile(source, path, "exec")  # syntax gate, no .pyc write
        for name, line in sorted(unused_imports(path, source, tree).items(),
                                 key=lambda kv: kv[1]):
            findings.append(Finding(
                "unused-import", rel(path, root), line,
                f"'{name}' imported but unused"))
    return findings


def check_package(root: str, package: str,
                  sources: Optional[Dict[str, SourceFile]] = None
                  ) -> List[Finding]:
    """One package cell: real ruff when available, else the AST sweep.
    Returns findings (empty = clean); raises only on broken source."""
    files = package_files(root, package)
    if not files:
        return []  # partial checkout; repo coverage pinned by the cells
    ruff = shutil.which("ruff")
    if ruff:
        proc = subprocess.run([ruff, "check"] + files, capture_output=True,
                              text=True, timeout=120)
        if proc.returncode == 0:
            return []
        return [Finding("unused-import", rel(os.path.join(root, package), root),
                        0, (proc.stdout + proc.stderr).strip())]
    return check_files(files, root, sources)


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    findings: List[Finding] = []
    for package in PACKAGES:
        findings.extend(check_package(root, package, sources))
    return findings
