"""Wire-action parity checker (pass 3 of ``distkeras-lint``).

The PS wire protocol is implemented twice by hand: the ``ACTION_*``
registry in ``runtime/networking.py`` + the Python hub's dispatch in
``runtime/parameter_server.py``, and the char-literal dispatch in
``native/ps_server.cpp``.  PR 11's entire premise was that these drift
silently.  This pass parses both sides (regex/char-literal scan — no
compiler needed) and fails when:

- a Python-hub-dispatched action byte is neither dispatched nor even
  referenced (reply write, explicit-refusal comment) in the C++ hub;
- the C++ dispatch handles a byte that is not a registered ``ACTION_*``
  in ``networking.py`` (an unregistered protocol extension);
- a registered ``ACTION_*`` never appears in the C++ source at all
  (a new action shipped with zero native-side story — it must at least
  be refused in a comment naming the byte, e.g. ``// 'Z' refused:``);
- a ``NotImplementedError`` guidance message anywhere in the package
  names a ``knob=value`` that is not an actual parameter of any
  function/constructor in the tree (stale advice is worse than none).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from distkeras_tpu.analysis.core import (Finding, SourceFile,
                                         apply_annotations, load_sources,
                                         python_files, rel, repo_root)

ACTION_DEF_RE = re.compile(r"^(ACTION_[A-Z_]+)\s*=\s*b\"(.)\"", re.M)
CPP_DISPATCH_RE = re.compile(r"action\s*==\s*'(.)'")
CPP_CHAR_RE = re.compile(r"'(.)'")
KNOB_RE = re.compile(r"\b([a-zA-Z_][a-zA-Z0-9_]*)=(?:'[^']*'|\"[^\"]*\""
                     r"|True|False|None|[0-9])")


def parse_action_registry(net_src: SourceFile) -> Dict[str, Tuple[str, int]]:
    """``networking.py``'s registry: ACTION name -> (byte char, line)."""
    out: Dict[str, Tuple[str, int]] = {}
    for m in ACTION_DEF_RE.finditer(net_src.text):
        line = net_src.text.count("\n", 0, m.start()) + 1
        out[m.group(1)] = (m.group(2), line)
    return out


def python_dispatched_actions(ps_src: SourceFile) -> Set[str]:
    """ACTION_* names compared against the dispatched action byte inside
    the Python hub's connection handler."""
    out: Set[str] = set()
    for node in ast.walk(ps_src.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_handle_connection":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("ACTION_"):
                    out.add(sub.attr)
    return out


def cpp_action_bytes(cpp_text: str) -> Tuple[Set[str], Set[str]]:
    """(dispatched bytes, all referenced bytes) from the C++ hub source.
    "Referenced" covers dispatch arms, reply writes (``p[8] = 'V'``),
    and explicit-refusal comments naming the byte."""
    dispatched = set(CPP_DISPATCH_RE.findall(cpp_text))
    referenced = set(CPP_CHAR_RE.findall(cpp_text))
    return dispatched, referenced


def check_parity(net_src: SourceFile, ps_src: SourceFile, cpp_path: str,
                 cpp_text: str, root: str) -> List[Finding]:
    findings: List[Finding] = []
    registry = parse_action_registry(net_src)
    if not registry:
        findings.append(Finding(
            "wire-parity", rel(net_src.path, root), 1,
            "no ACTION_* registry found in networking source"))
        return findings
    byte_of = {name: b for name, (b, _) in registry.items()}
    name_of = {b: name for name, b in byte_of.items()}
    py_dispatch = python_dispatched_actions(ps_src)
    cpp_dispatch, cpp_ref = cpp_action_bytes(cpp_text)
    cpp_rel = rel(cpp_path, root)

    for name in sorted(py_dispatch):
        if name not in registry:
            continue  # a reply constant used in the handler body
        b, line = registry[name]
        if b not in cpp_ref:
            findings.append(Finding(
                "wire-parity", rel(ps_src.path, root), line,
                f"{name} (byte '{b}') is dispatched by the Python hub but "
                f"neither handled nor explicitly refused in {cpp_rel} — "
                f"add a dispatch arm or a refusal comment naming '{b}'"))
    for b in sorted(cpp_dispatch):
        if b not in name_of:
            findings.append(Finding(
                "wire-parity", cpp_rel, 1,
                f"C++ hub dispatches action byte '{b}' which is not a "
                f"registered ACTION_* in {rel(net_src.path, root)}"))
    for name, (b, line) in sorted(registry.items()):
        if b not in cpp_ref:
            findings.append(Finding(
                "wire-parity", rel(net_src.path, root), line,
                f"{name} (byte '{b}') never appears in {cpp_rel}: the "
                f"native hub must handle it, produce it, or refuse it in "
                f"a comment naming the byte"))
    return findings


def known_parameter_names(sources: Sequence[SourceFile]) -> Set[str]:
    """Every function/method parameter name defined in ``sources`` —
    the vocabulary a NotImplementedError message may recommend."""
    out: Set[str] = set()
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                for arg in (list(a.posonlyargs) + list(a.args)
                            + list(a.kwonlyargs)):
                    out.add(arg.arg)
                if a.vararg:
                    out.add(a.vararg.arg)
                if a.kwarg:
                    out.add(a.kwarg.arg)
    return out


def check_nie_knobs(sources: Dict[str, SourceFile], root: str,
                    known: Optional[Set[str]] = None) -> List[Finding]:
    """Cross-check every NotImplementedError guidance message: each
    ``knob=value`` token it names must be a real parameter somewhere in
    the analyzed tree."""
    if known is None:
        known = known_parameter_names(list(sources.values()))
    findings: List[Finding] = []
    for path, src in sorted(sources.items()):
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Raise) and node.exc is not None):
                continue
            exc = node.exc
            if not (isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name)
                    and exc.func.id == "NotImplementedError" and exc.args):
                continue
            msg = _const_str(exc.args[0])
            if msg is None:
                continue
            for knob in KNOB_RE.findall(msg):
                if knob not in known:
                    findings.append(Finding(
                        "wire-parity", rel(path, root), node.lineno,
                        f"NotImplementedError guidance names knob "
                        f"'{knob}=' which is not a parameter of any "
                        f"function in the tree — stale advice"))
    return findings


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, right = _const_str(node.left), _const_str(node.right)
        if left is not None and right is not None:
            return left + right
    if isinstance(node, ast.JoinedStr):
        parts = [v.value for v in node.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "".join(parts)
    return None


def run(root: Optional[str] = None,
        sources: Optional[Dict[str, SourceFile]] = None) -> List[Finding]:
    root = root or repo_root()
    if sources is None:
        sources = load_sources(python_files(root, ("distkeras_tpu",),
                                            extra=("bench.py",)))
    net_path = os.path.join(root, "distkeras_tpu", "runtime", "networking.py")
    ps_path = os.path.join(root, "distkeras_tpu", "runtime",
                           "parameter_server.py")
    cpp_path = os.path.join(root, "native", "ps_server.cpp")
    findings: List[Finding] = []
    # partial checkouts (``--root`` elsewhere) skip the parity legs whose
    # inputs are absent — the repo's own completeness is pinned by
    # tests/test_analysis.py, which runs against the real tree
    if all(os.path.exists(p) for p in (net_path, ps_path, cpp_path)):
        net_src = sources.get(net_path) or SourceFile(net_path)
        ps_src = sources.get(ps_path) or SourceFile(ps_path)
        with open(cpp_path, encoding="utf-8") as f:
            cpp_text = f.read()
        findings.extend(check_parity(net_src, ps_src, cpp_path, cpp_text,
                                     root))
    findings.extend(check_nie_knobs(sources, root))
    # the annotation grammar covers the Python-side findings (registry
    # lines, NotImplementedError sites); C++-anchored findings pass
    # through — refusals are expressed IN the C++ source as comments
    return apply_annotations(findings, sources, root, rule="wire-parity")
