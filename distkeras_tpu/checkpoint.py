"""Checkpoint / resume subsystem.

The reference has **no** checkpoint support (SURVEY.md §5: "None in-library" —
users called ``model.save()`` after ``train()`` returned; the only
persistence primitive was ``distkeras/utils.py :: serialize_keras_model``).
On TPU, preemption-safe training is table stakes, so this module is a
required superset: it persists the full training state — parameters,
optimizer state, and per-replica algorithm state — at epoch boundaries.
Because every trainer's shuffle order is a pure function of (seed, epoch),
the completed-epoch count in the metadata fully determines the data
position, so a killed run resumes from the last epoch boundary with
identical semantics (bit-exact vs. an uninterrupted run; see
tests/test_checkpoint.py).

Design:

- **No pickle anywhere.** Every pytree is stored as an ``.npz`` of raw
  leaf arrays plus a JSON manifest of ``(path, dtype, shape)``; restore
  requires a *template* pytree (the caller can always construct one —
  ``Model.init`` + ``optimizer.init``) and fills its leaves by path.
  Loading an untrusted checkpoint can therefore not execute code.
- **Atomic.** A checkpoint is written to ``<dir>/.tmp-<step>`` and
  ``os.rename``'d to ``<dir>/step_<N>`` only after everything (including
  the manifest) is flushed; readers never observe a partial checkpoint.
- **Retention.** ``keep`` most-recent checkpoints are preserved; older
  ones are deleted after a successful save.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from distkeras_tpu.utils import decode_array, encode_array

_STEP_PREFIX = "step_"


def _leaf_paths(tree: Any) -> List[str]:
    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in leaves_with_paths]


def _tree_to_arrays(tree: Any) -> Dict[str, np.ndarray]:
    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): np.asarray(leaf) for path, leaf in leaves_with_paths}


def save_tree(path: str, tree: Any) -> None:
    """Serialize one pytree to ``<path>.npz`` + ``<path>.json`` (no pickle)."""
    arrays = _tree_to_arrays(tree)
    manifest = [
        {"path": k, "dtype": v.dtype.name, "shape": list(v.shape)} for k, v in arrays.items()
    ]
    # keyed by index: npz member names must be filesystem-safe, leaf paths
    # (with brackets/quotes) are not
    np.savez(path + ".npz", **{f"leaf{i}": encode_array(v)
                               for i, (_, v) in enumerate(arrays.items())})
    with open(path + ".json", "w") as f:
        json.dump(manifest, f)


def restore_tree(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_tree` into ``template``'s
    structure.  Leaves are matched by tree path; a structural mismatch
    (missing or extra path) raises rather than silently misloading."""
    with open(path + ".json") as f:
        manifest = json.load(f)
    stored: Dict[str, np.ndarray] = {}
    with np.load(path + ".npz", allow_pickle=False) as z:
        for i, meta in enumerate(manifest):
            stored[meta["path"]] = decode_array(z[f"leaf{i}"], meta["dtype"], meta["shape"])
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    want = [jax.tree_util.keystr(p) for p, _ in leaves_with_paths]
    missing = [p for p in want if p not in stored]
    extra = [p for p in stored if p not in want]
    if missing or extra:
        raise ValueError(
            f"checkpoint/template structure mismatch: missing={missing[:5]} extra={extra[:5]}")
    new_leaves = []
    for path_str, tmpl_leaf in zip(want, (l for _, l in leaves_with_paths)):
        arr = stored[path_str]
        tmpl_shape = tuple(np.shape(tmpl_leaf))
        if tmpl_shape != tuple(arr.shape):
            raise ValueError(
                f"checkpoint leaf {path_str} has shape {tuple(arr.shape)}, template expects {tmpl_shape}")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


class Checkpointer:
    """Directory of ``step_<N>`` checkpoints with atomic writes and keep-N
    retention.  A checkpoint holds named pytrees (``params``, ``opt_state``,
    ``state`` — anything) plus a small JSON metadata dict."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep} (a save must survive its own retention)")
        os.makedirs(self.directory, exist_ok=True)

    # -- enumeration -----------------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:010d}")

    # -- save / restore --------------------------------------------------------
    def save(self, step: int, trees: Dict[str, Any], metadata: Optional[Dict[str, Any]] = None,
             apply_retention: bool = True) -> str:
        """Atomically write checkpoint ``step`` and apply retention.

        ``apply_retention=False`` skips the per-directory keep-N prune —
        for callers that coordinate retention ACROSS several parallel
        checkpoint directories (a sharded hub's snapshot set must prune
        every ``shard-NN/`` dir in lockstep, not each on its own save)."""
        final = self._step_dir(step)
        tmp = os.path.join(self.directory, f".tmp-{step:010d}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            for name, tree in trees.items():
                save_tree(os.path.join(tmp, name), tree)
            meta = {"step": int(step), "trees": sorted(trees), "metadata": metadata or {}}
            with open(os.path.join(tmp, "checkpoint.json"), "w") as f:
                json.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if apply_retention:
            self._apply_retention()
        return final

    def delete_step(self, step: int) -> None:
        """Remove checkpoint ``step`` if present (idempotent).  Set-level
        GC across parallel directories deletes one step from EVERY
        directory before advancing to the next, so an interruption can
        strand at most the oldest step half-pruned — never a newer step
        readable in one directory and gone from another."""
        shutil.rmtree(self._step_dir(step), ignore_errors=True)

    def restore(self, templates: Dict[str, Any], step: Optional[int] = None) -> Dict[str, Any]:
        """Restore named pytrees at ``step`` (default: latest).  ``templates``
        maps tree name -> structure/shape template.

        With ``step=None``, a corrupt or partial latest checkpoint (torn by
        something the atomic rename can't defend against — disk
        truncation, a partial copy from another machine) is SKIPPED with a
        warning and the next older one is tried; only when no checkpoint
        is readable does the call raise.  An explicitly requested ``step``
        always raises on corruption — the caller named it, silently
        substituting a different state would be worse than failing."""
        if step is not None:
            return self._restore_at(step, templates)
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                return self._restore_at(s, templates)
            except Exception as e:
                last_err = e
                warnings.warn(f"skipping corrupt/unreadable checkpoint "
                              f"step {s}: {type(e).__name__}: {e}")
        # carry the last underlying error in the MESSAGE too: when every
        # step fails for the same non-corruption reason (e.g. a template
        # mismatch after a model-format change), the cause must be in the
        # caller's face, not only in the warning stream / __cause__
        raise FileNotFoundError(
            f"no readable checkpoint in {self.directory} "
            f"({len(steps)} present, all corrupt or unreadable; last error: "
            f"{type(last_err).__name__}: {last_err})") from last_err

    def _restore_at(self, step: int, templates: Dict[str, Any]) -> Dict[str, Any]:
        d = self._step_dir(step)
        with open(os.path.join(d, "checkpoint.json")) as f:
            meta = json.load(f)
        missing = sorted(set(templates) - set(meta["trees"]))
        if missing:
            raise ValueError(f"checkpoint {step} lacks trees {missing}; has {meta['trees']}")
        return {name: restore_tree(os.path.join(d, name), tmpl) for name, tmpl in templates.items()}

    def metadata(self, step: Optional[int] = None) -> Dict[str, Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        with open(os.path.join(self._step_dir(step), "checkpoint.json")) as f:
            return json.load(f)

    def _apply_retention(self) -> None:
        steps = self.all_steps()
        for step in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
