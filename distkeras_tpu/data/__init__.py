"""Data plane: columnar Dataset + feature/label transformers.

Replaces the reference's Spark DataFrame/RDD machinery (SURVEY.md §2.14):
rows live in host numpy columns, batches are device-sharded dicts.
"""

from distkeras_tpu.data.dataset import Dataset  # noqa: F401
from distkeras_tpu.data.ctr import synthetic_ctr_dataset  # noqa: F401
from distkeras_tpu.data.transformers import (  # noqa: F401
    OneHotTransformer,
    MinMaxTransformer,
    ReshapeTransformer,
    DenseTransformer,
    LabelIndexTransformer,
)
