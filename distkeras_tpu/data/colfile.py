"""DKCOL columnar container: native mmap loading for out-of-core datasets.

The reference's data plane was Spark reading HDFS partitions in the JVM;
the host-side native analogue here is a flat columnar file mapped straight
into the process by a C++ loader (``native/data_loader.cpp``): columns
come back as ZERO-COPY numpy views over the mapping, an optional
background thread warms the page cache ahead of the first epoch, and the
chunked feeder can ``prefetch`` the next chunk's byte range while the
current one trains.  Loading a 10 GB dataset is O(1); pages stream in as
touched.

When the native toolchain is unavailable the same container loads through
a pure-numpy ``np.memmap`` fallback with identical semantics (minus the
warm thread).

Format (little-endian): 8-byte magic ``DKCOL1\\0\\0``, u32 ncols, then per
column ``u32 name_len, name, u32 dtype_len, dtype(np .str), u32 ndim,
ndim*i64 dims, u64 offset (64-aligned), u64 nbytes``, then the data blobs.

Usage::

    write_columns("train.dkcol", {"features": x, "label": y})
    ds = ColumnFile("train.dkcol").dataset()   # Dataset of zero-copy views
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Dict, List, Optional

import numpy as np

from distkeras_tpu.data.dataset import Dataset

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native", "data_loader.cpp")
_LIB = os.path.join(_HERE, "_native_loader.so")

MAGIC = b"DKCOL1\0\0"
_ALIGN = 64

def _bind(lib: ctypes.CDLL) -> None:
    lib.dk_dl_open.restype = ctypes.c_void_p
    lib.dk_dl_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dk_dl_error.restype = ctypes.c_char_p
    lib.dk_dl_close.argtypes = [ctypes.c_void_p]
    lib.dk_dl_release.argtypes = [ctypes.c_void_p]
    lib.dk_dl_ncols.restype = ctypes.c_int32
    lib.dk_dl_ncols.argtypes = [ctypes.c_void_p]
    lib.dk_dl_col_name.restype = ctypes.c_char_p
    lib.dk_dl_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dk_dl_col_dtype.restype = ctypes.c_char_p
    lib.dk_dl_col_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dk_dl_col_ndim.restype = ctypes.c_int32
    lib.dk_dl_col_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dk_dl_col_dim.restype = ctypes.c_int64
    lib.dk_dl_col_dim.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.dk_dl_col_nbytes.restype = ctypes.c_int64
    lib.dk_dl_col_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dk_dl_col_data.restype = ctypes.c_void_p
    lib.dk_dl_col_data.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dk_dl_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                   ctypes.c_int64, ctypes.c_int64]
    lib.dk_dl_warmed_bytes.restype = ctypes.c_int64
    lib.dk_dl_warmed_bytes.argtypes = [ctypes.c_void_p]


def _lazy():
    from distkeras_tpu.runtime.native import LazyNativeLib

    global _lazy_lib
    if _lazy_lib is None:
        _lazy_lib = LazyNativeLib(_SRC, _LIB, _bind)
    return _lazy_lib


_lazy_lib = None


def _load_lib() -> Optional[ctypes.CDLL]:
    return _lazy().load()


def native_loader_available() -> bool:
    return _load_lib() is not None


def write_columns(path: str, columns: Dict[str, np.ndarray]) -> None:
    """Write a DKCOL container (atomic: tmp file + rename)."""
    cols = {k: np.ascontiguousarray(v) for k, v in columns.items()}
    header = bytearray()
    header += struct.pack("<I", len(cols))
    # compute offsets after a first pass to know the header size
    metas = []
    for name, arr in cols.items():
        nb = name.encode("utf-8")
        db = arr.dtype.str.encode("utf-8")
        metas.append((nb, db, arr))
    fixed = len(MAGIC) + 4
    for nb, db, arr in metas:
        fixed += 4 + len(nb) + 4 + len(db) + 4 + 8 * arr.ndim + 8 + 8
    offset = (fixed + _ALIGN - 1) // _ALIGN * _ALIGN
    placed = []
    for nb, db, arr in metas:
        placed.append(offset)
        offset = (offset + arr.nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
    for (nb, db, arr), off in zip(metas, placed):
        header += struct.pack("<I", len(nb)) + nb
        header += struct.pack("<I", len(db)) + db
        header += struct.pack("<I", arr.ndim)
        header += struct.pack(f"<{arr.ndim}q", *arr.shape)
        header += struct.pack("<QQ", off, arr.nbytes)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(bytes(header))
        for (nb, db, arr), off in zip(metas, placed):
            f.seek(off)
            arr.tofile(f)  # streams the buffer; no transient full copy
    os.replace(tmp, path)


class ColumnFile:
    """Open a DKCOL container; columns are zero-copy views of the mapping.

    ``warm=True`` starts the native background page-warm thread.  Falls
    back to ``np.memmap`` when the native loader can't build.
    """

    def __init__(self, path: str, warm: bool = False):
        self.path = path
        self._handle = None
        self._lib = _load_lib()
        self._cols: Dict[str, np.ndarray] = {}
        self._col_index: Dict[str, int] = {}
        self.native = self._lib is not None
        if self.native:
            self._open_native(warm)
        else:
            self._open_fallback()

    def _open_native(self, warm: bool) -> None:
        lib = self._lib
        handle = lib.dk_dl_open(self.path.encode("utf-8"), int(warm))
        if not handle:
            raise OSError(f"native loader failed: {lib.dk_dl_error().decode()}")
        self._handle = handle
        for i in range(lib.dk_dl_ncols(handle)):
            name = lib.dk_dl_col_name(handle, i).decode()
            dtype = np.dtype(lib.dk_dl_col_dtype(handle, i).decode())
            shape = tuple(lib.dk_dl_col_dim(handle, i, j)
                          for j in range(lib.dk_dl_col_ndim(handle, i)))
            nbytes = lib.dk_dl_col_nbytes(handle, i)
            if any(d < 0 for d in shape) or \
                    int(np.prod(shape, dtype=np.int64)) * dtype.itemsize != nbytes:
                # no views escaped yet: a full close (munmap) is safe here,
                # unlike the keep-mapped release used once views exist
                lib.dk_dl_close(handle)
                raise OSError(f"corrupt DKCOL header: column {name!r} dims {shape} "
                              f"disagree with nbytes {nbytes}")
            addr = lib.dk_dl_col_data(handle, i)
            buf = (ctypes.c_char * nbytes).from_address(addr)
            arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
            arr.flags.writeable = False
            self._cols[name] = arr
            self._col_index[name] = i

    def _open_fallback(self) -> None:
        size = os.path.getsize(self.path)
        try:
            with open(self.path, "rb") as f:
                if f.read(8) != MAGIC:
                    raise OSError(f"{self.path} is not a DKCOL1 container")
                (ncols,) = struct.unpack("<I", f.read(4))
                if ncols > 4096:
                    raise OSError("corrupt DKCOL header: column count")
                for i in range(ncols):
                    (nlen,) = struct.unpack("<I", f.read(4))
                    if nlen > 4096:  # same caps as the native loader, so a
                        raise OSError("corrupt DKCOL header: name length")
                    name = f.read(nlen).decode()
                    (dlen,) = struct.unpack("<I", f.read(4))
                    if dlen > 64:  # flipped byte can't trigger a huge read
                        raise OSError("corrupt DKCOL header: dtype length")
                    dtype = np.dtype(f.read(dlen).decode())
                    (ndim,) = struct.unpack("<I", f.read(4))
                    if ndim > 32:
                        raise OSError("corrupt DKCOL header: ndim")
                    shape = struct.unpack(f"<{ndim}q", f.read(8 * ndim))
                    off, nbytes = struct.unpack("<QQ", f.read(16))
                    # same validation contract as the native loader
                    if off > size or nbytes > size - off:
                        raise OSError("corrupt DKCOL header: column out of bounds")
                    if any(d < 0 for d in shape) or \
                            int(np.prod(shape, dtype=np.int64)) * dtype.itemsize != nbytes:
                        raise OSError("corrupt DKCOL header: dims/nbytes mismatch")
                    self._cols[name] = np.memmap(self.path, dtype=dtype, mode="r",
                                                 offset=off, shape=tuple(shape))
                    self._col_index[name] = i
        except (struct.error, UnicodeDecodeError, TypeError, ValueError,
                OverflowError) as e:
            raise OSError(f"corrupt DKCOL header: {e}") from None

    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def dataset(self) -> Dataset:
        """Dataset over the zero-copy views; its chunked feeding prefetches
        one chunk ahead through the native madvise hook."""
        return _PrefetchingDataset(self._cols, self)

    def prefetch(self, name: str, start_row: int, num_rows: int) -> None:
        """Advise the kernel to fault in rows [start, start+num) of a column
        (no-op on the fallback path — memmap still works, just lazily)."""
        if not self.native or self._handle is None:
            return  # fallback, or closed: memmap/page cache still works lazily
        arr = self._cols[name]
        row_bytes = arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64))
        self._lib.dk_dl_prefetch(self._handle, self._col_index[name],
                                 start_row * row_bytes, num_rows * row_bytes)

    def warmed_bytes(self) -> int:
        if not self.native or self._handle is None:
            return 0
        return int(self._lib.dk_dl_warmed_bytes(self._handle))

    def close(self) -> None:
        """Stop the warm thread and close the fd.  The MAPPING stays alive
        for the process lifetime, so views/Datasets handed out earlier can
        never dangle (file-backed clean pages — the kernel reclaims them
        under pressure; the cost is address space, not RAM)."""
        if self.native and self._handle is not None:
            self._lib.dk_dl_release(self._handle)
            self._handle = None

    def __enter__(self) -> "ColumnFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _PrefetchingDataset(Dataset):
    """Dataset whose chunked feeding overlaps IO with compute: while chunk k
    trains, chunk k+1's pages are madvise'd in by the native loader.

    Out-of-core semantics differ from the in-RAM Dataset in one place:
    ``shuffle`` is CHUNK-LOCAL — rows are permuted inside each fed chunk
    (bounded memory; prefetch stays effective) instead of globally.  A
    global permutation would fancy-index every mapped column into a full
    in-RAM copy, the exact OOM this container exists to avoid; for a true
    global shuffle, load the data into a plain Dataset.  ``split`` is
    unsupported for the same reason — split at ``write_columns`` time.
    """

    def __init__(self, columns, colfile: ColumnFile, shuffle_seed: Optional[int] = None):
        super().__init__(columns)
        self._colfile = colfile
        self._shuffle_seed = shuffle_seed

    def shuffle(self, seed: int = 0) -> "_PrefetchingDataset":
        return _PrefetchingDataset(self._columns, self._colfile, shuffle_seed=seed)

    def split(self, fraction, seed=None):
        raise NotImplementedError(
            "split() on a mapped DKCOL dataset would materialize it; write "
            "separate train/test containers instead (write_columns twice)")

    def chunked_epoch(self, batch_size, columns, window=1, chunk_windows=None):
        per_window = batch_size * window
        num_windows = len(self) // per_window
        step = num_windows if chunk_windows is None else int(chunk_windows)
        rng = (np.random.default_rng(self._shuffle_seed)
               if self._shuffle_seed is not None else None)
        for i, chunk in enumerate(super().chunked_epoch(
                batch_size, columns, window=window, chunk_windows=chunk_windows)):
            nxt = (i + 1) * step
            if nxt < num_windows:
                n = min(step, num_windows - nxt)
                for c in columns:
                    if c in self._colfile._col_index:
                        self._colfile.prefetch(c, nxt * per_window, n * per_window)
            if rng is not None:
                # chunk-local shuffle: one permutation of the chunk's rows,
                # applied identically to every column (the copy is bounded
                # by the chunk size, which is the point of chunking)
                n_rows = chunk[columns[0]].shape[0] * window * batch_size
                perm = rng.permutation(n_rows)
                chunk = {
                    c: v.reshape((n_rows,) + v.shape[3:])[perm].reshape(v.shape)
                    for c, v in chunk.items()
                }
            yield chunk
