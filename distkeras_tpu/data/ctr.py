"""Synthetic CTR impressions — the row-sparse embedding workload's data
(ISSUE 9).

Real CTR logs have two properties the row-sparse PS path is built around:

- each impression names only ``fields`` ids out of a vocabulary of
  ``rows`` — a batch touches a tiny row subset of the embedding table;
- id traffic is heavily skewed (a small hot set takes most impressions),
  so the touched-row set per communication window is far below
  ``batch x window x fields`` distinct ids.

This generator reproduces both with a two-tier draw: a ``hot_fraction``
of the vocabulary receives ``hot_prob`` of the traffic, the cold tail is
uniform.  Labels are LEARNABLE, not noise: each id carries a fixed random
propensity weight and the click probability is the sigmoid of the
impression's summed weights — so a trained embedding model's loss
actually falls, and bench/e2e runs exercise real gradients over real row
subsets.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def synthetic_ctr_dataset(n: int, rows, fields: int = 4, seed: int = 0,
                          hot_fraction: float = 0.01,
                          hot_prob: float = 0.9) -> Dataset:
    """``n`` impressions over a ``rows``-id vocabulary: int32 ``features``
    ``[n, fields]`` and one-hot float32 ``label`` ``[n, 2]``
    (click / no-click).

    ``rows`` as an int draws every field from ONE shared vocabulary (the
    PR-9 contract, unchanged); a SEQUENCE gives each field its own
    independent vocabulary size (``fields`` is then implied) — the
    multi-table shape ``ctr_embedding_spec(rows=[...])`` trains on, with
    the same two-tier hot/cold skew applied per field."""
    if not 0.0 < hot_fraction <= 1.0:
        raise ValueError(f"hot_fraction must be in (0, 1], got {hot_fraction}")
    if not 0.0 <= hot_prob <= 1.0:
        raise ValueError(f"hot_prob must be in [0, 1], got {hot_prob}")
    rng = np.random.default_rng(seed)
    if isinstance(rows, (list, tuple)):
        # multi-vocabulary draw: per-field id streams and per-field
        # propensity tables (a fresh code path — the scalar branch below
        # stays stream-for-stream identical to PR 9's generator)
        per_field = [int(r) for r in rows]
        fields = len(per_field)
        shape = (int(n), int(fields))
        is_hot = rng.random(shape) < hot_prob
        cols = []
        for f, r in enumerate(per_field):
            hot = max(1, min(r, int(round(r * hot_fraction))))
            cols.append(np.where(is_hot[:, f],
                                 rng.integers(0, hot, size=int(n)),
                                 rng.integers(0, r, size=int(n))))
        ids = np.stack(cols, axis=1).astype(np.int32)
        logits = np.zeros(int(n), np.float32)
        for f, r in enumerate(per_field):
            propensity = rng.normal(scale=1.0 / np.sqrt(fields),
                                    size=r).astype(np.float32)
            logits += propensity[ids[:, f]]
        p_click = 1.0 / (1.0 + np.exp(-logits))
        clicks = (rng.random(int(n)) < p_click).astype(np.int64)
        label = np.eye(2, dtype=np.float32)[clicks]
        return Dataset({"features": ids, "label": label})
    rows = int(rows)
    hot = max(1, min(int(rows), int(round(rows * hot_fraction))))
    shape = (int(n), int(fields))
    is_hot = rng.random(shape) < hot_prob
    ids = np.where(is_hot,
                   rng.integers(0, hot, size=shape),
                   rng.integers(0, rows, size=shape)).astype(np.int32)
    # per-id click propensity: fixed for the dataset, so the label is a
    # function of the ids and an embedding model can actually learn it
    propensity = rng.normal(scale=1.0 / np.sqrt(fields),
                            size=int(rows)).astype(np.float32)
    logits = propensity[ids].sum(axis=1)
    p_click = 1.0 / (1.0 + np.exp(-logits))
    clicks = (rng.random(int(n)) < p_click).astype(np.int64)
    label = np.eye(2, dtype=np.float32)[clicks]
    return Dataset({"features": ids, "label": label})


def touched_row_fraction(ids: np.ndarray, rows: int, batch_size: int,
                         window: int) -> float:
    """Mean fraction of the vocabulary one communication window's batches
    touch — the number the sparse wire-savings tripwire is phrased in."""
    ids = np.asarray(ids)
    per_window = int(batch_size) * int(window)
    n_windows = len(ids) // per_window
    if n_windows == 0 or rows <= 0:
        return 1.0
    fracs = [np.unique(ids[w * per_window:(w + 1) * per_window]).size / rows
             for w in range(n_windows)]
    return float(np.mean(fracs))
