"""Columnar in-memory Dataset — the Spark-DataFrame stand-in.

Reference parity: trainers consumed a Spark ``DataFrame`` with
``features_col``/``label_col`` string-named columns, repartitioned it over
workers, and iterated partitions row-by-row inside executors
(``distkeras/workers.py``).  TPU-native design: columns are contiguous
host numpy arrays (no row objects, no JVM), batching is a zero-copy slice,
and "repartitioning over workers" becomes device-sharding the leading batch
axis over a mesh axis — the data plane feeds the chips directly.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu import utils

# Out-of-core chunk-size budget (bytes of feature data per chunk) for the
# double-buffered feed.  Promoted from bench.py's ``feed`` chunk_mb sweep
# (12/25/49/98 MB legs, ``best_chunk_mb``): the bench re-runs the sweep
# every capture and uses its own best for the headline comparison, so a
# platform where a different size wins shows up as a recorded number —
# re-promote this constant when the sweep moves.  25 MB balances transfer
# granularity (enough batches per chunk to amortize the per-transfer
# relay latency) against double-buffer residency (2 chunks in flight).
DEFAULT_CHUNK_BUDGET_BYTES = 25 * 2**20


def chunk_windows_for_budget(row_bytes: int, batch_size: int, window: int = 1,
                             budget_bytes: Optional[int] = None) -> int:
    """``chunk_windows`` value sizing each chunk near the feed budget.

    ``row_bytes`` is one sample's feature bytes (``features[0].nbytes``).
    Returns at least 1 (a single window may exceed the budget; chunking
    cannot split below one window)."""
    if row_bytes <= 0 or batch_size <= 0 or window <= 0:
        raise ValueError(f"row_bytes, batch_size and window must be positive, "
                         f"got {row_bytes}, {batch_size}, {window}")
    budget = DEFAULT_CHUNK_BUDGET_BYTES if budget_bytes is None else budget_bytes
    return max(1, budget // (row_bytes * batch_size * window))


def prefetch_to_device(chunks: Iterator, place: Callable,
                       produce_ahead: bool = True,
                       metric_prefix: str = "feed") -> Iterator:
    """Double-buffered feed: yield ``place(chunk)`` with the NEXT chunk's
    host->device transfer already issued before the caller consumes the
    current one.

    ``place`` must only ISSUE the transfer (``jax.device_put`` /
    ``jnp.asarray`` — both asynchronous), never block on it; the caller's
    loss read for chunk N then overlaps chunk N+1's copy-in.  With
    ``produce_ahead`` (default) chunk PRODUCTION — disk page faults and
    the chunk-local shuffle copy for ``ColumnFile`` datasets — runs on a
    background thread with a one-chunk queue, so host-side IO overlaps
    training too, not just the transfer.  At most two chunks are in
    flight either way, so feeding stays O(chunk) memory — the out-of-core
    epoch's IO/H2D/compute overlap (SURVEY §7 step 3; round-4 verdict
    weak #6: the old loop issued synchronous per-chunk transfers with no
    overlap)."""
    if produce_ahead:
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=1)
        stop = threading.Event()
        # the producer must NOT capture the `chunks` cell: it is rebound to
        # the produced() generator below, and a closure reference from the
        # live thread would keep that generator (and so its stop-setting
        # finalizer) alive exactly until stop is set — a reference deadlock
        # that leaked the thread on abandoned consumers
        source = chunks

        def put(item) -> bool:
            # bounded-wait put so an abandoned consumer (exception mid-
            # epoch, early break) cannot strand this thread in q.put
            # forever — it notices `stop` within 0.1s, drops its chunk,
            # and exits instead of leaking a thread + a chunk per retry
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        # telemetry (no-op unless observability is enabled): producer-side
        # chunk production latency (disk page faults + shuffle copies) and
        # the handoff queue's occupancy — the feed path's two signals.
        # ``metric_prefix`` keeps distinct producers in distinct
        # instruments (the async trainer's window staging uses
        # "async_feed" so its microsecond slice walk cannot pollute the
        # disk feed's chunk-load histogram or flap its depth gauge)
        m_load = obs.histogram(f"{metric_prefix}_chunk_load_seconds")
        m_depth = obs.gauge(f"{metric_prefix}_queue_depth")
        m_chunks = obs.counter(f"{metric_prefix}_chunks_total")

        def producer():
            try:
                it_src = iter(source)
                while True:
                    telemetry = obs.enabled()
                    t0 = time.perf_counter() if telemetry else 0.0
                    try:
                        c = next(it_src)
                    except StopIteration:
                        break
                    if telemetry:
                        m_load.observe(time.perf_counter() - t0)
                        m_chunks.inc()
                    if not put(("chunk", c)):
                        return
                    m_depth.set(q.qsize())
            except BaseException as exc:  # surfaced on the consumer side
                put(("error", exc))
            else:
                put(("done", None))

        producer_thread = threading.Thread(target=producer, daemon=True)
        producer_thread.start()

        def produced():
            try:
                while True:
                    # bounded wait + liveness check (ADVICE round 5): a
                    # producer killed WITHOUT its sentinel (interpreter
                    # teardown, an exception inside the sentinel put
                    # itself) must surface as an error, not a silent
                    # forever-hang in q.get()
                    try:
                        kind, val = q.get(timeout=1.0)
                    except queue.Empty:
                        if not producer_thread.is_alive():
                            # one last non-blocking drain: the producer may
                            # have enqueued its sentinel between our timeout
                            # and the liveness check
                            try:
                                kind, val = q.get_nowait()
                            except queue.Empty:
                                raise RuntimeError(
                                    "prefetch producer thread died without "
                                    "delivering its chunk or end-of-epoch "
                                    "sentinel; the feed cannot make progress"
                                ) from None
                        else:
                            continue
                    m_depth.set(q.qsize())
                    if kind == "error":
                        raise val
                    if kind == "done":
                        return
                    yield val
            finally:
                stop.set()  # runs on normal exhaustion AND GeneratorExit

        chunks = produced()
    it = iter(chunks)
    try:
        cur = place(next(it))
    except StopIteration:
        return
    for nxt in it:
        nxt_placed = place(nxt)
        yield cur
        cur = nxt_placed
    yield cur


class Dataset:
    """A dict of equal-length numpy columns with DataFrame-ish helpers."""

    def __init__(self, columns: Dict[str, np.ndarray]):
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"column length mismatch: {lengths}")
        self._columns = {k: np.asarray(v) for k, v in columns.items()}

    # -- DataFrame-ish surface -------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return len(next(iter(self._columns.values()))) if self._columns else 0

    def __getitem__(self, col: str) -> np.ndarray:
        return self._columns[col]

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        if len(values) != len(self):
            raise ValueError(f"new column {name!r} has {len(values)} rows, dataset has {len(self)}")
        cols = dict(self._columns)
        cols[name] = np.asarray(values)
        return Dataset(cols)

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset({n: self._columns[n] for n in names})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    def shuffle(self, seed: int = 0) -> "Dataset":
        """Row shuffle (reference: ``utils.shuffle`` before repartitioning)."""
        return Dataset(utils.shuffle_arrays(self._columns, seed=seed))

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Contiguous row shard ``index`` of ``num_shards`` (reference:
        ``df.repartition(num_workers)`` handing each worker one partition).
        Equal-size shards; the tail remainder is dropped so every worker
        sees the same number of rows."""
        if not 0 <= index < num_shards:
            raise ValueError(f"shard index {index} out of range for {num_shards} shards")
        per = len(self) // num_shards
        if per == 0:
            raise ValueError(f"dataset of {len(self)} rows cannot be split into {num_shards} shards")
        return Dataset({k: v[index * per:(index + 1) * per] for k, v in self._columns.items()})

    def split(self, fraction: float, seed: Optional[int] = None) -> Sequence["Dataset"]:
        """Random (train, test)-style split; reference: ``df.randomSplit``."""
        ds = self.shuffle(seed) if seed is not None else self
        cut = int(len(ds) * fraction)
        left = Dataset({k: v[:cut] for k, v in ds._columns.items()})
        right = Dataset({k: v[cut:] for k, v in ds._columns.items()})
        return left, right

    # -- batch plane -----------------------------------------------------------
    def batches(self, batch_size: int, columns: Optional[Sequence[str]] = None,
                drop_remainder: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """Yield batch dicts of the requested columns."""
        names = list(columns) if columns is not None else self.columns
        n = len(self)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            yield {c: self._columns[c][i : i + batch_size] for c in names}

    def chunked_epoch(self, batch_size: int, columns: Sequence[str],
                      window: int = 1, chunk_windows: Optional[int] = None
                      ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield the epoch in bounded chunks of ``[n, window, batch, ...]``.

        The memory-bounded form of :meth:`stacked_epoch`: at most
        ``chunk_windows`` windows are materialized per yield (each chunk is
        a zero-copy reshape of a column slice), so epoch feeding is
        O(chunk), not O(dataset) — the host-sharded-feeding story for data
        that doesn't fit the single-transfer fast path.  ``None`` yields
        the whole epoch as one chunk.  The final chunk may be smaller
        (possible one-off recompile of the epoch program for that shape).
        """
        per_window = batch_size * window
        num_windows = len(self) // per_window
        if num_windows == 0:
            raise ValueError(
                f"dataset of {len(self)} rows too small for batch_size={batch_size} window={window}")
        step = num_windows if chunk_windows is None else int(chunk_windows)
        if step <= 0:
            raise ValueError(f"chunk_windows must be positive, got {chunk_windows}")
        for start in range(0, num_windows, step):
            n = min(step, num_windows - start)
            out = {}
            for c in columns:
                v = self._columns[c][start * per_window:(start + n) * per_window]
                out[c] = v.reshape((n, window, batch_size) + v.shape[1:])
            yield out

    def stacked_epoch(self, batch_size: int, columns: Sequence[str],
                      window: int = 1) -> Dict[str, np.ndarray]:
        """Materialize one epoch as [num_windows, window, batch, ...] arrays.

        This is the TPU-friendly feed shape: a whole epoch (or a large chunk)
        becomes one device transfer and the train loop runs as a compiled
        ``lax.scan`` over windows instead of a Python batch loop — the
        replacement for the reference's per-row partition iterators.
        (Exactly the single-chunk case of :meth:`chunked_epoch`.)
        """
        return next(self.chunked_epoch(batch_size, columns, window=window))
