"""Dataset loaders for the BASELINE.md measurement matrix.

Reference parity: the reference's examples fed MNIST / CIFAR / Higgs CSVs
through Spark DataFrames (SURVEY §2.21).  Here loaders produce columnar
:class:`Dataset` pairs directly.

Offline-first design: loaders search local caches for the standard
``.npz`` archives and NEVER download.  When no cache exists they fall back
to deterministic, clearly-labeled synthetic stand-ins with identical
shapes/dtypes (class-prototype clusters — learnable, so accuracy targets
still exercise the full train/eval loop), and the returned ``info`` dict
says so: benchmark records must carry the ``synthetic`` flag.

Cache search order: explicit ``cache_dir`` arg, ``$DKT_DATA_DIR``,
``~/.keras/datasets``, ``~/.cache/distkeras_tpu``, ``./data``.

Accepted archive formats — the RAW distribution artifacts work as dropped
in, no conversion step:

- ``mnist.npz`` — keys ``x_train, y_train, x_test, y_test`` (Keras layout);
- the four raw IDX files (optionally gzipped): ``train-images-idx3-ubyte
  [.gz]``, ``train-labels-idx1-ubyte[.gz]``, ``t10k-images-idx3-ubyte
  [.gz]``, ``t10k-labels-idx1-ubyte[.gz]``;
- ``cifar10.npz`` / ``cifar100.npz`` — npz with the same keys, images
  [N, 32, 32, 3] uint8;
- the upstream ``cifar-10-batches-py``/``cifar-100-python`` directories or
  their ``.tar.gz`` archives (the canonical pickled python batches — these
  are the one place the no-pickle rule yields, because the upstream
  distribution IS a pickle).  Pickled archives are ONLY loaded from dirs
  you designated explicitly — the ``cache_dir`` argument or
  ``$DKT_DATA_DIR`` — never from the shared search dirs (cwd ``./data``,
  ``~/.keras/datasets``), so nothing an attacker drops there is unpickled.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
import time
from typing import Dict, Optional, Tuple

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.data.dataset import Dataset


def _trusted_dirs(cache_dir: Optional[str]):
    """Dirs the user designated EXPLICITLY (a ``cache_dir`` argument or
    ``$DKT_DATA_DIR``).  Formats whose parsing executes a pickle are only
    ever loaded from here — never from the shared/implicit search dirs —
    so an attacker-placed archive in cwd or ``~/.keras`` cannot reach
    ``pickle.loads`` (the module's no-pickle rule, see module docstring)."""
    dirs = []
    if cache_dir:
        dirs.append(cache_dir)
    if os.environ.get("DKT_DATA_DIR"):
        dirs.append(os.environ["DKT_DATA_DIR"])
    return dirs


def _search_dirs(cache_dir: Optional[str]):
    home = os.path.expanduser("~")
    return _trusted_dirs(cache_dir) + [
        os.path.join(home, ".keras", "datasets"),
        os.path.join(home, ".cache", "distkeras_tpu"),
        os.path.join(os.getcwd(), "data")]


def _find_npz(filename: str, cache_dir: Optional[str]) -> Optional[str]:
    for d in _search_dirs(cache_dir):
        path = os.path.join(d, filename)
        if os.path.exists(path):
            return path
    return None


def _read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (the raw MNIST distribution format), gzipped or
    not: big-endian magic 0x0000080{1,3} + dims, then uint8 payload."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        if magic >> 8 != 0x08 or ndim not in (1, 3):
            raise ValueError(f"{path}: not an IDX uint8 file (magic 0x{magic:08x})")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    if data.size != int(np.prod(dims)):
        raise ValueError(f"{path}: payload size {data.size} != dims {dims}")
    return data.reshape(dims)


_IDX_NAMES = {  # (images, labels) per split, each with optional .gz
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _find_mnist_idx(cache_dir: Optional[str]):
    """The four raw IDX files in one search dir -> (xtr, ytr, xte, yte)."""
    for d in _search_dirs(cache_dir):
        def resolve(stem):
            for name in (stem, stem + ".gz"):
                p = os.path.join(d, name)
                if os.path.exists(p):
                    return p
            return None

        paths = [resolve(s) for split in ("train", "test") for s in _IDX_NAMES[split]]
        if all(p is not None for p in paths):
            try:
                xtr, ytr, xte, yte = (_read_idx(p) for p in paths)
                return (xtr, ytr, xte, yte), d
            except (OSError, ValueError):
                continue  # corrupt/truncated IDX set: keep searching/fall back
    return None, None


def _cifar_from_pickles(members) -> Dict[str, np.ndarray]:
    """Merge CIFAR pickle batches: {b'data': [N, 3072], b'labels'|b'fine_labels'}."""
    xs, ys = [], []
    for raw in members:
        batch = pickle.loads(raw, encoding="bytes")
        data = np.asarray(batch[b"data"], np.uint8)
        labels = batch.get(b"labels", batch.get(b"fine_labels"))
        if labels is None:  # neither key: raise the callers' catchable error
            raise KeyError("CIFAR batch has neither b'labels' nor b'fine_labels'")
        xs.append(data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        ys.append(np.asarray(labels, np.int64))
    return {"x": np.concatenate(xs), "y": np.concatenate(ys)}


_CIFAR_LAYOUT = {
    # archive/dir name -> (train member basenames, test member basename)
    "cifar-10-batches-py": ([f"data_batch_{i}" for i in range(1, 6)], "test_batch"),
    "cifar-100-python": (["train"], "test"),
}


def _find_cifar_raw(kind: str, cache_dir: Optional[str]):
    """The upstream pickled distribution, extracted dir or .tar.gz."""
    train_names, test_name = _CIFAR_LAYOUT[kind]

    def read_file(path):
        with open(path, "rb") as f:
            return f.read()

    trusted = _trusted_dirs(cache_dir)
    for d in trusted:
        root = os.path.join(d, kind)
        if os.path.isdir(root):
            try:
                tr = _cifar_from_pickles(
                    read_file(os.path.join(root, n)) for n in train_names)
                te = _cifar_from_pickles([read_file(os.path.join(root, test_name))])
                return (tr["x"], tr["y"], te["x"], te["y"]), root
            except (OSError, KeyError, pickle.UnpicklingError):
                pass  # corrupt dir: fall through to the tar in the SAME dir
        tar_path = os.path.join(d, kind.replace("-batches-py", "-python") + ".tar.gz")
        if os.path.exists(tar_path):
            try:
                with tarfile.open(tar_path, "r:gz") as tf:
                    def member(n):
                        return tf.extractfile(f"{kind}/{n}").read()

                    tr = _cifar_from_pickles(member(n) for n in train_names)
                    te = _cifar_from_pickles([member(test_name)])
                return (tr["x"], tr["y"], te["x"], te["y"]), tar_path
            except (OSError, KeyError, tarfile.TarError, pickle.UnpicklingError):
                continue
    # existence-only scan (nothing is unpickled) of the SHARED dirs so a
    # user whose archive sits in ~/.keras/datasets learns why it was
    # skipped instead of silently training on synthetics
    import warnings

    for d in _search_dirs(cache_dir):
        if d in trusted:
            continue
        for name in (kind, kind.replace("-batches-py", "-python") + ".tar.gz"):
            p = os.path.join(d, name)
            if os.path.exists(p):
                warnings.warn(
                    f"found raw CIFAR archive {p!r} but pickled archives are "
                    f"only loaded from explicitly designated dirs (the "
                    f"cache_dir argument or $DKT_DATA_DIR); move the archive "
                    f"to a directory YOU control and designate that — do not "
                    f"designate shared/world-writable dirs, unpickling an "
                    f"attacker-placed archive executes code", stacklevel=3)
                break
    return None, None


def _synthetic_images(num_classes: int, shape: Tuple[int, ...], n_train: int,
                      n_test: int, seed: int, label_noise: float = 0.05,
                      signal_amplitude: float = 7.0):
    """Hard synthetic stand-ins: same shape/dtype as the real set,
    deterministic, and calibrated so accuracy targets take real training.

    Round-2 versions separated in 1-2 epochs, so "wall-clock to target"
    mostly measured compile time.  Now the classes share one base image
    and differ only by a LOW-amplitude prototype delta under heavy pixel
    noise (low per-pixel SNR — the model must average evidence over many
    pixels across many steps), and ``label_noise`` of the TRAIN labels are
    resampled (test stays clean, so the target stays reachable)."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(64.0, 192.0, size=shape).astype(np.float32)
    # low-amplitude class signal with SPATIAL structure: iid pixel deltas
    # are invisible to convolutional inductive bias (a CNN plateaued ~0.87
    # on them), so smooth the per-class pattern with a box blur and
    # renormalize to the target amplitude
    deltas = rng.normal(0.0, 1.0, size=(num_classes,) + shape).astype(np.float32)
    if len(shape) >= 2:
        for axis in (1, 2):  # H and W (leading axis is the class)
            k = 5
            pad = [(0, 0)] * deltas.ndim
            pad[axis] = (k // 2, k // 2)
            padded = np.pad(deltas, pad, mode="wrap")
            deltas = np.mean(np.stack([np.roll(padded, -i, axis=axis)
                                       for i in range(k)]), axis=0)
            sl = [slice(None)] * deltas.ndim
            sl[axis] = slice(0, shape[axis - 1])
            deltas = deltas[tuple(sl)]
    # per-dataset amplitude: the pixel-SNR knob that sets how many epochs
    # of evidence-averaging a conv net needs (calibration notes on each
    # loader; lower = harder)
    deltas *= signal_amplitude / (deltas.std() + 1e-9)

    def make(n, split_seed, noisy_labels):
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, num_classes, size=n)
        # per-sample nuisance offset: the first thing a model fits is NOT
        # the label signal, which buys the later epochs their job
        offset = r.normal(0.0, 16.0, size=(n,) + (1,) * len(shape))
        imgs = base + deltas[labels] + offset \
            + r.normal(0.0, 48.0, size=(n,) + shape)
        seen = labels
        if noisy_labels and label_noise > 0.0:
            flip = r.random(n) < label_noise
            seen = np.where(flip, r.integers(0, num_classes, size=n), labels)
        return np.clip(imgs, 0, 255).astype(np.uint8), seen.astype(np.int64)

    xtr, ytr = make(n_train, seed + 1, noisy_labels=True)
    xte, yte = make(n_test, seed + 2, noisy_labels=False)
    return xtr, ytr, xte, yte


def _to_datasets(x_train, y_train, x_test, y_test, num_classes: int,
                 flatten: bool) -> Tuple[Dataset, Dataset]:
    def prep(x, y):
        feats = np.asarray(x, np.float32) / 255.0
        if feats.ndim == 3:  # grayscale [N, H, W] -> [N, H, W, 1]
            feats = feats[..., None]
        if flatten:
            feats = feats.reshape(len(feats), -1)
        y = np.asarray(y).reshape(-1).astype(np.int32)
        return Dataset({"features": feats,
                        "label": np.eye(num_classes, dtype=np.float32)[y],
                        "label_index": y})

    return prep(x_train, y_train), prep(x_test, y_test)


def _load(filename: str, num_classes: int, image_shape: Tuple[int, ...],
          synthetic_sizes: Tuple[int, int], seed: int, cache_dir: Optional[str],
          synthetic_fallback: bool, flatten: bool, raw_finder=None,
          signal_amplitude: float = 7.0) -> Tuple[Dataset, Dataset, Dict]:
    with obs.span("data.load", dataset=filename):
        train, test, info = _load_inner(
            filename, num_classes, image_shape, synthetic_sizes, seed,
            cache_dir, synthetic_fallback, flatten, raw_finder,
            signal_amplitude)
    if obs.enabled():
        obs.counter("data_loads_total", dataset=filename,
                    synthetic=str(bool(info["synthetic"])).lower()).inc()
    return train, test, info


def _load_inner(filename: str, num_classes: int, image_shape: Tuple[int, ...],
                synthetic_sizes: Tuple[int, int], seed: int,
                cache_dir: Optional[str], synthetic_fallback: bool,
                flatten: bool, raw_finder=None,
                signal_amplitude: float = 7.0) -> Tuple[Dataset, Dataset, Dict]:
    t0 = time.perf_counter()
    path = _find_npz(filename, cache_dir)
    raw = raw_source = None
    if path is None and raw_finder is not None:
        raw, raw_source = raw_finder(cache_dir)
    if path is not None:
        with np.load(path) as z:
            xtr, ytr = z["x_train"], z["y_train"]
            xte, yte = z["x_test"], z["y_test"]
        info = {"synthetic": False, "source": path}
    elif raw is not None:
        xtr, ytr, xte, yte = raw
        info = {"synthetic": False, "source": raw_source}
    elif synthetic_fallback:
        xtr, ytr, xte, yte = _synthetic_images(
            num_classes, image_shape, *synthetic_sizes, seed=seed,
            signal_amplitude=signal_amplitude)
        info = {"synthetic": True,
                "source": f"deterministic synthetic stand-in (no {filename} in "
                          f"{_search_dirs(cache_dir)}; raw pickled archives are "
                          f"honored only in {_trusted_dirs(cache_dir) or 'cache_dir/$DKT_DATA_DIR'})"}
    else:
        raise FileNotFoundError(
            f"{filename} not found in {_search_dirs(cache_dir)} (raw pickled "
            f"archives are honored only in explicitly designated dirs: "
            f"{_trusted_dirs(cache_dir) or 'pass cache_dir= or set $DKT_DATA_DIR'}) "
            "and synthetic_fallback=False (this environment has no network access)")
    train, test = _to_datasets(xtr, ytr, xte, yte, num_classes, flatten)
    info.update(num_classes=num_classes, train_rows=len(train), test_rows=len(test))
    if obs.enabled():
        obs.histogram("data_load_seconds").observe(time.perf_counter() - t0)
    return train, test, info


def load_mnist(cache_dir: Optional[str] = None, synthetic_fallback: bool = True,
               flatten: bool = False) -> Tuple[Dataset, Dataset, Dict]:
    """MNIST digits: features [N, 28, 28, 1] float32 in [0,1] (or flat 784),
    ``label`` one-hot, ``label_index`` int32.  Returns (train, test, info)."""
    return _load("mnist.npz", 10, (28, 28), (60000, 10000), seed=1234,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=flatten, raw_finder=_find_mnist_idx)


def load_cifar10(cache_dir: Optional[str] = None, synthetic_fallback: bool = True
                 ) -> Tuple[Dataset, Dataset, Dict]:
    """CIFAR-10: features [N, 32, 32, 3] float32 in [0,1].

    Synthetic amplitude 3.5 (v5e calibration, 2026-07-31): at the round-3
    default of 7.0 the 32x32x3 CNN separated the classes in 1-2 epochs
    (0.986 after epoch 1), defeating the wall-to-target metric.  At 3.5
    the DOWNPOUR/AEASGD BASELINE configs climb 0.67 -> 0.78 -> 0.88 ->
    0.89 -> 0.90 -> 0.92 and cross their 0.90 target around epoch 5."""
    return _load("cifar10.npz", 10, (32, 32, 3), (50000, 10000), seed=2345,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=False, signal_amplitude=3.5,
                 raw_finder=lambda cd: _find_cifar_raw("cifar-10-batches-py", cd))


def load_cifar100(cache_dir: Optional[str] = None, synthetic_fallback: bool = True
                  ) -> Tuple[Dataset, Dataset, Dict]:
    """CIFAR-100: features [N, 32, 32, 3] float32 in [0,1], 100 classes."""
    return _load("cifar100.npz", 100, (32, 32, 3), (50000, 10000), seed=3456,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=False,
                 raw_finder=lambda cd: _find_cifar_raw("cifar-100-python", cd))
