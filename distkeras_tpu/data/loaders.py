"""Dataset loaders for the BASELINE.md measurement matrix.

Reference parity: the reference's examples fed MNIST / CIFAR / Higgs CSVs
through Spark DataFrames (SURVEY §2.21).  Here loaders produce columnar
:class:`Dataset` pairs directly.

Offline-first design: loaders search local caches for the standard
``.npz`` archives and NEVER download.  When no cache exists they fall back
to deterministic, clearly-labeled synthetic stand-ins with identical
shapes/dtypes (class-prototype clusters — learnable, so accuracy targets
still exercise the full train/eval loop), and the returned ``info`` dict
says so: benchmark records must carry the ``synthetic`` flag.

Cache search order: explicit ``cache_dir`` arg, ``$DKT_DATA_DIR``,
``~/.keras/datasets``, ``~/.cache/distkeras_tpu``, ``./data``.

Expected archive formats (all no-pickle):
- ``mnist.npz``   — keys ``x_train, y_train, x_test, y_test`` (Keras layout)
- ``cifar10.npz`` / ``cifar100.npz`` — same keys; images [N, 32, 32, 3] uint8
  (convert the upstream pickled python batches once, offline, with any tool)
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def _search_dirs(cache_dir: Optional[str]):
    dirs = []
    if cache_dir:
        dirs.append(cache_dir)
    if os.environ.get("DKT_DATA_DIR"):
        dirs.append(os.environ["DKT_DATA_DIR"])
    home = os.path.expanduser("~")
    dirs += [os.path.join(home, ".keras", "datasets"),
             os.path.join(home, ".cache", "distkeras_tpu"),
             os.path.join(os.getcwd(), "data")]
    return dirs


def _find_npz(filename: str, cache_dir: Optional[str]) -> Optional[str]:
    for d in _search_dirs(cache_dir):
        path = os.path.join(d, filename)
        if os.path.exists(path):
            return path
    return None


def _synthetic_images(num_classes: int, shape: Tuple[int, ...], n_train: int,
                      n_test: int, seed: int):
    """Class-prototype images + noise: same shape/dtype as the real set,
    deterministic, and separable enough that accuracy targets are
    meaningful for the training loop being measured."""
    rng = np.random.default_rng(seed)
    protos = rng.uniform(0.0, 255.0, size=(num_classes,) + shape).astype(np.float32)

    def make(n, split_seed):
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, num_classes, size=n)
        imgs = protos[labels] + r.normal(0.0, 64.0, size=(n,) + shape).astype(np.float32)
        return np.clip(imgs, 0, 255).astype(np.uint8), labels.astype(np.int64)

    xtr, ytr = make(n_train, seed + 1)
    xte, yte = make(n_test, seed + 2)
    return xtr, ytr, xte, yte


def _to_datasets(x_train, y_train, x_test, y_test, num_classes: int,
                 flatten: bool) -> Tuple[Dataset, Dataset]:
    def prep(x, y):
        feats = np.asarray(x, np.float32) / 255.0
        if feats.ndim == 3:  # grayscale [N, H, W] -> [N, H, W, 1]
            feats = feats[..., None]
        if flatten:
            feats = feats.reshape(len(feats), -1)
        y = np.asarray(y).reshape(-1).astype(np.int32)
        return Dataset({"features": feats,
                        "label": np.eye(num_classes, dtype=np.float32)[y],
                        "label_index": y})

    return prep(x_train, y_train), prep(x_test, y_test)


def _load(filename: str, num_classes: int, image_shape: Tuple[int, ...],
          synthetic_sizes: Tuple[int, int], seed: int, cache_dir: Optional[str],
          synthetic_fallback: bool, flatten: bool
          ) -> Tuple[Dataset, Dataset, Dict]:
    path = _find_npz(filename, cache_dir)
    if path is not None:
        with np.load(path) as z:
            xtr, ytr = z["x_train"], z["y_train"]
            xte, yte = z["x_test"], z["y_test"]
        info = {"synthetic": False, "source": path}
    elif synthetic_fallback:
        xtr, ytr, xte, yte = _synthetic_images(
            num_classes, image_shape, *synthetic_sizes, seed=seed)
        info = {"synthetic": True,
                "source": f"deterministic synthetic stand-in (no {filename} in "
                          f"{_search_dirs(cache_dir)})"}
    else:
        raise FileNotFoundError(
            f"{filename} not found in {_search_dirs(cache_dir)} and "
            f"synthetic_fallback=False (this environment has no network access)")
    train, test = _to_datasets(xtr, ytr, xte, yte, num_classes, flatten)
    info.update(num_classes=num_classes, train_rows=len(train), test_rows=len(test))
    return train, test, info


def load_mnist(cache_dir: Optional[str] = None, synthetic_fallback: bool = True,
               flatten: bool = False) -> Tuple[Dataset, Dataset, Dict]:
    """MNIST digits: features [N, 28, 28, 1] float32 in [0,1] (or flat 784),
    ``label`` one-hot, ``label_index`` int32.  Returns (train, test, info)."""
    return _load("mnist.npz", 10, (28, 28), (60000, 10000), seed=1234,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=flatten)


def load_cifar10(cache_dir: Optional[str] = None, synthetic_fallback: bool = True
                 ) -> Tuple[Dataset, Dataset, Dict]:
    """CIFAR-10: features [N, 32, 32, 3] float32 in [0,1]."""
    return _load("cifar10.npz", 10, (32, 32, 3), (50000, 10000), seed=2345,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=False)


def load_cifar100(cache_dir: Optional[str] = None, synthetic_fallback: bool = True
                  ) -> Tuple[Dataset, Dataset, Dict]:
    """CIFAR-100: features [N, 32, 32, 3] float32 in [0,1], 100 classes."""
    return _load("cifar100.npz", 100, (32, 32, 3), (50000, 10000), seed=3456,
                 cache_dir=cache_dir, synthetic_fallback=synthetic_fallback,
                 flatten=False)
