"""Text preprocessing: the Keras-1-era ``Tokenizer`` / ``pad_sequences``
surface.

The reference trains whatever the user's Keras pipeline produced, and the
era's text workflows (IMDB sentiment etc.) universally used
``keras.preprocessing.text.Tokenizer`` + ``pad_sequences`` before the
Embedding/LSTM stack; without them the recurrent family here
(``models/rnn.py``) and ``sequential`` embed stacks have no on-ramp from
raw text.  Host-side numpy — tokenization is IO-bound prep work, not chip
work; the output feeds straight into a ``Dataset`` column.
"""

from __future__ import annotations

import json
import re
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

# a PLAIN character list (Keras's default set), not regex syntax: real tab
# and newline, one real backslash — _split escapes each char itself
_DEFAULT_FILTERS = '!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n'


class Tokenizer:
    """Word-index tokenizer (Keras semantics).

    - index 0 is reserved for padding (never assigned to a word);
    - ``num_words`` caps the vocabulary to the most frequent words at
      *encode* time (ranks computed over everything seen by ``fit``);
    - out-of-vocabulary words are dropped unless ``oov_token`` is set, in
      which case they map to its (stable) index 1.
    """

    def __init__(self, num_words: Optional[int] = None, lower: bool = True,
                 filters: str = _DEFAULT_FILTERS, oov_token: Optional[str] = None):
        self.num_words = num_words
        self.lower = lower
        self.filters = filters
        self.oov_token = oov_token
        self.word_counts: Dict[str, int] = {}
        self.word_index: Dict[str, int] = {}

    def _split(self, text: str) -> List[str]:
        if self.lower:
            text = text.lower()
        if self.filters:
            # filters is a plain character list (Keras semantics), not regex
            # syntax — escape every character before building the class
            text = re.sub("[" + re.escape(self.filters) + "]", " ", text)
        return text.split()

    def _rebuild_index(self) -> None:
        """Recompute word_index from word_counts: frequency desc, then
        alphabetical for ties, so two fits on the same corpus agree
        exactly.  The oov token always keeps index 1, even if it also
        occurs as a corpus word."""
        start = 1
        self.word_index = {}
        if self.oov_token is not None:
            self.word_index[self.oov_token] = 1
            start = 2
        ranked = sorted((w for w in self.word_counts if w != self.oov_token),
                        key=lambda w: (-self.word_counts[w], w))
        for i, w in enumerate(ranked):
            self.word_index[w] = i + start

    def fit_on_texts(self, texts: Iterable[str]) -> "Tokenizer":
        for text in texts:
            for w in self._split(text):
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
        self._rebuild_index()
        return self

    def _effective_vocab(self) -> int:
        """Highest index + 1 the encoder can emit under ``num_words``."""
        if not self.word_index:
            return 1
        if self.num_words is None:
            return max(self.word_index.values()) + 1
        return min(self.num_words, max(self.word_index.values()) + 1)

    @property
    def vocab_size(self) -> int:
        """Pass as ``vocab_size``/``embed`` size: indices are < this."""
        return self._effective_vocab()

    def texts_to_sequences(self, texts: Iterable[str]) -> List[List[int]]:
        if not self.word_index:
            raise ValueError("fit_on_texts must run before texts_to_sequences")
        cap = self._effective_vocab()
        oov = self.word_index.get(self.oov_token) if self.oov_token else None
        out = []
        for text in texts:
            seq = []
            for w in self._split(text):
                idx = self.word_index.get(w)
                if idx is not None and idx < cap:
                    seq.append(idx)
                elif oov is not None:
                    seq.append(oov)
            out.append(seq)
        return out

    # -- persistence (no pickle, like everything else here) -------------------
    def to_json(self) -> str:
        return json.dumps({
            "num_words": self.num_words, "lower": self.lower,
            "filters": self.filters, "oov_token": self.oov_token,
            "word_counts": self.word_counts,
        })

    @staticmethod
    def from_json(blob: str) -> "Tokenizer":
        d = json.loads(blob)
        tok = Tokenizer(num_words=d["num_words"], lower=d["lower"],
                        filters=d["filters"], oov_token=d["oov_token"])
        tok.word_counts = {k: int(v) for k, v in d["word_counts"].items()}
        tok._rebuild_index()
        return tok


def pad_sequences(sequences: Sequence[Sequence[int]], maxlen: Optional[int] = None,
                  padding: str = "pre", truncating: str = "pre",
                  value: int = 0, dtype=np.int32) -> np.ndarray:
    """[N] ragged int sequences -> [N, maxlen] array (Keras semantics:
    default PRE-padding/truncation — the convention LSTM workflows assume,
    keeping the informative tail adjacent to the final hidden state)."""
    if padding not in ("pre", "post") or truncating not in ("pre", "post"):
        raise ValueError("padding/truncating must be 'pre' or 'post'")
    seqs = [list(s) for s in sequences]
    if maxlen is None:
        maxlen = max((len(s) for s in seqs), default=0)
    out = np.full((len(seqs), maxlen), value, dtype=dtype)
    for i, s in enumerate(seqs):
        if not s:
            continue
        if len(s) > maxlen:
            # len(s) - maxlen, not -maxlen: s[-0:] would keep everything
            s = s[len(s) - maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "pre":
            out[i, maxlen - len(s):] = s
        else:
            out[i, :len(s)] = s
    return out
