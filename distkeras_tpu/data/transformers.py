"""Feature/label transformers (reference parity: ``distkeras/transformers.py``).

The reference shipped Spark-ML-style objects with ``.transform(dataframe)``
that mapped a Python function over DataFrame rows.  TPU-native design: each
transformer is a thin object whose math lives in a jit'd vectorized pure
function applied to whole columns at once (no per-row Python), returning a
new ``Dataset`` with the output column appended.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Transformer:
    """Base: subclasses implement ``transform(dataset) -> Dataset``."""

    def transform(self, dataset: Dataset) -> Dataset:  # pragma: no cover - interface
        raise NotImplementedError


class OneHotTransformer(Transformer):
    """Integer label column -> one-hot float column.

    Reference: ``OneHotTransformer(output_dim, input_col, output_col)``.
    """

    def __init__(self, output_dim: int, input_col: str = "label", output_col: str = "label_onehot"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col
        self._fn = jax.jit(lambda x: jax.nn.one_hot(x.astype(jnp.int32), output_dim))

    def transform(self, dataset: Dataset) -> Dataset:
        col = dataset[self.input_col]
        if col.ndim > 1:
            col = col.reshape(len(col))
        out = np.asarray(self._fn(jnp.asarray(col)))
        return dataset.with_column(self.output_col, out)


class MinMaxTransformer(Transformer):
    """Affine rescale of a feature column to [o_min, o_max].

    Reference: ``MinMaxTransformer(o_min, o_max, input_col, output_col)``
    which rescaled using the *known* data range (n_min/n_max ctor args).
    """

    def __init__(self, o_min: float = 0.0, o_max: float = 1.0, n_min: float = 0.0, n_max: float = 255.0,
                 input_col: str = "features", output_col: str = "features_normalized"):
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.input_col, self.output_col = input_col, output_col
        scale = (self.o_max - self.o_min) / (self.n_max - self.n_min)
        self._fn = jax.jit(lambda x: (x.astype(jnp.float32) - self.n_min) * scale + self.o_min)

    def transform(self, dataset: Dataset) -> Dataset:
        out = np.asarray(self._fn(jnp.asarray(dataset[self.input_col])))
        return dataset.with_column(self.output_col, out)


class ReshapeTransformer(Transformer):
    """Reshape each row of a flat feature column to a tensor shape.

    Reference: ``ReshapeTransformer(input_col, output_col, shape)`` used to
    turn flat MNIST vectors into (28, 28, 1) images for CNNs.
    """

    def __init__(self, input_col: str, output_col: str, shape: Sequence[int]):
        self.input_col, self.output_col = input_col, output_col
        self.shape = tuple(int(s) for s in shape)

    def transform(self, dataset: Dataset) -> Dataset:
        col = dataset[self.input_col]
        out = col.reshape((len(col),) + self.shape)
        return dataset.with_column(self.output_col, out)


class DenseTransformer(Transformer):
    """Sparse (indices, values, size) rows -> dense vectors.

    Reference: ``DenseTransformer`` converted Spark SparseVectors to
    DenseVectors.  Here sparsity is represented as two aligned columns of
    padded indices/values (pad index = -1) plus a fixed output size.
    """

    def __init__(self, size: int, indices_col: str = "indices", values_col: str = "values",
                 output_col: str = "features"):
        self.size = int(size)
        self.indices_col, self.values_col, self.output_col = indices_col, values_col, output_col

        def densify(indices, values):
            valid = indices >= 0
            safe = jnp.where(valid, indices, 0).astype(jnp.int32)
            contrib = jnp.where(valid, values, 0.0).astype(jnp.float32)
            out = jnp.zeros((indices.shape[0], self.size), dtype=jnp.float32)
            return out.at[jnp.arange(indices.shape[0])[:, None], safe].add(contrib)

        self._fn = jax.jit(densify)

    def transform(self, dataset: Dataset) -> Dataset:
        out = np.asarray(self._fn(jnp.asarray(dataset[self.indices_col]), jnp.asarray(dataset[self.values_col])))
        return dataset.with_column(self.output_col, out)


class LabelIndexTransformer(Transformer):
    """Prediction vector column -> argmax class index.

    Reference: ``LabelIndexTransformer(output_dim, input_col='prediction',
    output_col='prediction_index')`` — the bridge between ``ModelPredictor``
    output and ``AccuracyEvaluator`` input.
    """

    def __init__(self, output_dim: Optional[int] = None, input_col: str = "prediction",
                 output_col: str = "prediction_index"):
        self.output_dim = output_dim  # kept for reference API parity; argmax needs no dim
        self.input_col, self.output_col = input_col, output_col
        self._fn = jax.jit(lambda x: jnp.argmax(x, axis=-1).astype(jnp.int32))

    def transform(self, dataset: Dataset) -> Dataset:
        out = np.asarray(self._fn(jnp.asarray(dataset[self.input_col])))
        return dataset.with_column(self.output_col, out)
