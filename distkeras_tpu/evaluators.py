"""Evaluators (reference parity: ``distkeras/evaluators.py``).

Reference: ``AccuracyEvaluator(prediction_col, label_col).evaluate(df)``
computed classification accuracy by comparing two DataFrame columns.
Here the comparison is one jit'd reduction over whole columns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover - interface
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction matches label.

    Accepts class-index columns, one-hot/probability-vector columns, or a
    mix (vectors are argmax'd) — covering both the reference usage
    (``LabelIndexTransformer`` output vs integer label) and direct logits.
    """

    def __init__(self, prediction_col: str = "prediction_index", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

        def acc(pred, label):
            if pred.ndim > 1:
                pred = jnp.argmax(pred, axis=-1)
            if label.ndim > 1:
                label = jnp.argmax(label, axis=-1)
            return jnp.mean((pred.astype(jnp.int32) == label.astype(jnp.int32)).astype(jnp.float32))

        self._fn = jax.jit(acc)

    def evaluate(self, dataset: Dataset) -> float:
        return float(self._fn(jnp.asarray(dataset[self.prediction_col]), jnp.asarray(dataset[self.label_col])))
