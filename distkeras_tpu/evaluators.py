"""Evaluators (reference parity: ``distkeras/evaluators.py``).

Reference: ``AccuracyEvaluator(prediction_col, label_col).evaluate(df)``
computed classification accuracy by comparing two DataFrame columns.
Here the comparison is one jit'd reduction over whole columns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover - interface
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction matches label.

    Accepts class-index columns, one-hot/probability-vector columns, or a
    mix (vectors are argmax'd) — covering both the reference usage
    (``LabelIndexTransformer`` output vs integer label) and direct logits.
    """

    def __init__(self, prediction_col: str = "prediction_index", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

        def acc(pred, label):
            p, l = _pred_to_index(pred), _to_index(label)
            if p.shape != l.shape:
                # e.g. an INTEGER-dtype one-hot label column: integer arrays
                # are always treated as class indices (so (B, T) token
                # labels survive), which would otherwise broadcast into a
                # silently wrong accuracy whenever shapes happen to align
                raise ValueError(
                    f"prediction indices {p.shape} vs label indices {l.shape}: "
                    "shapes must match after index conversion. Integer label "
                    "columns are taken as class indices whatever their rank — "
                    "convert one-hot labels to float, or argmax them first")
            return jnp.mean((p == l).astype(jnp.float32))

        self._fn = jax.jit(acc)

    def evaluate(self, dataset: Dataset) -> float:
        return float(self._fn(jnp.asarray(dataset[self.prediction_col]), jnp.asarray(dataset[self.label_col])))


def _to_index(col: jnp.ndarray) -> jnp.ndarray:
    """Class-index or one-hot/probability column -> int32 class indices.

    A trailing size-1 axis is an index column wearing a column shape
    ((N, 1) from dataframe-style sources), NOT a one-class one-hot —
    argmax over it would collapse every row to 0.  Integer arrays are
    ALWAYS indices whatever their rank ((B, T) token labels stay (B, T));
    only float arrays argmax over the class axis."""
    if col.ndim > 1 and col.shape[-1] == 1:
        col = col[..., 0]
    if col.ndim > 1 and not jnp.issubdtype(col.dtype, jnp.integer):
        col = jnp.argmax(col, axis=-1)
    return col.astype(jnp.int32)


def _pred_to_index(col: jnp.ndarray) -> jnp.ndarray:
    """Model-output column -> int32 class indices.

    Differs from ``_to_index`` on 1-D (or (N, 1)) FLOAT columns: a model's
    scalar output is a single-logit binary score (class = logit > 0, the
    raw-logit convention the trainers' validation path also uses), not a
    float-coded class id — truncating 2.7 to class 2 would be noise."""
    if col.ndim > 1 and col.shape[-1] == 1:
        col = col[..., 0]
    if col.ndim > 1:
        col = jnp.argmax(col, axis=-1)
    elif not jnp.issubdtype(col.dtype, jnp.integer):
        col = col > 0
    return col.astype(jnp.int32)


class TopKAccuracyEvaluator(Evaluator):
    """Fraction of rows whose true class is in the top-k predictions.

    Needs a vector prediction column (logits/probabilities); beyond the
    reference surface (which had accuracy only), standard for the CIFAR/
    ImageNet-style configs in BASELINE.md.
    """

    def __init__(self, k: int = 5, prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.k = int(k)
        self.prediction_col = prediction_col
        self.label_col = label_col

        def topk(pred, label):
            if pred.ndim < 2:
                raise ValueError("TopKAccuracyEvaluator needs a vector "
                                 "prediction column (logits/probabilities)")
            label = _to_index(label)
            _, idx = jax.lax.top_k(pred, self.k)
            return jnp.mean(jnp.any(idx == label[:, None], axis=-1).astype(jnp.float32))

        self._fn = jax.jit(topk)

    def evaluate(self, dataset: Dataset) -> float:
        return float(self._fn(jnp.asarray(dataset[self.prediction_col]),
                              jnp.asarray(dataset[self.label_col])))


class ConfusionMatrixEvaluator(Evaluator):
    """num_classes x num_classes counts: rows = true class, cols = predicted.

    ``evaluate`` returns the matrix as a numpy int array (not a float) —
    the building block for any derived metric.
    """

    def __init__(self, num_classes: int, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self.num_classes = int(num_classes)
        self.prediction_col = prediction_col
        self.label_col = label_col

        def confusion(pred, label):
            pred, label = _pred_to_index(pred), _to_index(label)
            c = self.num_classes
            # out-of-range indices (e.g. the common -1 "ignore" sentinel, or
            # an index >= num_classes) must not clamp into bin 0 / vanish —
            # route them to an overflow bin that is sliced off
            valid = (pred >= 0) & (pred < c) & (label >= 0) & (label < c)
            flat = jnp.where(valid, label * c + pred, c * c)
            counts = jnp.bincount(flat, length=c * c + 1)
            return counts[: c * c].reshape(c, c)

        self._fn = jax.jit(confusion)

    def evaluate(self, dataset: Dataset) -> np.ndarray:
        return np.asarray(self._fn(jnp.asarray(dataset[self.prediction_col]),
                                   jnp.asarray(dataset[self.label_col])))


class PrecisionRecallF1Evaluator(Evaluator):
    """Per-class precision/recall/F1 plus macro averages, from the
    confusion matrix.  ``evaluate`` returns a dict:
    ``{"precision": [C], "recall": [C], "f1": [C], "macro_precision": x,
    "macro_recall": x, "macro_f1": x}`` (zero-division yields 0, the
    sklearn ``zero_division=0`` convention).
    """

    def __init__(self, num_classes: int, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self._confusion = ConfusionMatrixEvaluator(num_classes, prediction_col, label_col)

    def evaluate(self, dataset: Dataset) -> dict:
        cm = self._confusion.evaluate(dataset).astype(np.float64)
        tp = np.diag(cm)
        pred_tot = cm.sum(axis=0)
        true_tot = cm.sum(axis=1)
        with np.errstate(divide="ignore", invalid="ignore"):
            precision = np.where(pred_tot > 0, tp / pred_tot, 0.0)
            recall = np.where(true_tot > 0, tp / true_tot, 0.0)
            denom = precision + recall
            f1 = np.where(denom > 0, 2 * precision * recall / denom, 0.0)
        return {
            "precision": precision, "recall": recall, "f1": f1,
            "macro_precision": float(precision.mean()),
            "macro_recall": float(recall.mean()),
            "macro_f1": float(f1.mean()),
        }
