"""Runnable example workflows (reference parity: ``examples/`` notebooks).

Installed with the package so ``distkeras-workflow`` works from any CWD;
the repo-root ``examples/`` directory keeps thin shims for discoverability.
"""
