"""BASELINE.md measurement matrix runner (configs 1-5).

Runs each config end to end — load data, train, evaluate after every
epoch — and reports samples/sec/chip plus wall-clock-to-target-accuracy,
the two halves of the headline metric.  One JSON line per config; a
summary table at the end; optionally writes ``BASELINE_RESULTS.json``.

Offline environments run on the loaders' deterministic synthetic
stand-ins (flagged in every record); drop real ``mnist.npz`` /
``cifar10.npz`` / ``cifar100.npz`` into a cache dir (see
``data/loaders.py``) to measure the real thing.

Usage:
    distkeras-baseline --config all --epochs-cap 10
    distkeras-baseline --config 2 --cpu 8        # simulate an 8-chip slice
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional


def _evaluate(model, test_ds) -> float:
    from distkeras_tpu.data.transformers import LabelIndexTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.predictors import ModelPredictor

    scored = ModelPredictor(model, features_col="features").predict(test_ds)
    scored = LabelIndexTransformer(scored["label"].shape[-1]).transform(scored)
    return AccuracyEvaluator(prediction_col="prediction_index",
                             label_col="label_index").evaluate(scored)


def _steady_rate(trainer, train_ds, reps: int = 3, max_windows: int = 64) -> float:
    """In-program steady-state samples/sec/chip (round-2 weak #7 fix): the
    multi-epoch program amortizes per-dispatch relay overhead, so this
    column reflects chip throughput — unlike the wall columns, which also
    bill host feeding and ~100ms relay RPCs per dispatch."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.trainers import DistributedTrainer

    cols = [trainer.features_col, trainer.label_col]
    if isinstance(trainer, DistributedTrainer):
        window = trainer.communication_window
        global_batch = trainer.batch_size * trainer.num_workers
        chunk = next(iter(train_ds.chunked_epoch(
            global_batch, cols, window=window, chunk_windows=max_windows)))
        engine = trainer.engine
        state = engine.init_state(trainer.model)
        return engine.steady_state_rate(
            state, chunk[trainer.features_col], chunk[trainer.label_col], reps=reps)

    # SingleTrainer: same shape as the headline MNIST bench — an outer scan
    # over reps of the inner per-batch scan, one compiled program.  Reject
    # dropout-bearing specs like the engine path does: silently timing the
    # eval-mode forward would overstate the steady rate
    trainer.model.spec.reject_rng_spec("_steady_rate")
    from distkeras_tpu.parallel.engine import make_minibatch_step

    chunk = next(iter(train_ds.chunked_epoch(
        trainer.batch_size, cols, window=1, chunk_windows=max_windows * 4)))
    xs = jnp.asarray(chunk[trainer.features_col].squeeze(1))
    ys = jnp.asarray(chunk[trainer.label_col].squeeze(1))
    mini = make_minibatch_step(trainer.model.spec.apply_fn(), trainer.loss,
                               trainer.optimizer)

    @jax.jit
    def multi(params, opt_state, xs, ys):
        def one_pass(carry, _):
            carry, losses = jax.lax.scan(mini, carry, (xs, ys))
            return carry, losses[-1]

        (params, opt_state), last = jax.lax.scan(
            one_pass, (params, opt_state), None, length=reps)
        return params, opt_state, last

    params = jax.tree.map(jnp.array, trainer.model.params)
    opt_state = trainer.optimizer.init(params)
    _, _, last = multi(params, opt_state, xs, ys)
    np.asarray(last)
    samples = reps * xs.shape[0] * xs.shape[1]
    rates = []
    for _ in range(3):
        t0 = _time.perf_counter()
        _, _, last = multi(params, opt_state, xs, ys)
        np.asarray(last)
        rates.append(samples / (_time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2]


def run_config(num: int, epochs_cap: int, batch_size: Optional[int] = None,
               synthetic_target: Optional[float] = None) -> Dict[str, Any]:
    """Train one BASELINE config to its accuracy target (or the epoch cap);
    returns the metric record."""
    import jax

    from distkeras_tpu import (ADAG, AEASGD, DOWNPOUR, DynSGD, SingleTrainer)
    from distkeras_tpu.data.loaders import load_cifar10, load_cifar100, load_mnist
    from distkeras_tpu.models.cnn import cifar_cnn_spec, mnist_cnn_spec
    from distkeras_tpu.models.mlp import mnist_mlp_spec
    from distkeras_tpu.models.resnet import resnet20_spec

    # (name, trainer class, trainer kwargs, spec, loader,
    #  real-data target, synthetic target).  Synthetic targets are
    # calibrated per shape on v5e so every config needs multiple epochs of
    # REAL training: the CIFAR-10 stand-in runs at signal amplitude 3.5
    # (2026-07-31 recalibration — at the old 7.0 the CNN configs hit 0.99
    # in 2 epochs, defeating wall-to-target; at 3.5 / target 0.90 they
    # cross around epoch 5), and 100-way classification plateaus near
    # 0.73 on the amplitude-7.0 generator (bar 0.70, first crossed at
    # epoch 14 in the recorded v5e run — see BASELINE_RESULTS.json).
    configs = {
        1: ("SingleTrainer MLP/MNIST", SingleTrainer, {},
            mnist_mlp_spec(), lambda: load_mnist(flatten=True), 0.97, 0.95),
        2: ("ADAG CNN/MNIST", ADAG, {"communication_window": 4},
            mnist_cnn_spec(), lambda: load_mnist(), 0.99, 0.95),
        3: ("AEASGD CNN/CIFAR-10", AEASGD, {"communication_window": 8, "rho": 1.0},
            cifar_cnn_spec(), lambda: load_cifar10(), 0.70, 0.90),
        4: ("DOWNPOUR CNN/CIFAR-10", DOWNPOUR, {"communication_window": 4},
            cifar_cnn_spec(), lambda: load_cifar10(), 0.70, 0.90),
        5: ("DynSGD ResNet-20/CIFAR-100", DynSGD, {"communication_window": 4},
            resnet20_spec(num_outputs=100), lambda: load_cifar100(), 0.40, 0.70),
    }
    name, cls, kwargs, spec, loader, real_target, synth_target = configs[num]
    train_ds, test_ds, info = loader()
    if synthetic_target is not None:
        synth_target = synthetic_target
    target = synth_target if info["synthetic"] else real_target
    bs = batch_size or (64 if num >= 3 else 128)
    lr = 0.05 if num != 5 else 0.02

    trainer = cls(spec, loss="categorical_crossentropy", worker_optimizer="sgd",
                  learning_rate=lr, batch_size=bs, num_epoch=1, seed=0, **kwargs)

    samples_per_epoch = len(train_ds)
    accs: List[float] = []
    epoch_walls: List[float] = []  # per-epoch train+eval wall (round-3
    # verdict weak #6: single-shot wall columns on a shared relayed chip
    # swung 2-8x with tenancy; the per-epoch spread makes the noise visible
    # and the median gives a de-noised wall estimate)
    t0 = time.perf_counter()
    t_target = None
    for epoch in range(epochs_cap):
        # distinct shuffle order per outer epoch: each train() call runs its
        # internal epoch 0, whose shuffle seed is trainer.seed + 0
        trainer.seed = epoch
        t_ep = time.perf_counter()
        trainer.train(train_ds, shuffle=True)
        acc = float(_evaluate(trainer.model, test_ds))
        epoch_walls.append(time.perf_counter() - t_ep)
        accs.append(round(acc, 4))
        if t_target is None and acc >= target:
            t_target = time.perf_counter() - t0
            break
    wall = time.perf_counter() - t0
    # one extra epoch AFTER the target: the trainer's epoch program is
    # cached across train() calls (SingleTrainer._epoch_fn / the engine on
    # DistributedTrainer), so this record is the steady-state rate
    trainer.seed = epochs_cap
    trainer.train(train_ds, shuffle=True)
    # chips actually engaged by this trainer (SingleTrainer=1, mesh trainers
    # = replica count) — NOT jax.device_count()
    n_chips = trainer.metrics[-1]["chips"] if trainer.metrics else jax.device_count()
    epochs_run = len(accs)
    # the first epoch pays compilation; the median of the REMAINING epochs
    # is the de-noised per-epoch wall (falls back to all epochs when only
    # one ran).  spread = (max-min)/median over the same set.
    import statistics

    steady_walls = epoch_walls[1:] or epoch_walls
    if steady_walls:
        ep_median = statistics.median(steady_walls)
        ep_spread = ((max(steady_walls) - min(steady_walls)) / ep_median
                     if ep_median else 0.0)
    else:  # epochs_cap = 0: degenerate but must not crash
        ep_median = ep_spread = 0.0
    return {
        "config": num,
        "name": name,
        "data": "synthetic" if info["synthetic"] else "real",
        "chips": n_chips,
        "platform": jax.default_backend(),
        "batch_size": bs,
        "epochs_run": epochs_run,
        "accuracy": accs,
        "target": target,
        "target_reached": t_target is not None,
        "wall_to_target_s": round(t_target, 2) if t_target is not None else None,
        # single-shot wall above is tenancy-exposed; these qualify it:
        "epoch_walls_s": [round(w, 2) for w in epoch_walls],
        "epoch_wall_median_s": round(ep_median, 2),
        "epoch_wall_spread": round(ep_spread, 3),
        "wall_to_target_denoised_s": (
            round(epoch_walls[0] + ep_median * (epochs_run - 1), 2)
            if t_target is not None else None),
        # wall-inclusive rate (compile + train + eval — the user experience)
        "samples_per_sec_per_chip_wall": round(
            epochs_run * samples_per_epoch / wall / n_chips, 1),
        # best per-epoch rate from the trainer's own metrics — still billed
        # for host feeding + one relay dispatch per epoch
        "samples_per_sec_per_chip_train": max(
            (m["samples_per_sec_per_chip"] for m in trainer.metrics), default=None),
        # in-program multi-epoch rate (see _steady_rate): wall-timed over
        # one compiled program — comparable to the bench headline's v2
        # wall tag, NOT its round-4 v3 device tag, which additionally
        # excludes the ~100ms relay dispatch (a ~10-20% gap, not a
        # regression)
        "samples_per_sec_per_chip_steady": round(_steady_rate(trainer, train_ds), 1),
        "final_loss": round(trainer.history[-1], 4) if trainer.history else None,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="BASELINE.md config matrix runner")
    parser.add_argument("--config", default="all",
                        help="1-5 or 'all'")
    parser.add_argument("--cpu", type=int, default=0,
                        help="simulate this many CPU devices instead of real chips")
    # default cap sized for the HARDEST config on the round-3 synthetics
    # (config 5 crosses its 0.70 bar around epoch 14)
    parser.add_argument("--epochs-cap", type=int, default=18)
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--out", default=None, help="write records to this JSON file")
    args = parser.parse_args(argv)

    if args.cpu:
        from distkeras_tpu.platform import pin_cpu_devices

        pin_cpu_devices(args.cpu)

    nums = [1, 2, 3, 4, 5] if args.config == "all" else [int(args.config)]
    records = []
    for n in nums:
        rec = run_config(n, epochs_cap=args.epochs_cap, batch_size=args.batch_size)
        records.append(rec)
        print(json.dumps(rec), flush=True)

    ok = all(r["target_reached"] for r in records)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=2)
    if not ok:
        print("WARNING: some configs missed their accuracy target", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
