"""Row-sparse CTR training walkthrough (ISSUE 9).

The millions-of-users workload: an embedding table that dwarfs the dense
model, batches that touch a few hundred of its rows, and a parameter
service that moves ONLY those rows.  This example drives the whole
row-sparse PS stack end to end on a synthetic CTR log:

1.  **data**    — :func:`distkeras_tpu.data.ctr.synthetic_ctr_dataset`:
    skewed categorical id columns + a learnable click label;
2.  **model**   — ``embedding_classifier`` (one shared ``[rows, dim]``
    table declared as an EmbeddingTable leaf via ``sparse_param_names``);
3.  **train**   — ``AsyncADAG(sparse_tables="auto")``: workers pull only
    the rows each window's batches touch (wire action ``S``/``V``) and
    commit ``(row_ids, row_grads)`` pairs (``U``), applied by the hub
    under the ordinary staleness clock;
4.  **compare** — the same run dense (``sparse_tables=None``), printing
    the hub's wire-byte counters side by side — the "idle rows cost zero
    wire bytes" claim as two numbers.

Usage:
    python -m distkeras_tpu.examples.ctr_workflow          # defaults
    distkeras-ctr --rows 100000 --dim 32                   # bigger table
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rows", type=int, default=20000,
                        help="embedding-table vocabulary size")
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--fields", type=int, default=4,
                        help="categorical id columns per impression")
    parser.add_argument("--samples", type=int, default=8192)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--window", type=int, default=4,
                        help="communication window (batches per exchange)")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--hot-fraction", type=float, default=0.01,
                        help="fraction of ids receiving most traffic")
    parser.add_argument("--cache-rows", type=int, default=None,
                        help="hot-tier client cache size in rows (ISSUE "
                             "15): bound each worker's host cache to this "
                             "many rows per table instead of the full "
                             "vocabulary; size it from the hub's "
                             "ps.sparse_hot_rows estimate (~2x the hot "
                             "set)")
    parser.add_argument("--vocab-sizes", type=str, default=None,
                        help="comma-separated per-field vocabulary sizes "
                             "(ISSUE 15 multi-table mode: one independent "
                             "embedding table per field; overrides --rows/"
                             "--fields)")
    args = parser.parse_args(argv)

    from distkeras_tpu import observability as obs
    from distkeras_tpu.data.ctr import synthetic_ctr_dataset, \
        touched_row_fraction
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.embedding import ctr_embedding_spec
    from distkeras_tpu.runtime.async_trainer import AsyncADAG

    if args.vocab_sizes:
        vocabs = [int(v) for v in args.vocab_sizes.split(",")]
        rows_spec, fields, total_rows = vocabs, len(vocabs), max(vocabs)
    else:
        rows_spec, fields, total_rows = args.rows, args.fields, args.rows
    ds = synthetic_ctr_dataset(args.samples, rows_spec, fields=fields,
                               hot_fraction=args.hot_fraction, seed=0)
    frac = touched_row_fraction(ds["features"], total_rows,
                                args.batch_size, args.window)
    print(f"CTR log: {args.samples} impressions, vocab {rows_spec}, "
          f"{fields} fields; one window touches "
          f"~{100.0 * frac:.2f}% of the largest table's rows")
    spec = ctr_embedding_spec(rows_spec, dim=args.dim, fields=fields)

    def run(sparse):
        obs.enable()
        obs.reset()
        trainer = AsyncADAG(Model.init(spec, seed=0),
                            loss="categorical_crossentropy",
                            batch_size=args.batch_size,
                            num_epoch=args.epochs, learning_rate=0.05,
                            seed=0, num_workers=args.workers,
                            communication_window=args.window,
                            sparse_tables="auto" if sparse else None,
                            sparse_cache_rows=(args.cache_rows if sparse
                                               else None))
        model = trainer.train(ds, shuffle=False)
        snap = obs.snapshot()
        wire = (snap["counters"].get("ps_pull_bytes_total", 0.0)
                + snap["counters"].get("ps_commit_bytes_total", 0.0))
        rows_moved = (snap["counters"].get("ps.sparse_rows_pulled", 0.0)
                      + snap["counters"].get("ps.sparse_rows_committed", 0.0))
        saved = snap["counters"].get("ps.sparse_wire_bytes_saved", 0.0)
        obs.disable()
        obs.reset()
        loss = trainer.history[-1] if trainer.history else float("nan")
        return model, wire, rows_moved, saved, loss

    _, wire_sparse, rows_moved, saved, loss_s = run(sparse=True)
    _, wire_dense, _, _, loss_d = run(sparse=False)
    print(f"sparse run : {wire_sparse / 1e6:9.2f} MB on the PS wire "
          f"({rows_moved:.0f} rows moved, {saved / 1e6:.2f} MB saved), "
          f"final window loss {loss_s:.4f}")
    print(f"dense run  : {wire_dense / 1e6:9.2f} MB on the PS wire, "
          f"final window loss {loss_d:.4f}")
    if wire_dense:
        print(f"wire ratio : {wire_sparse / wire_dense:.4f} "
              f"(touched-row fraction {frac:.4f})")


if __name__ == "__main__":
    main()
