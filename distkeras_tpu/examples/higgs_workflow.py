"""End-to-end workflow walkthrough — the ATLAS-Higgs notebook analogue.

The reference's flagship example (SURVEY §2.21) was a notebook driving the
whole library on the ATLAS Higgs dataset: preprocess with transformers,
train the same model with several distributed trainers, predict, evaluate,
compare.  This is that walkthrough for the TPU-native framework, runnable
top to bottom in CI and on a real chip, on a physics-flavoured synthetic
stand-in (no network egress here; swap ``_higgs_like`` for a real table
and nothing else changes):

1.  **preprocess**  — raw detector-ish columns through the transformer
    chain: ``MinMaxTransformer`` (rescale), ``OneHotTransformer`` (labels);
2.  **train**       — the SAME spec through three trainers
    (``SingleTrainer``, ``ADAG``, ``AEASGD``) with per-epoch validation;
3.  **predict**     — ``ModelPredictor`` + ``LabelIndexTransformer``;
4.  **evaluate**    — all four evaluators: accuracy, top-k, confusion
    matrix, per-class precision/recall/F1;
5.  **checkpoint**  — train with a ``Checkpointer``, "crash", resume from
    the latest step and verify the resumed model matches;
6.  **deploy**      — submit the winning config to a Punchcard daemon and
    fetch the trained model back over the wire.

Usage:
    python -m distkeras_tpu.examples.higgs_workflow --cpu 8   # CPU mesh
    python -m distkeras_tpu.examples.higgs_workflow           # real chip
    distkeras-higgs                                           # console script
"""

from __future__ import annotations

import argparse
import tempfile


def _higgs_like(n: int, seed: int):
    """Signal-vs-background binary table, 28 'detector' features [0, 255].

    Signal rows get correlated momentum-like bumps plus a nonlinear
    invariant-mass-ish combination, so a linear probe underfits and the
    MLP has real work to do — the shape of the actual Higgs task.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    base = rng.normal(0.0, 1.0, (n, 28))
    mix = rng.normal(0.0, 0.6, (28, 28)) / np.sqrt(28)
    x = base @ mix  # correlated detector channels
    bump = rng.normal(0.8, 0.3, (n, 4)) * y[:, None]
    x[:, :4] += bump
    # "invariant mass": nonlinear pairing only signal rows satisfy
    x[:, 4] += y * (x[:, 0] * x[:, 1] - x[:, 2] * x[:, 3])
    x = (x - x.min(0)) / (x.max(0) - x.min(0) + 1e-9) * 255.0  # raw 0-255
    return x.astype(np.float32), y.astype(np.int64)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--cpu", type=int, default=0,
                        help="simulate this many CPU devices instead of real chips")
    parser.add_argument("--rows", type=int, default=4096)
    parser.add_argument("--epochs", type=int, default=8)
    parser.add_argument("--workers", type=int, default=None,
                        help="mesh replicas for the distributed trainers "
                             "(default: all visible devices)")
    args = parser.parse_args(argv)
    if args.cpu:
        from distkeras_tpu.platform import pin_cpu_devices

        pin_cpu_devices(args.cpu)

    import numpy as np

    from distkeras_tpu import ADAG, AEASGD, SingleTrainer
    from distkeras_tpu.checkpoint import Checkpointer
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.data.transformers import (
        LabelIndexTransformer, MinMaxTransformer, OneHotTransformer)
    from distkeras_tpu.evaluators import (
        AccuracyEvaluator, ConfusionMatrixEvaluator, PrecisionRecallF1Evaluator,
        TopKAccuracyEvaluator)
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.predictors import ModelPredictor

    # -- 1. preprocess ------------------------------------------------------
    x, y = _higgs_like(args.rows, seed=7)
    split = int(0.8 * len(x))
    raw_train = Dataset({"raw": x[:split], "label": y[:split]})
    raw_test = Dataset({"raw": x[split:], "label": y[split:]})

    chain = [MinMaxTransformer(0.0, 1.0, n_min=0.0, n_max=255.0,
                               input_col="raw", output_col="features"),
             OneHotTransformer(2, input_col="label", output_col="label_onehot")]
    train = raw_train
    test = raw_test
    for t in chain:
        train, test = t.transform(train), t.transform(test)
    print(f"preprocessed: {len(train)} train / {len(test)} test rows, "
          f"features in [{train['features'].min():.2f}, {train['features'].max():.2f}]")

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (64, 32), "num_outputs": 2},
                     input_shape=(28,))

    # -- 2. train: one spec, three trainers ---------------------------------
    common = dict(loss="categorical_crossentropy", worker_optimizer="sgd",
                  learning_rate=0.1, num_epoch=args.epochs,
                  features_col="features", label_col="label_onehot", seed=0)
    # distributed runs split the global batch over the replicas, so their
    # per-worker batch is smaller; window * global batch must fit the data
    dist = dict(num_workers=args.workers, communication_window=2, batch_size=16)
    trainers = {
        "single": SingleTrainer(spec, batch_size=64, **common),
        "adag": ADAG(spec, **common, **dist),
        "aeasgd": AEASGD(spec, **common, **dist, rho=1.0),
    }
    results = {}
    for name, trainer in trainers.items():
        model = trainer.train(train, validation_data=test)
        results[name] = (trainer, model)
        val = trainer.metrics[-1]
        print(f"trainer {name:<7} {trainer.get_training_time():6.2f}s  "
              f"val_loss {val.get('val_loss', float('nan')):.4f}  "
              f"val_acc {val.get('val_accuracy', float('nan')):.4f}")

    # -- 3. predict ---------------------------------------------------------
    best_name = max(results, key=lambda n: results[n][0].metrics[-1]["val_accuracy"])
    best = results[best_name][1]
    scored = ModelPredictor(best, features_col="features").predict(test)
    scored = LabelIndexTransformer().transform(scored)

    # -- 4. evaluate: all four evaluators -----------------------------------
    acc = AccuracyEvaluator(prediction_col="prediction_index",
                            label_col="label").evaluate(scored)
    top2 = TopKAccuracyEvaluator(k=2, prediction_col="prediction",
                                 label_col="label").evaluate(scored)
    cm = ConfusionMatrixEvaluator(2, prediction_col="prediction_index",
                                  label_col="label").evaluate(scored)
    prf = PrecisionRecallF1Evaluator(2, prediction_col="prediction_index",
                                     label_col="label").evaluate(scored)
    print(f"best trainer: {best_name}")
    print(f"accuracy {acc:.4f}  top-2 {top2:.4f} (sanity: must be 1.0)")
    print(f"confusion matrix:\n{cm}")
    print(f"signal precision {prf['precision'][1]:.3f} recall {prf['recall'][1]:.3f} "
          f"F1 {prf['f1'][1]:.3f} (macro F1 {prf['macro_f1']:.3f})")

    # -- 5. checkpoint / crash / resume -------------------------------------
    with tempfile.TemporaryDirectory() as ckdir:
        ck = Checkpointer(ckdir, keep=2)
        half = dict(common, num_epoch=args.epochs // 2)
        ADAG(spec, **half, **dist).train(train, checkpointer=ck)
        assert ck.latest_step() == args.epochs // 2
        # "crash" here: a NEW trainer resumes from the spooled step and
        # finishes the remaining epochs
        resumed = ADAG(spec, **common, **dist)
        model_resumed = resumed.train(train, checkpointer=ck)
        done_epochs = ck.metadata()["metadata"]["epochs_done"]
        racc = AccuracyEvaluator(prediction_col="prediction_index",
                                 label_col="label").evaluate(
            LabelIndexTransformer().transform(
                ModelPredictor(model_resumed, features_col="features").predict(test)))
        print(f"checkpoint-resume: {done_epochs} total epochs, resumed acc {racc:.4f}")

    # -- 6. deploy through Punchcard ----------------------------------------
    from distkeras_tpu.runtime.job_deployment import Job, Punchcard

    with tempfile.TemporaryDirectory() as sroot:
        pc = Punchcard(secret="higgs-demo", data_root=sroot).start()
        try:
            # the daemon is the cluster head (SURVEY §2.18): it owns the
            # devices, so the job it executes is the flagship DISTRIBUTED
            # trainer — ADAG trains on the daemon's whole mesh and the
            # client fetches the center model back over the wire
            job_kwargs = {k: v for k, v in common.items()
                          if k not in ("features_col", "label_col")}
            job_kwargs.update(dist)
            job = Job("127.0.0.1", pc.port, "higgs-demo", name="higgs",
                      model=spec, trainer="adag",
                      trainer_kwargs=job_kwargs,
                      data=Dataset({"features": train["features"],
                                    "label": train["label_onehot"]}))
            fetched = job.run(timeout=600)
            fscored = LabelIndexTransformer().transform(
                ModelPredictor(fetched, features_col="features").predict(test))
            facc = AccuracyEvaluator(prediction_col="prediction_index",
                                     label_col="label").evaluate(fscored)
            print(f"punchcard round trip: fetched model acc {facc:.4f}")
        finally:
            pc.stop()

    ok = acc >= 0.80 and racc >= 0.75 and facc >= 0.80
    print("workflow", "OK" if ok else "BELOW TARGET")
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
