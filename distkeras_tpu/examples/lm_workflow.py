"""Language-model workflow: train a tiny char-LM, then generate from it.

No reference counterpart (the reference predates transformers) — this is
the end-to-end demo of the framework's headroom path: TransformerLM
training (optionally dp-sharded over a mesh) followed by KV-cache
generation, all through the public API.

The corpus is synthetic but structured: arithmetic-progression "sentences"
over a small alphabet, so a 2-layer model learns real next-char structure
in seconds and greedy generation visibly continues the pattern (loss
falling + non-degenerate samples = the observable success criterion).

Usage:
    python -m distkeras_tpu.examples.lm_workflow --cpu 8     # 8-dev CPU mesh
    python -m distkeras_tpu.examples.lm_workflow             # real chip
    distkeras-lm                                             # console script
"""

from __future__ import annotations

import argparse


def _corpus(n_seqs: int, seq_len: int, vocab: int, seed: int):
    """Progressions c, c+d, c+2d, ... (mod vocab), one (start, step) per
    sequence: next-token is a deterministic function of the previous two."""
    import numpy as np

    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab, (n_seqs, 1))
    step = rng.integers(1, 5, (n_seqs, 1))
    pos = np.arange(seq_len + 1)[None, :]
    return ((start + step * pos) % vocab).astype(np.int32)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpu", type=int, default=0,
                        help="simulate this many CPU devices instead of real chips")
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--model-dim", type=int, default=128)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--sample-len", type=int, default=24)
    parser.add_argument("--kv-heads", type=int, default=None,
                        help="grouped-query attention: KV head count "
                             "(default: = query heads, i.e. MHA); shrinks "
                             "the decode cache by the head ratio")
    args = parser.parse_args()
    if args.steps < 1:
        parser.error("--steps must be >= 1")

    if args.cpu:
        from distkeras_tpu.platform import pin_cpu_devices

        pin_cpu_devices(args.cpu)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.decode import make_generate_fn
    from distkeras_tpu.models.transformer import small_lm_spec
    from distkeras_tpu.parallel.lm import (lm_data_shardings, lm_state_shardings,
                                           make_lm_train_step)
    from distkeras_tpu.parallel.mesh import create_nd_mesh

    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].device_kind}")
    dp = len(devices)

    # position budget: training needs seq_len; generation needs the
    # prompt half + sample_len, and the speculative demo additionally
    # writes k + 1 lookahead rows past the end (k = 4 below)
    # head_dim as close to the v5e-recommended 128 as divisibility allows
    # (BASELINE.md head-dim study): smallest head count that divides
    # model_dim with head_dim <= 128 — at the default 128-dim demo model
    # that is a single head
    num_heads = next(h for h in range(max(1, -(-args.model_dim // 128)),
                                      args.model_dim + 1)
                     if args.model_dim % h == 0 and args.model_dim // h <= 128)
    if args.kv_heads is not None and (
            args.kv_heads < 1 or num_heads % args.kv_heads):
        parser.error(f"--kv-heads {args.kv_heads} must be a positive divisor "
                     f"of the query head count {num_heads}")
    spec = small_lm_spec(vocab_size=args.vocab, model_dim=args.model_dim,
                         num_heads=num_heads,
                         num_kv_heads=args.kv_heads,
                         num_layers=args.layers,
                         max_seq_len=max(args.seq_len,
                                         args.seq_len // 2 + args.sample_len + 5))
    model = Model.init(spec, seed=0)
    opt = optax.adam(3e-3)

    mesh = create_nd_mesh((dp,), ("dp",))
    step = make_lm_train_step(spec, opt, mesh, sp_axis=None)
    psh, osh = lm_state_shardings(mesh, opt, model.params)
    dsh = lm_data_shardings(mesh)
    params = jax.device_put(jax.tree.map(jnp.asarray, model.params), psh)
    opt_state = jax.device_put(opt.init(params), osh)

    global_batch = args.batch_size * dp
    data = _corpus(global_batch * args.steps, args.seq_len, args.vocab, seed=1)
    first = last = None
    for i in range(args.steps):
        chunk = data[i * global_batch:(i + 1) * global_batch]
        tokens = jax.device_put(chunk[:, :-1], dsh)
        targets = jax.device_put(chunk[:, 1:], dsh)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        if i == 0:
            first = float(loss)
        if i % 50 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    last = float(loss)
    print(f"loss {first:.3f} -> {last:.3f}")

    # generate: feed half a progression, the model must continue it
    trained = Model(spec=spec, params=jax.tree.map(np.asarray, params))
    gen = make_generate_fn(spec, args.sample_len)
    prompt = _corpus(2, args.seq_len, args.vocab, seed=99)[:, : args.seq_len // 2]
    out = np.asarray(gen(trained.params, jnp.asarray(prompt)))
    correct = 0
    for row, (p, o) in enumerate(zip(prompt, out)):
        d = int(p[1] - p[0]) % args.vocab
        want = [(int(p[-1]) + d * (i + 1)) % args.vocab for i in range(args.sample_len)]
        hits = sum(int(a) == b for a, b in zip(o, want))
        correct += hits
        print(f"prompt {list(map(int, p[:6]))}...  generated {list(map(int, o[:8]))}... "
              f"({hits}/{args.sample_len} continuation hits)")
    acc = correct / (2 * args.sample_len)
    print(f"continuation accuracy: {acc:.2f}")

    # the rest of the serving family, same public API: beam search (width
    # 4, scores are true sequence logprobs) and speculative decoding with
    # the model as its own draft (every proposal accepted — the committed
    # tokens are the model's own greedy decode, here nearly deterministic
    # because the learned progression logits are sharp)
    from distkeras_tpu.models.beam import make_beam_search_fn
    from distkeras_tpu.models.speculative import make_speculative_generate_fn

    beam_toks, beam_scores = make_beam_search_fn(spec, args.sample_len,
                                                 beam_width=4)(
        trained.params, jnp.asarray(prompt))
    print(f"beam-4 best scores: {[round(float(s), 2) for s in beam_scores]}")
    spec_toks = np.asarray(make_speculative_generate_fn(spec, spec,
                                                        args.sample_len, k=4)(
        trained.params, trained.params, jnp.asarray(prompt)))
    spec_agree = float((spec_toks == out).mean())
    print(f"speculative (self-draft) vs greedy agreement: {spec_agree:.2f}")

    if last > first or acc < 0.5 or spec_agree < 0.9:
        print("WARNING: model did not learn the progression structure "
              "or a serving path diverged")
        raise SystemExit(1)


if __name__ == "__main__":
    main()
