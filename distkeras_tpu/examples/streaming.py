"""Streaming-inference example (reference parity: the Kafka + Spark
Streaming notebook, SURVEY §2.21).

Trains a small classifier, serves it with
:class:`~distkeras_tpu.runtime.streaming.StreamingInferenceServer`, then
plays an "event stream" (rows arriving one at a time, the Kafka-topic
shape) through ``stream_predict`` and reports running accuracy.

Usage:
    distkeras-streaming [--events 2048] [--micro-batch 64] [--cpu N]
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--events", type=int, default=2048)
    parser.add_argument("--micro-batch", type=int, default=64)
    parser.add_argument("--cpu", type=int, default=0,
                        help="simulate this many CPU devices instead of real chips")
    args = parser.parse_args(argv)

    if args.cpu:
        from distkeras_tpu.platform import pin_cpu_devices

        pin_cpu_devices(args.cpu)
    import numpy as np

    from distkeras_tpu import Dataset, ModelSpec, SingleTrainer
    from distkeras_tpu.runtime.streaming import StreamingInferenceServer, stream_predict

    # train a quick classifier on gaussian-blob "sensor readings"
    rng = np.random.default_rng(0)
    classes, dim, n = 4, 16, 4096
    centers = rng.normal(scale=3.0, size=(classes, dim))
    labels = rng.integers(0, classes, size=n)
    feats = (centers[labels] + rng.normal(scale=0.7, size=(n, dim))).astype(np.float32)
    ds = Dataset({"features": feats, "label": np.eye(classes, dtype=np.float32)[labels]})
    spec = ModelSpec(name="mlp", config={"hidden_sizes": (32,), "num_outputs": classes},
                     input_shape=(dim,))
    trainer = SingleTrainer(spec, batch_size=64, num_epoch=5, learning_rate=0.1)
    model = trainer.train(ds)

    server = StreamingInferenceServer(model, max_batch=args.micro_batch).start()
    print(f"streaming predictor on 127.0.0.1:{server.port}", flush=True)
    try:
        # the "Kafka topic": an endless-looking iterator of single events
        ev_labels = rng.integers(0, classes, size=args.events)
        events = (centers[l] + rng.normal(scale=0.7, size=dim).astype(np.float32)
                  for l in ev_labels)

        seen = correct = 0
        t0 = time.perf_counter()
        for rows, preds in stream_predict("127.0.0.1", server.port, events,
                                          micro_batch=args.micro_batch):
            got = preds.argmax(axis=-1)
            correct += int((got == ev_labels[seen:seen + len(rows)]).sum())
            seen += len(rows)
        dt = time.perf_counter() - t0
        acc = correct / max(seen, 1)
        print(f"streamed {seen} events in {dt:.2f}s "
              f"({seen / dt:,.0f} events/s); accuracy {acc:.4f}", flush=True)
        if acc < 0.9:
            print("WARNING: streaming accuracy below 0.9", file=sys.stderr)
            sys.exit(1)
    finally:
        server.stop()


if __name__ == "__main__":
    main()
