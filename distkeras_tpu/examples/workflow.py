"""End-to-end workflow example (reference parity: ``examples/workflow.ipynb``).

Mirrors the reference's canonical pipeline: load a classification dataset
-> feature prep with transformers -> train with one of the trainer family
-> predict -> evaluate.  Runs on whatever devices are visible; pass
``--cpu N`` to simulate an N-chip slice on CPU.

Usage:
    python examples/workflow.py --trainer adag --cpu 8
    python examples/workflow.py --trainer single          # one real chip
"""

from __future__ import annotations

import argparse
import sys


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trainer", default="adag",
                        choices=["single", "adag", "downpour", "aeasgd", "eamsgd", "dynsgd",
                                 "averaging", "ensemble",
                                 "async-downpour", "async-adag", "async-aeasgd",
                                 "async-eamsgd", "async-dynsgd"])
    parser.add_argument("--cpu", type=int, default=0,
                        help="simulate this many CPU devices instead of real chips")
    parser.add_argument("--native-ps", action="store_true",
                        help="async trainers: use the C++ parameter-server hub")
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--workers", type=int, default=None)
    args = parser.parse_args()

    if args.cpu:
        from distkeras_tpu.platform import pin_cpu_devices

        pin_cpu_devices(args.cpu)
    import jax
    import numpy as np

    from distkeras_tpu import (
        ADAG, AEASGD, DOWNPOUR, AccuracyEvaluator, AsyncADAG, AsyncAEASGD,
        AsyncDOWNPOUR, AsyncDynSGD, AsyncEAMSGD, AveragingTrainer, Dataset,
        DynSGD, EAMSGD, EnsembleTrainer, ModelPredictor, SingleTrainer,
    )
    from distkeras_tpu.data.transformers import LabelIndexTransformer, MinMaxTransformer, OneHotTransformer
    from distkeras_tpu.models.base import ModelSpec

    print(f"devices: {jax.devices()}")

    # synthetic 10-class "digits": gaussian clusters in 64-d (stands in for
    # MNIST in offline environments; swap for a real loader freely)
    rng = np.random.default_rng(0)
    num_classes, dim, n = 10, 64, 8192
    centers = rng.normal(scale=4.0, size=(num_classes, dim))
    labels = rng.integers(0, num_classes, size=n)
    feats = (centers[labels] + rng.normal(scale=1.0, size=(n, dim)) + 8.0) * 16.0  # ~[0, 255]
    raw = Dataset({"features_raw": feats.astype(np.float32), "label_index": labels.astype(np.int32)})

    # feature prep: rescale to [0,1], one-hot the labels
    ds = MinMaxTransformer(0.0, 1.0, feats.min(), feats.max(),
                           input_col="features_raw", output_col="features").transform(raw)
    ds = OneHotTransformer(num_classes, input_col="label_index", output_col="label").transform(ds)
    train_ds, test_ds = ds.split(0.9, seed=1)

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (128, 128), "num_outputs": num_classes},
                     input_shape=(dim,))
    common = dict(loss="categorical_crossentropy", worker_optimizer="sgd", learning_rate=0.05,
                  features_col="features", label_col="label", batch_size=args.batch_size,
                  num_epoch=args.epochs)
    dist = dict(num_workers=args.workers, communication_window=4)

    # DOWNPOUR's commit adds every replica's delta UNSCALED (reference
    # semantics), so its stable lr shrinks with the replica count
    n_replicas = args.workers or len(jax.devices())
    downpour_common = dict(common, learning_rate=common["learning_rate"] / max(n_replicas, 1))

    trainers = {
        "single": lambda: SingleTrainer(spec, **common),
        "adag": lambda: ADAG(spec, **common, **dist),
        "downpour": lambda: DOWNPOUR(spec, **downpour_common, **dist),
        "aeasgd": lambda: AEASGD(spec, rho=1.0, **common, **dist),
        "eamsgd": lambda: EAMSGD(spec, rho=1.0, momentum=0.9, **{**common, "worker_optimizer": "nesterov"}, **dist),
        "dynsgd": lambda: DynSGD(spec, **common, **dist),
        "averaging": lambda: AveragingTrainer(spec, **common, num_workers=args.workers),
        "ensemble": lambda: EnsembleTrainer(spec, **common, num_workers=args.workers),
    }
    # genuinely-async family: host-loop workers racing a PS hub (optionally
    # the C++ one); num_workers defaults to 4 host threads
    adist = dict(num_workers=args.workers or 4, communication_window=4,
                 native_ps=args.native_ps)
    trainers.update({
        "async-downpour": lambda: AsyncDOWNPOUR(spec, **common, **adist),
        "async-adag": lambda: AsyncADAG(spec, **common, **adist),
        "async-aeasgd": lambda: AsyncAEASGD(spec, rho=1.0, **common, **adist),
        "async-eamsgd": lambda: AsyncEAMSGD(
            spec, rho=1.0, momentum=0.9, **{**common, "worker_optimizer": "nesterov"}, **adist),
        "async-dynsgd": lambda: AsyncDynSGD(spec, **common, **adist),
    })
    trainer = trainers[args.trainer]()
    result = trainer.train(train_ds)
    model = result[0] if isinstance(result, list) else result
    print(f"trained with {args.trainer} in {trainer.get_training_time():.2f}s; "
          f"loss {trainer.history[0]:.4f} -> {trainer.history[-1]:.4f}")

    # predict + evaluate (reference: ModelPredictor -> LabelIndexTransformer
    # -> AccuracyEvaluator chain, SURVEY §3.3)
    scored = ModelPredictor(model, features_col="features").predict(test_ds)
    scored = LabelIndexTransformer(num_classes).transform(scored)
    acc = AccuracyEvaluator(prediction_col="prediction_index", label_col="label_index").evaluate(scored)
    print(f"test accuracy: {acc:.4f}")
    if acc < 0.9:
        print("WARNING: accuracy below 0.9", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
