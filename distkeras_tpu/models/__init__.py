"""Model zoo: registry-backed Flax architectures.

Importing this package registers the built-in architectures (MLP, CNN,
ResNet, TransformerLM, MoE classifier) with the model registry used by
serialization.
"""

from distkeras_tpu.models.base import (  # noqa: F401
    Model,
    ModelSpec,
    register_model,
    build_module,
)
import distkeras_tpu.models.mlp  # noqa: F401
import distkeras_tpu.models.cnn  # noqa: F401
import distkeras_tpu.models.resnet  # noqa: F401
import distkeras_tpu.models.transformer  # noqa: F401
import distkeras_tpu.models.rnn  # noqa: F401
import distkeras_tpu.models.sequential  # noqa: F401
import distkeras_tpu.models.embedding  # noqa: F401
# the MoE classifier lives with its parallelism machinery but must register
# here too, or cross-process deserialization can't rebuild it
import distkeras_tpu.parallel.moe  # noqa: F401  (registers moe_mlp_classifier)
