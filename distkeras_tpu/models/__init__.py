"""Model zoo: registry-backed Flax architectures.

Importing this package registers the built-in architectures (MLP, CNN,
ResNet, TransformerLM) with the model registry used by serialization.
"""

from distkeras_tpu.models.base import (  # noqa: F401
    Model,
    ModelSpec,
    register_model,
    build_module,
)
import distkeras_tpu.models.mlp  # noqa: F401
import distkeras_tpu.models.cnn  # noqa: F401
import distkeras_tpu.models.resnet  # noqa: F401
import distkeras_tpu.models.transformer  # noqa: F401
