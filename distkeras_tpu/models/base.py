"""Model abstraction: architecture registry + (spec, params) bundles.

Reference parity: the reference moved Keras models around as
``{architecture JSON, weight list}`` dicts (``distkeras/utils.py ::
serialize_keras_model``) and rebuilt+compiled them inside each Spark
executor (``distkeras/workers.py :: Worker.prepare_model``).  TPU-native
equivalent: an architecture is a *registry name + config dict* that builds
a Flax module deterministically, parameters are a pytree, and "compile"
is ``jax.jit`` of the step function — there is no per-worker rebuild
because SPMD replicas share one traced program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import utils

_MODEL_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register_model(name: str):
    """Class decorator registering a Flax module under an architecture name."""

    def wrap(cls):
        _MODEL_REGISTRY[name] = cls
        cls.architecture_name = name
        return cls

    return wrap


def build_module(name: str, config: Dict[str, Any]):
    try:
        cls = _MODEL_REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown architecture {name!r}; known: {sorted(_MODEL_REGISTRY)}") from None
    return cls(**config)


def sparse_param_names(spec: "ModelSpec") -> Tuple[str, ...]:
    """Param-path leaf names this architecture declares as row-sparse
    ``[rows, dim]`` embedding tables (``sparse_param_names`` on the
    registered module class; empty for everything else).  This is the
    EmbeddingTable metadata the async trainers thread into the PS stack
    (ISSUE 9)."""
    cls = _MODEL_REGISTRY.get(spec.name)
    return tuple(getattr(cls, "sparse_param_names", ()) or ())


def sparse_leaf_indices(spec: "ModelSpec", params: Any) -> Tuple[int, ...]:
    """Flat-leaf indices (``jax.tree.flatten`` order — the PS template
    order) of the spec's declared sparse embedding tables: leaves whose
    param path ends in one of :func:`sparse_param_names` and that are
    2-D.  Empty when the architecture declares none."""
    names = set(sparse_param_names(spec))
    if not names:
        return ()
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for idx, (path, leaf) in enumerate(flat):
        last = path[-1] if path else None
        key = getattr(last, "key", getattr(last, "name", None))
        if key in names and getattr(leaf, "ndim", 0) == 2:
            out.append(idx)
    return tuple(out)


def sparse_table_fields(spec: "ModelSpec", params: Any):
    """Per-table input-column declaration for MULTI-VOCABULARY sparse
    architectures (ISSUE 15): which columns of the int-id feature matrix
    feed each sparse embedding table.

    The registered module class declares ``sparse_field_map`` — a dict
    mapping a MODULE PATH SEGMENT (e.g. ``"table_1"``, the flax
    submodule name that owns the table param) to the tuple of feature
    columns indexing that table.  Returns the column tuples aligned with
    :func:`sparse_leaf_indices` order, or ``None`` when the architecture
    declares no map — the single-vocabulary contract, where every table
    is indexed by EVERY column and all tables must share one row count
    (the async trainers enforce that reduction).

    Raises when a map exists but does not cover every sparse leaf: a
    silently-defaulted table would send another vocabulary's ids."""
    cls = _MODEL_REGISTRY.get(spec.name)
    fmap = getattr(cls, "sparse_field_map", None)
    if not fmap:
        return None
    names = set(sparse_param_names(spec))
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        last = path[-1] if path else None
        key = getattr(last, "key", getattr(last, "name", None))
        if key not in names or getattr(leaf, "ndim", 0) != 2:
            continue
        segs = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        owner = next((s for s in segs if s in fmap), None)
        if owner is None:
            raise ValueError(
                f"architecture {spec.name!r} declares sparse_field_map "
                f"{sorted(fmap)} but sparse leaf at {segs} matches no "
                f"entry — every sparse table needs its column declaration")
        out.append(tuple(int(c) for c in fmap[owner]))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Declarative architecture record: registry name + config + input shape.

    ``input_shape`` excludes the batch dimension (Keras convention).
    """

    name: str
    config: Dict[str, Any]
    input_shape: Tuple[int, ...]
    input_dtype: str = "float32"

    def __post_init__(self):
        # canonicalize so a JSON round-trip (tuples -> lists) compares equal;
        # recurses through dicts too (sequential's layer dicts nest configs)
        def canon(v):
            if isinstance(v, (list, tuple)):
                return tuple(canon(x) for x in v)
            if isinstance(v, dict):
                return {k: canon(x) for k, x in v.items()}
            return v

        object.__setattr__(self, "config", {k: canon(v) for k, v in self.config.items()})
        object.__setattr__(self, "input_shape", tuple(self.input_shape))

    def build(self):
        return build_module(self.name, self.config)

    def init_params(self, seed: int = 0) -> Any:
        module = self.build()
        dummy = jnp.zeros((1,) + tuple(self.input_shape), dtype=self.input_dtype)
        variables = module.init(jax.random.PRNGKey(seed), dummy)
        return variables["params"]

    def apply_fn(self) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
        module = self.build()

        def apply(params: Any, x: jnp.ndarray) -> jnp.ndarray:
            return module.apply({"params": params}, x)

        return apply

    @property
    def needs_rng(self) -> bool:
        """True when training this architecture needs a PRNG key per step
        (currently: sequential stacks containing active dropout layers).
        Drives the trainers' key plumbing; paths without it must refuse
        such specs (``reject_rng_spec``) rather than silently train with
        dropout off."""
        if self.name != "sequential":
            return False
        return any(l.get("kind") == "dropout" and float(l.get("rate", 0)) > 0
                   for l in self.config.get("layers", ()))

    def reject_rng_spec(self, where: str) -> None:
        if self.needs_rng:
            raise ValueError(
                f"{where} has no PRNG plumbing (v1) and would silently train "
                "with dropout disabled; remove the dropout layers or use "
                "SingleTrainer / the sync distributed trainer family")

    def train_apply_fn(self) -> Callable[[Any, jnp.ndarray, Any], jnp.ndarray]:
        """Training-mode forward ``(params, x, rng) -> out``.

        For specs with ``needs_rng`` the key feeds the dropout rng stream
        and ``train=True`` activates the stochastic layers; otherwise the
        rng is ignored and this is exactly ``apply_fn``."""
        if not self.needs_rng:
            plain = self.apply_fn()
            return lambda params, x, rng: plain(params, x)
        module = self.build()

        def apply(params: Any, x: jnp.ndarray, rng) -> jnp.ndarray:
            return module.apply({"params": params}, x, train=True,
                                rngs={"dropout": rng})

        return apply

    def reject_silent_aux(self, where: str) -> None:
        """Raise if training this spec through a plain ``apply_fn`` step
        would silently drop sown aux losses (``sow`` into an immutable
        collection is a no-op): currently MoE load-balance losses —
        ``moe_experts`` on transformer_lm specs, ``num_experts`` on
        moe_mlp_classifier specs."""
        if self.config.get("moe_experts") or self.config.get("num_experts"):
            raise ValueError(
                f"{where} would silently drop the MoE load-balance aux losses "
                "(sow into an immutable collection is a no-op); train MoE "
                "models with parallel/moe.py :: make_moe_train_step / "
                "make_moe_lm_train_step")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "config": dict(self.config),
            "input_shape": list(self.input_shape),
            "input_dtype": self.input_dtype,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ModelSpec":
        return ModelSpec(
            name=d["name"],
            config=dict(d["config"]),
            input_shape=tuple(d["input_shape"]),
            input_dtype=d.get("input_dtype", "float32"),
        )


@dataclasses.dataclass
class Model:
    """A trained (or initialized) model: spec + parameter pytree.

    This is what trainers return — the analogue of the Keras model object
    the reference's ``Trainer.train`` handed back.
    """

    spec: ModelSpec
    params: Any

    @staticmethod
    def init(spec: ModelSpec, seed: int = 0) -> "Model":
        return Model(spec=spec, params=spec.init_params(seed))

    def _jitted_apply(self):
        # cached per instance: spec.apply_fn() returns a fresh closure each
        # call, which would defeat jax's jit cache and recompile every time
        cached = getattr(self, "_apply_cache", None)
        if cached is None:
            cached = jax.jit(self.spec.apply_fn())
            object.__setattr__(self, "_apply_cache", cached)
        return cached

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._jitted_apply()(self.params, x)

    def predict(self, x: np.ndarray, batch_size: int = 512) -> np.ndarray:
        """Batched jit'd inference over a host array (see also ModelPredictor)."""
        apply = self._jitted_apply()
        outs = []
        for i in range(0, len(x), batch_size):
            outs.append(np.asarray(apply(self.params, jnp.asarray(x[i : i + batch_size]))))
        return np.concatenate(outs, axis=0) if outs else np.zeros((0,))

    def serialize(self) -> bytes:
        return utils.serialize_model(self.spec.to_dict(), self.params)

    @staticmethod
    def deserialize(blob: bytes) -> "Model":
        arch, weights = utils.deserialize_model(blob)
        spec = ModelSpec.from_dict(arch)
        template = spec.init_params(seed=0)
        _, treedef = jax.tree.flatten(template)
        params = utils.unflatten_weights(treedef, weights)
        return Model(spec=spec, params=params)

    def copy(self) -> "Model":
        return Model(spec=self.spec, params=jax.tree.map(jnp.array, self.params))

    def summary(self) -> str:
        """Keras ``model.summary()`` parity: per-module parameter table.

        Groups leaves by top-level param-tree key (one row per layer/block),
        with shapes for single-leaf modules and totals throughout.
        """
        rows = []
        total = total_bytes = 0
        for name, sub in self.params.items():
            leaves = jax.tree.leaves(sub)
            n = sum(int(l.size) for l in leaves)
            nbytes = sum(int(l.size) * l.dtype.itemsize for l in leaves)
            shape = str(tuple(leaves[0].shape)) if len(leaves) == 1 else f"{len(leaves)} tensors"
            rows.append((name, shape, n))
            total += n
            total_bytes += nbytes
        name_w = max([5] + [len(r[0]) for r in rows])   # >= len("layer")
        shape_w = max([5] + [len(r[1]) for r in rows])  # >= len("shape")
        lines = [f'Model "{self.spec.name}"  (input {self.spec.input_shape}, '
                 f'{self.spec.input_dtype})',
                 f"{'layer':<{name_w}}  {'shape':<{shape_w}}  params"]
        lines.append("-" * len(lines[-1]))
        for name, shape, n in rows:
            lines.append(f"{name:<{name_w}}  {shape:<{shape_w}}  {n:,}")
        lines.append("-" * len(lines[1]))
        lines.append(f"total: {total:,} params  ({total_bytes / 1e6:.2f} MB)")
        return "\n".join(lines)
