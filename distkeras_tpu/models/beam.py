"""Beam-search decoding for ``TransformerLM``.

No reference counterpart (the reference predates LMs; SURVEY.md §2.21) —
this completes the serving family next to greedy/sampled decoding
(``models/decode.py``) and speculative drafting (``models/speculative.py``).

Compiler-first shape: the whole search is one jitted program — a prefill
on the true batch, the KV cache tiled to ``B*W`` rows, then a
``lax.scan`` of fixed-shape steps.  Each step scores all ``W*V``
continuations per batch row with one ``top_k``, reorders the cache and
the token history by the surviving beams' parent indices
(``jnp.take`` along the batch axis — the classic beam-search cache
shuffle), and appends the chosen tokens.  No dynamic shapes anywhere;
finished beams are masked, not removed:

- a beam that has emitted ``eos_id`` only ever extends with ``pad_id``
  at zero additional score (every other token is -inf), so its final
  score is frozen while live beams keep competing;
- the EOS token itself is kept, pads follow — the same output
  convention as ``make_generate_fn``.

Scores are sums of f32 ``log_softmax`` token logprobs under the target.
``length_penalty`` alpha > 0 applies the GNMT normalization
``score / ((5 + len) / 6)**alpha`` at the FINAL beam selection only
(len = tokens before padding), the standard way to stop beam search
favoring short EOS-terminated hypotheses; 0 disables it.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.models.decode import (KVCache, dequant_embed,
                                         forward_with_cache, init_cache,
                                         validate_decode_spec)

_NEG_INF = jnp.float32(-1e30)  # finite: -inf - -inf = nan would poison scores


def make_beam_search_fn(spec: ModelSpec, max_new_tokens: int, *,
                        beam_width: int = 4, length_penalty: float = 0.0,
                        eos_id: Optional[int] = None, pad_id: int = 0,
                        cache_len: Optional[int] = None,
                        return_all: bool = False):
    """Build a jitted ``(params, prompt [B, P]) -> (tokens, scores)``.

    Default: the best beam per row — tokens [B, max_new_tokens], scores
    [B] (f32 total logprob; length-normalized iff ``length_penalty`` >
    0).  ``return_all=True`` returns every beam, best first: tokens
    [B, W, max_new_tokens], scores [B, W].

    ``beam_width=1`` IS greedy decoding (equality with
    ``make_generate_fn(temperature=0)`` is test-pinned).
    """
    config = validate_decode_spec(spec, "beam search")
    if not 1 <= beam_width <= config["vocab_size"]:
        raise ValueError(f"beam_width must be in [1, vocab_size="
                         f"{config['vocab_size']}], got {beam_width}")
    if eos_id is not None and not 0 <= eos_id < config["vocab_size"]:
        raise ValueError(f"eos_id {eos_id} outside vocab "
                         f"[0, {config['vocab_size']})")
    if not 0 <= pad_id < config["vocab_size"]:
        raise ValueError(f"pad_id {pad_id} outside vocab "
                         f"[0, {config['vocab_size']}) — an out-of-range pad "
                         "would be silently clamped by the frozen-row scatter")
    max_seq = config["max_seq_len"]
    w = beam_width
    vocab = config["vocab_size"]

    @functools.partial(jax.jit, static_argnames=("prompt_len",))
    def run(params, prompt, prompt_len):
        n = max_new_tokens
        b = prompt.shape[0]
        total = cache_len or (prompt_len + n)
        if prompt_len + n > total:
            raise ValueError(f"cache_len = {total} cannot hold prompt "
                             f"({prompt_len}) + max_new_tokens ({n})")
        # the table bound applies only to learned positions (rope has none)
        if ((config.get("positional") or "learned") == "learned"
                and prompt_len + n > max_seq):
            raise ValueError(f"prompt ({prompt_len}) + max_new_tokens ({n}) "
                             f"exceeds the positional table max_seq_len = "
                             f"{max_seq}")
        params = dequant_embed(params)
        cache = init_cache(config, b, total)
        logits, cache = forward_with_cache(params, config, prompt, 0, cache,
                                           last_only=True)
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [B, V]

        # first expansion: top-W distinct first tokens seed the beams
        scores, tok0 = lax.top_k(logp0, w)                  # [B, W] both
        tok0 = tok0.astype(jnp.int32)
        done = (jnp.zeros((b, w), bool) if eos_id is None else tok0 == eos_id)
        # beam-major layout: flat row b*W + w holds batch b's w-th beam
        cache = KVCache(jnp.repeat(cache.k, w, axis=1),
                        jnp.repeat(cache.v, w, axis=1))
        history = jnp.full((b, w, n), pad_id, jnp.int32)
        history = history.at[:, :, 0].set(tok0)

        # a finished beam's only continuation: pad at zero added score
        frozen_row = jnp.full((vocab,), _NEG_INF).at[pad_id].set(0.0)

        lengths = jnp.ones((b, w), jnp.float32)  # scored tokens per beam

        def step(carry, t):
            cache, cur, scores, history, done, lengths = carry
            logits, cache = forward_with_cache(
                params, config, cur.reshape(b * w)[:, None],
                prompt_len + t, cache)
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32)).reshape(b, w, vocab)
            logp = jnp.where(done[:, :, None], frozen_row[None, None], logp)
            cand = scores[:, :, None] + logp                # [B, W, V]
            scores, flat = lax.top_k(cand.reshape(b, w * vocab), w)
            parent = flat // vocab                          # [B, W]
            tok = (flat % vocab).astype(jnp.int32)

            # reorder every per-beam carry by the surviving parents
            take = jnp.take_along_axis
            history = take(history, parent[:, :, None], axis=1)
            history = history.at[:, :, t + 1].set(tok)
            done = take(done, parent, axis=1)
            # the new token is a scored part of the hypothesis unless its
            # beam had already finished (then it is a frozen pad).  This
            # is the exact GNMT length — counting non-pad history tokens
            # would miscount when pad_id appears as a genuine token
            lengths = take(lengths, parent, axis=1) + (~done).astype(jnp.float32)
            if eos_id is not None:
                done = done | (tok == eos_id)
            flat_parent = (jnp.arange(b)[:, None] * w + parent).reshape(-1)
            cache = KVCache(jnp.take(cache.k, flat_parent, axis=1),
                            jnp.take(cache.v, flat_parent, axis=1))
            return (cache, tok, scores, history, done, lengths), None

        if n > 1:
            (cache, _, scores, history, done, lengths), _ = lax.scan(
                step, (cache, tok0, scores, history, done, lengths),
                jnp.arange(n - 1))

        # final ranking (length-normalized iff requested)
        if length_penalty > 0.0:
            ranked = scores / ((5.0 + lengths) / 6.0) ** length_penalty
        else:
            ranked = scores
        order = jnp.argsort(-ranked, axis=1)
        history = jnp.take_along_axis(history, order[:, :, None], axis=1)
        ranked = jnp.take_along_axis(ranked, order, axis=1)
        if return_all:
            return history, ranked
        return history[:, 0], ranked[:, 0]

    def beam_fn(params, prompt):
        prompt = jnp.asarray(prompt)
        return run(params, prompt, prompt.shape[1])

    return beam_fn


def beam_search(model: Model, prompt, max_new_tokens: int, *,
                beam_width: int = 4, length_penalty: float = 0.0,
                eos_id: Optional[int] = None, pad_id: int = 0) -> Tuple:
    """Convenience one-shot wrapper (rebuilds + recompiles per call; for
    repeated use build once with :func:`make_beam_search_fn`)."""
    fn = make_beam_search_fn(model.spec, max_new_tokens,
                             beam_width=beam_width,
                             length_penalty=length_penalty,
                             eos_id=eos_id, pad_id=pad_id)
    return fn(model.params, jnp.asarray(prompt))
