"""Small convnet (the reference's MNIST-CNN / CIFAR-CNN example family).

NHWC layout throughout — XLA's preferred convolution layout on TPU (the
MXU tiles the channel dim onto lanes).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


@register_model("cnn")
class CNN(nn.Module):
    """Conv-relu-pool blocks then a dense head. Outputs logits."""

    conv_channels: Sequence[int] = (32, 64)
    kernel_size: int = 3
    dense_size: int = 256
    num_outputs: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for ch in self.conv_channels:
            x = nn.Conv(ch, (self.kernel_size, self.kernel_size), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_size)(x))
        return nn.Dense(self.num_outputs)(x)


def mnist_cnn_spec():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="cnn",
        config={"conv_channels": (32, 64), "kernel_size": 3, "dense_size": 256, "num_outputs": 10},
        input_shape=(28, 28, 1),
    )


def cifar_cnn_spec(num_outputs: int = 10):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="cnn",
        config={"conv_channels": (64, 128, 256), "kernel_size": 3, "dense_size": 512, "num_outputs": num_outputs},
        input_shape=(32, 32, 3),
    )
