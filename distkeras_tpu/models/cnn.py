"""Small convnet (the reference's MNIST-CNN / CIFAR-CNN example family).

NHWC layout throughout — XLA's preferred convolution layout on TPU (the
MXU tiles the channel dim onto lanes).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


@register_model("cnn")
class CNN(nn.Module):
    """Conv-relu-pool blocks then a dense head. Outputs logits.

    ``compute_dtype`` (e.g. ``"bfloat16"``) runs convs/matmuls and
    activations in that dtype with float32 params/optimizer — the LM
    stack's mixed-precision scheme, and the measured-faster choice even
    at MNIST scale (1.35x the f32 headline on v5e; the old "bf16 slower"
    result applied to a whole-model cast — see BASELINE.md round 5).
    The head always emits float32 logits.  ``None`` keeps float32 (the
    historical default; parity-tested against bf16)."""

    conv_channels: Sequence[int] = (32, 64)
    kernel_size: int = 3
    dense_size: int = 256
    num_outputs: int = 10
    compute_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cdt = jnp.dtype(self.compute_dtype or "float32")
        x = x.astype(cdt)
        for ch in self.conv_channels:
            x = nn.Conv(ch, (self.kernel_size, self.kernel_size),
                        padding="SAME", dtype=cdt)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_size, dtype=cdt)(x))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(x)


def mnist_cnn_spec(compute_dtype: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="cnn",
        config={"conv_channels": (32, 64), "kernel_size": 3, "dense_size": 256,
                "num_outputs": 10, "compute_dtype": compute_dtype},
        input_shape=(28, 28, 1),
    )


def cifar_cnn_spec(num_outputs: int = 10, compute_dtype: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="cnn",
        config={"conv_channels": (64, 128, 256), "kernel_size": 3, "dense_size": 512,
                "num_outputs": num_outputs, "compute_dtype": compute_dtype},
        input_shape=(32, 32, 3),
    )
