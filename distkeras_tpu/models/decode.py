"""KV-cache autoregressive decoding for ``TransformerLM``.

The reference has a streaming-inference story only as a Spark+Kafka
pipeline of independent ``model.predict`` calls (SURVEY.md §2.21); for the
flagship LM family the TPU-native equivalent is real incremental decoding:
a compiled prefill that ingests the whole prompt in one MXU-shaped pass and
a compiled per-token step that attends against an in-HBM KV cache instead
of re-running the full sequence (O(L) per token instead of O(L²)).

Implementation notes:

- Pure functions over the published param tree (``embed``, ``pos_embed``,
  ``block_{i}.{LayerNorm_0,qkv,proj,LayerNorm_1,up,down}``, ``final_norm``;
  GQA specs replace the fused ``qkv`` leaf with ``q`` [E, H, Dh] and
  ``kv`` [E, 2, Hkv, Dh] — ``_block`` dispatches on which is present)
  rather than a Flax method: a compact Flax module allows only one
  ``nn.compact`` method, and threading a mutable cache collection through
  ``module.apply`` would force the training path to carry decode-only
  plumbing.  Parity with ``TransformerLM.__call__`` is enforced by test
  (``tests/test_decode.py``), not by code sharing.
- One attention routine serves prefill (L = prompt) and decode (L = 1):
  new K/V rows are written into the cache at ``start_pos`` with
  ``lax.dynamic_update_slice`` and queries attend over the full cache
  under the mask ``key_pos <= start_pos + query_offset`` — dead cache rows
  are masked, so the cache can be any length >= the generated sequence.
- Static shapes throughout: the generation loop is a ``lax.scan`` of
  single-token steps over a fixed ``max_new_tokens``; finished rows (past
  EOS) keep emitting ``pad_id`` under a carried ``done`` flag instead of
  breaking out, which is the compiler-friendly form of early exit.
- The KV cache is [num_layers, B, cache_len, Hkv, Dh] in the compute dtype
  (bfloat16 by default; Hkv = ``num_kv_heads`` under GQA, else H) — the
  decode-time HBM working set — and attention logits/softmax run in
  float32 like the training path.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.ops.quantize import QTensor


def _wmul(eq: str, y: jnp.ndarray, w, dtype) -> jnp.ndarray:
    """``einsum(eq, y, w)`` where ``w`` may be an int8 ``QTensor``.

    The per-OUTPUT-channel scale commutes out of the contraction
    (``einsum(y, q * s) == einsum(y, q) * s`` when ``s`` varies only along
    the kernel's last, non-contracted axis), so the weight is consumed as
    int8 — the convert fuses into the matmul's operand read and the scale
    multiply into its epilogue, keeping per-step HBM weight traffic at 1
    byte/elem instead of materializing an f32 copy outside the decode loop.
    Every block kernel here (qkv [E,3,H,Dh] — or the GQA pair q [E,H,Dh] /
    kv [E,2,Hkv,Dh] — proj [H,Dh,E], up [E,F], down [F,E]) has its channel
    axis last and uncontracted; the embedding does NOT (``attend``
    contracts E), so it is dequantized once up front.
    """
    if isinstance(w, QTensor):
        out = jnp.einsum(eq, y, w.q.astype(dtype))
        return out * w.scale.reshape(-1).astype(dtype)
    return jnp.einsum(eq, y, w.astype(dtype))


def dequant_embed(params: Any) -> Any:
    """int8 trees (ops/quantize.py) decode transparently: block kernels are
    consumed as int8 per use via ``_wmul`` (the scale commutes out of each
    matmul), so per-step weight traffic stays at 1 byte/elem.  Only the
    embedding dequantizes up front — its scale axis (E) is contracted by
    the unembed, so the scale does not commute there.  Shared prologue of
    ``make_generate_fn`` and ``speculative.make_speculative_generate_fn``."""
    emb = params["embed"]["embedding"]
    if isinstance(emb, QTensor):
        params = dict(params, embed={"embedding": emb.dequantize(jnp.float32)})
    return params


class KVCache(NamedTuple):
    """Stacked per-layer key/value cache: [num_layers, B, S, H, Dh]."""

    k: jnp.ndarray
    v: jnp.ndarray


class QKVCache(NamedTuple):
    """int8-quantized KV cache: values [L, B, S, H, Dh] int8 with
    per-(position, head) float32 scales [L, B, S, H, 1].

    Serving memory-bandwidth lever (batched decode reads the whole cache
    every step — ~86MB/token at bench size, the dominant cost at batch
    8): storing KV int8 halves that traffic, and XLA fuses the
    dequantize into the attention dots' operand reads (measured 1.53x on
    the cache-attention pass, v5e 2026-07-31).  Quantization error is
    one rounding step per K/V row — NOT bit-exact with the bf16 cache;
    the ``tests/test_decode.py`` oracle pins that the quantized-cache
    forward equals a full-precision forward over the SAME
    rounded-then-dequantized values."""

    k: jnp.ndarray        # int8
    v: jnp.ndarray        # int8
    k_scale: jnp.ndarray  # f32 [L, B, S, H, 1]
    v_scale: jnp.ndarray  # f32


def _quantize_rows(x: jnp.ndarray):
    """[B, L, H, D] -> (int8 values, f32 scales [B, L, H, 1]); symmetric
    per-(position, head), exact zero rows keep scale 1."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _cfg_dtype(config: dict) -> Any:
    return config.get("compute_dtype", jnp.bfloat16)


def validate_decode_spec(spec: ModelSpec, what: str = "decoding") -> dict:
    """Shared precondition gate for the whole decoder family (plain
    generate, speculative target/draft, beam search): KV-cache math is
    single-program transformer_lm only.  Returns a config copy."""
    config = dict(spec.config)
    if config.get("seq_axis") or config.get("tp_axis"):
        raise ValueError(f"{what} expects a plain (non-sharded) spec; strip "
                         "seq_axis/tp_axis — the cache math is single-program")
    if config.get("moe_experts"):
        raise ValueError(f"KV-cache {what} does not support MoE specs (v1)")
    if spec.name != "transformer_lm":
        raise ValueError(f"{what} is defined for transformer_lm specs, "
                         f"got {spec.name!r}")
    return config


def _layer_norm(p: dict, x: jnp.ndarray, dtype) -> jnp.ndarray:
    """flax.linen.LayerNorm semantics: stats in float32, eps 1e-6."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = jnp.square(xf - mu).mean(-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + 1e-6)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def _block(pb: dict, x: jnp.ndarray, cache, layer: int, start_pos, dtype,
           positional: str = "learned"):
    """One transformer block over ``x`` [B, L, E] with KV caching.

    ``cache`` is the STACKED [layers, B, S, H, Dh] :class:`KVCache` (or
    :class:`QKVCache`); only the L new K/V rows of layer ``layer`` are
    written (in place when XLA can alias the scan carry — the whole
    point: rewriting the full cache per decoded token would move
    ~50MB/token at bench size).  Queries attend over the layer's slab
    masked to ``key_pos <= start_pos + query_offset``, which also masks
    dead rows beyond the write head.

    On a quantized cache the new rows are rounded to int8 on write; the
    per-(position, head) K scale commutes out of the score dot and the V
    scale folds into the attention probabilities (both vary only along
    the key axis), so the int8 slabs feed the einsums directly and XLA
    fuses the convert into the operand reads — the cache's HBM traffic
    halves, which is the whole point at decode batch sizes.
    """
    head_dim = cache.k.shape[-1]
    quant = isinstance(cache, QKVCache)

    y = _layer_norm(pb["LayerNorm_0"], x, dtype)
    if "qkv" in pb:
        qkv = _wmul("ble,eshd->blshd", y, pb["qkv"]["kernel"], dtype)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    else:
        # GQA layout: separate q [E, H, Dh] and kv [E, 2, Hkv, Dh]
        # projections (models/transformer.py); the cache stores Hkv heads
        q = _wmul("ble,ehd->blhd", y, pb["q"]["kernel"], dtype)
        kv = _wmul("ble,eshd->blshd", y, pb["kv"]["kernel"], dtype)
        k, v = kv[:, :, 0], kv[:, :, 1]
    if positional == "rope":
        from distkeras_tpu.ops.rotary import rope_rotate

        # K enters the cache ALREADY rotated (rotation depends only on the
        # row's own absolute position, so cached rows never need revisiting)
        rpos = start_pos + jnp.arange(x.shape[1])
        q, k = rope_rotate(q, rpos), rope_rotate(k, rpos)
    if quant:
        k_rows, k_rows_scale = _quantize_rows(k)
        v_rows, v_rows_scale = _quantize_rows(v)
    else:
        k_rows, v_rows = k.astype(cache.k.dtype), v.astype(cache.v.dtype)
    k_all = lax.dynamic_update_slice(
        cache.k, k_rows[None], (layer, 0, start_pos, 0, 0))
    v_all = lax.dynamic_update_slice(
        cache.v, v_rows[None], (layer, 0, start_pos, 0, 0))
    ck, cv = k_all[layer], v_all[layer]

    # grouped heads: fold the query heads as [Hkv, G] and contract each
    # group against its single cached KV head — the cache slabs feed the
    # einsums at Hkv width, never materializing an H-headed copy (that
    # read traffic is GQA's savings); G == 1 reduces to plain MHA
    b, l, hq, _ = q.shape
    hkv = ck.shape[2]
    g = hq // hkv
    qg = q.reshape(b, l, hkv, g, head_dim)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                        ck.astype(dtype) if quant else ck,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / head_dim ** 0.5)
    if quant:
        k_scale = lax.dynamic_update_slice(
            cache.k_scale, k_rows_scale[None], (layer, 0, start_pos, 0, 0))
        v_scale = lax.dynamic_update_slice(
            cache.v_scale, v_rows_scale[None], (layer, 0, start_pos, 0, 0))
        # [L?, B, S, Hkv, 1] -> [B, Hkv, 1, 1, S] broadcast along keys
        scores = scores * k_scale[layer][..., 0].transpose(0, 2, 1)[:, :, None, None, :]
    q_pos = start_pos + lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    k_pos = lax.broadcasted_iota(jnp.int32, scores.shape, 4)
    scores = jnp.where(k_pos <= q_pos, scores, float("-inf"))
    attn = jax.nn.softmax(scores, axis=-1)
    if quant:
        attn = attn * v_scale[layer][..., 0].transpose(0, 2, 1)[:, :, None, None, :]
    attn = attn.astype(dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", attn,
                   cv.astype(dtype) if quant else cv).reshape(b, l, hq, head_dim)
    o = _wmul("bqhd,hde->bqe", o, pb["proj"]["kernel"], dtype)
    x = x + o

    y = _layer_norm(pb["LayerNorm_1"], x, dtype)
    y = jax.nn.gelu(_wmul("ble,ef->blf", y, pb["up"]["kernel"], dtype))
    y = _wmul("blf,fe->ble", y, pb["down"]["kernel"], dtype)
    new_cache = (QKVCache(k_all, v_all, k_scale, v_scale) if quant
                 else KVCache(k_all, v_all))
    return x + y, new_cache


def init_cache(config: dict, batch: int, cache_len: int,
               quantized: bool = False):
    """Zero cache sized for ``cache_len`` total positions (prompt + new);
    ``quantized`` selects the int8 :class:`QKVCache` layout.  Under GQA
    the cache holds only ``num_kv_heads`` heads — the bytes (and decode
    HBM traffic) shrink by num_kv_heads/num_heads, which is the feature's
    whole point at serving batch sizes."""
    n_layers = config["num_layers"]
    heads = config.get("num_kv_heads") or config["num_heads"]
    head_dim = config["model_dim"] // config["num_heads"]
    shape = (n_layers, batch, cache_len, heads, head_dim)
    if quantized:
        sshape = shape[:-1] + (1,)
        return QKVCache(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                        jnp.ones(sshape, jnp.float32),
                        jnp.ones(sshape, jnp.float32))
    dtype = _cfg_dtype(config)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def forward_with_cache(params: Any, config: dict, tokens: jnp.ndarray,
                       start_pos, cache: KVCache,
                       last_only: bool = False) -> Tuple[jnp.ndarray, KVCache]:
    """Run tokens [B, L] at positions ``start_pos..start_pos+L-1`` against
    the cache; returns (float32 logits, updated cache) — [B, L, vocab], or
    [B, 1, vocab] when ``last_only`` (generation consumes only the final
    position, and the [L, vocab] unembed matmul is the prefill's single
    biggest op at real vocab sizes).

    Serves both phases: prefill (L = prompt length, start_pos = 0) and
    decode (L = 1, start_pos = current length).
    """
    dtype = _cfg_dtype(config)
    n_layers = config["num_layers"]
    positional = config.get("positional") or "learned"
    x = params["embed"]["embedding"].astype(dtype)[tokens]
    if positional == "learned":
        pos = start_pos + jnp.arange(tokens.shape[1])
        x = x + params["pos_embed"][pos].astype(dtype)

    for i in range(n_layers):
        x, cache = _block(params[f"block_{i}"], x, cache, i, start_pos, dtype,
                          positional)

    if last_only:
        x = x[:, -1:]
    x = _layer_norm(params["final_norm"], x, dtype)
    logits = jnp.einsum("ble,ve->blv", x.astype(jnp.float32),
                        params["embed"]["embedding"].astype(jnp.float32))
    return logits, cache


class FusedStepState(NamedTuple):
    """Everything the fused Pallas decode step needs beyond the caches:
    the stacked weight slabs plus the embedding/head params shared with
    the XLA formulation.  Built once per generate call (loop-invariant —
    XLA hoists it out of the decode scan)."""

    weights: Any          # ops.decode_step.DecodeWeights
    embedding: jnp.ndarray  # [V, E] compute dtype (gather side)
    params: Any           # full tree (final_norm + f32 unembed + pos_embed)
    config: dict
    interpret: bool


def make_fused_state(params: Any, config: dict) -> FusedStepState:
    from distkeras_tpu.ops.decode_step import stack_decode_weights

    dtype = _cfg_dtype(config)
    return FusedStepState(
        weights=stack_decode_weights(params, config["num_layers"], dtype),
        embedding=params["embed"]["embedding"].astype(dtype),
        params=params, config=config,
        interpret=jax.default_backend() != "tpu")


def fused_token_forward(state: FusedStepState, tok: jnp.ndarray, pos,
                        k_t: jnp.ndarray, v_all: jnp.ndarray):
    """One fused single-token step + head: [B] tokens at ``pos`` ->
    (float32 logits [B, 1, V], k_t, v_all).  The head math mirrors
    ``forward_with_cache`` exactly (f32 final norm stats, f32 unembed)."""
    from distkeras_tpu.ops.decode_step import fused_decode_step

    config, params = state.config, state.params
    dtype = _cfg_dtype(config)
    x = state.embedding[tok] + params["pos_embed"][pos].astype(dtype)
    hidden, k_t, v_all = fused_decode_step(
        state.weights, x, k_t, v_all, pos,
        heads=config["num_heads"], interpret=state.interpret)
    h = _layer_norm(params["final_norm"], hidden[:, None], dtype)
    logits = jnp.einsum("ble,ve->blv", h.astype(jnp.float32),
                        params["embed"]["embedding"].astype(jnp.float32))
    return logits, k_t, v_all


def _sample(logits: jnp.ndarray, rng, temperature: float, top_k: int,
            top_p: float = 0.0) -> jnp.ndarray:
    """[B, vocab] float32 logits -> [B] int32 token ids.

    ``top_k`` keeps the k highest logits; ``top_p`` (nucleus sampling,
    Holtzman et al. 2019) keeps the smallest set of tokens whose
    temperature-scaled probabilities sum to >= top_p — the filters
    compose (k first, then p) and both are no-ops at their 0 defaults.
    The nucleus always contains the argmax, so top_p -> 0 degrades to
    greedy, not to an empty support."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, float("-inf"), logits)
    if top_p and top_p < 1.0:
        probs = jax.nn.softmax(logits / temperature, axis=-1)
        order = jnp.argsort(-probs, axis=-1)
        sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
        cum = jnp.cumsum(sorted_probs, axis=-1)
        # a token stays iff the mass BEFORE it (exclusive) is < top_p; the
        # exclusive form keeps the top-1 token unconditionally.  The mask
        # maps back through the inverse permutation (NOT a probability
        # threshold, which would re-admit every token tied with the
        # boundary and make top_p a no-op on tied distributions)
        keep_sorted = (cum - sorted_probs) < top_p
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, float("-inf"))
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(jnp.int32)


def warn_quantized_cache_gqa(config: dict, context: str) -> None:
    """Warn when ``quantize_cache=True`` composes with GQA — a measured
    NET LOSS, not a neutral default.

    The int8 KV cache pays a quantize-on-write op per step to halve cache
    READ traffic; GQA (``num_kv_heads < num_heads``) has already cut that
    traffic by the head ratio, so there is little bandwidth left to win
    and the write cost dominates: v5e b64 batched decode measured
    **94.9k -> 82.4k tok/s (-13%)** when int8 was stacked on a 4x-GQA
    cache (BENCH_r05 gqa_b64; BASELINE.md round 5 "int8 atop GQA is a
    measured net loss").  The combination composes silently in config, so
    every decode builder routes through this guard; it stays a WARNING
    (not a refusal) because the crossover may return at much longer
    cache_len — re-measure at your shape before suppressing it."""
    kv_heads = config.get("num_kv_heads") or config["num_heads"]
    if kv_heads < config["num_heads"]:
        warnings.warn(
            f"quantize_cache=True with GQA (num_kv_heads={kv_heads} < "
            f"num_heads={config['num_heads']}) in {context} is a measured "
            "net loss on v5e batched decode (94.9k -> 82.4k tok/s at "
            "batch 64, -13%): GQA already cut the cache reads by the head "
            "ratio, so int8's read savings no longer cover its "
            "quantize-on-write cost.  Drop quantize_cache (keep GQA), or "
            "re-measure at your shape (bench.py decode legs fp_b64_gqa vs "
            "kv_int8_b64_gqa) before relying on this combination.",
            UserWarning, stacklevel=3)


def make_generate_fn(spec: ModelSpec, max_new_tokens: int, *,
                     temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 0.0,
                     eos_id: Optional[int] = None, pad_id: int = 0,
                     cache_len: Optional[int] = None,
                     step_impl: Optional[str] = None,
                     quantize_cache: bool = False):
    """Build a jitted ``(params, prompt [B, P], rng) -> tokens [B, max_new]``.

    ``cache_len`` defaults to prompt length + ``max_new_tokens`` (it is a
    static shape, so the returned fn recompiles per distinct prompt length,
    like any jitted shape-polymorphic JAX program).  Greedy when
    ``temperature == 0``; ``top_k``/``top_p`` (nucleus) filter the sampled
    distribution (see ``_sample``).  Rows that have emitted ``eos_id``
    keep emitting ``pad_id``.

    ``quantize_cache=True`` stores KV int8 with per-(position, head)
    scales (:class:`QKVCache`): cache HBM traffic halves — the dominant
    batched-decode cost — at one rounding step of approximation per K/V
    row (an accuracy/throughput trade, NOT bit-exact; see the QKVCache
    docstring and the oracle test).  Requires the XLA step
    (``step_impl`` must not be ``"fused"``).

    ``step_impl``: ``None`` auto-selects — the fused Pallas block kernel
    (``ops/decode_step.py``) on TPU when the shapes support it, the XLA
    per-op step otherwise.  ``"fused"`` / ``"xla"`` pin the path for A/B
    measurement (``"fused"`` off-TPU runs the Pallas interpreter — slow,
    test-only).  Both paths produce the same tokens (parity-tested); the
    fused step exists because the XLA form pays ~15 ops of fixed sequencing
    cost per layer per token (see the kernel module docstring).
    """
    if step_impl not in (None, "fused", "xla"):
        raise ValueError(f"unknown step_impl {step_impl!r}; use None, 'fused' or 'xla'")
    if not 0.0 <= top_p <= 1.0:  # also rejects NaN
        raise ValueError(f"top_p must be in [0, 1], got {top_p} (a negative "
                         "value would mask every token — including the argmax "
                         "— and categorical over an all--inf row silently "
                         "emits token 0)")
    if not temperature >= 0.0:  # also rejects NaN
        raise ValueError(f"temperature must be >= 0, got {temperature} "
                         "(a negative value would silently select greedy)")
    if quantize_cache and step_impl == "fused":
        raise ValueError("quantize_cache requires the XLA step: the fused "
                         "kernel's slabs are bf16 (step_impl='xla' or None)")
    config = validate_decode_spec(spec, "decoding")
    if quantize_cache:
        warn_quantized_cache_gqa(config, "make_generate_fn")
    if not 0 <= top_k <= config["vocab_size"]:
        raise ValueError(f"top_k must be in [0, vocab_size="
                         f"{config['vocab_size']}], got {top_k} "
                         "(out-of-range values fail at trace time inside "
                         "lax.top_k, not here where the mistake is visible)")
    max_seq = config["max_seq_len"]

    @functools.partial(jax.jit, static_argnames=("prompt_len", "impl"))
    def run(params, prompt, rng, prompt_len, impl):
        params = dequant_embed(params)
        total = cache_len or (prompt_len + max_new_tokens)
        # validate the user-supplied capacity BEFORE the fused path rounds it
        # up to a lane multiple, so both impls accept/reject identically (an
        # undersized cache_len must not pass on one step_impl and raise on
        # the other depending on auto-selection)
        if prompt_len + max_new_tokens > total:
            raise ValueError(
                f"cache_len = {total} cannot hold prompt ({prompt_len}) + "
                f"max_new_tokens ({max_new_tokens}); out-of-range cache "
                "writes would silently clamp and corrupt generation")
        if impl == "fused":
            from distkeras_tpu.ops.decode_step import round_cache_len

            total = round_cache_len(total)  # K-slab lane tiling
        # the positional-TABLE bound applies only under "learned": rope has
        # no table and generates past max_seq_len freely (the cache checks
        # above are the real capacity bound there)
        if ((config.get("positional") or "learned") == "learned"
                and prompt_len + max_new_tokens > max_seq):
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the positional table max_seq_len = {max_seq}")
        cache = init_cache(config, prompt.shape[0], total,
                           quantized=quantize_cache)
        logits, cache = forward_with_cache(params, config, prompt, 0, cache,
                                           last_only=True)
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature, top_k, top_p)
        # the EOS token itself is kept in the output; rows are padded after
        done = jnp.zeros(prompt.shape[0], bool) if eos_id is None else tok == eos_id

        if impl == "fused":
            from distkeras_tpu.ops.decode_step import transpose_k_cache

            # loop-invariant w.r.t. the scan: XLA materializes this once
            # per call, not per token
            state = make_fused_state(params, config)
            # the fused kernel wants lane-major keys; transpose ONCE after
            # prefill (the scan then carries KVCache(k_t, v) — k in
            # [L, HD, B, S] layout, v unchanged)
            cache = KVCache(transpose_k_cache(cache.k), cache.v)

        def step(carry, _):
            tok, cache, pos, rng, done = carry
            if impl == "fused":
                logits, k_t, v_all = fused_token_forward(
                    state, tok, pos, cache.k, cache.v)
                cache = KVCache(k_t, v_all)
            else:
                logits, cache = forward_with_cache(
                    params, config, tok[:, None], pos, cache)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature, top_k, top_p)
            if eos_id is not None:
                nxt = jnp.where(done, pad_id, nxt)
                done = done | (nxt == eos_id)
            return (nxt, cache, pos + 1, rng, done), nxt

        carry = (tok, cache, jnp.asarray(prompt_len, jnp.int32), rng, done)
        if max_new_tokens > 1:
            (_, _, _, _, _), rest = lax.scan(step, carry, None,
                                             length=max_new_tokens - 1)
            return jnp.concatenate([tok[:, None], rest.T], axis=1)
        return tok[:, None]

    def generate_fn(params, prompt, rng=None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        from distkeras_tpu.ops.decode_step import resolve_step_impl

        if quantize_cache:
            # the fused kernel's slabs are bf16 — an int8 QKVCache through
            # it would silently drop the scales.  The explicit-'fused'
            # combination already raised at build time; auto must resolve
            # to the XLA step here, not just usually avoid it
            impl = "xla"
        else:
            # auto keys on the MEASURED win region (small models, batch 1
            # — see ops.decode_step.fused_step_auto), not just shape
            # support: the 8-layer/512-dim XLA step is already optimal
            impl = resolve_step_impl(
                config, prompt.shape[0],
                cache_len or (prompt.shape[1] + max_new_tokens), step_impl)
        return run(params, prompt, rng, prompt.shape[1], impl)

    return generate_fn


def make_sharded_generate_fn(spec: ModelSpec, mesh, max_new_tokens: int, *,
                             tp_axis: Optional[str] = "tp",
                             dp_axis: Optional[str] = None, **kw):
    """Distributed decoding via GSPMD sharding propagation.

    Rather than rewriting the cache math in shard_map, this places the
    params with the SAME Megatron partition specs the tensor-parallel
    training step uses (``parallel/lm.py :: lm_param_specs``: qkv
    column-parallel over heads, proj/down row-parallel, up column-parallel)
    and the prompt batch over ``dp_axis``, then lets XLA's sharding
    propagation partition the jitted generation program — the KV cache
    inherits the head sharding from the qkv einsum, attention stays local
    to the head shard, and the row-parallel matmuls become psums over ICI.
    Compiler-first: the single-device program IS the distributed program.

    Returns ``fn(params, prompt, rng=None) -> tokens [B, max_new_tokens]``;
    placement happens inside, so callers pass ordinary host/device arrays.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from distkeras_tpu.parallel.lm import lm_param_specs

    # the fused Pallas step would be an opaque box to GSPMD's sharding
    # propagation — the whole mechanism this path relies on — so the
    # sharded program always uses the XLA step (None = auto resolves to
    # it here; only an explicit 'fused' is an error)
    if kw.get("step_impl") is None:
        kw["step_impl"] = "xla"
    if kw["step_impl"] != "xla":
        raise ValueError("make_sharded_generate_fn requires step_impl='xla': "
                         "sharding propagation cannot see through the fused "
                         "Pallas decode kernel")
    inner = make_generate_fn(spec, max_new_tokens, **kw)  # validates the spec
    for name, axis in (("tp_axis", tp_axis), ("dp_axis", dp_axis)):
        # a typo'd axis must not silently degrade to full replication
        if axis is not None and axis not in mesh.shape:
            raise ValueError(f"{name} {axis!r} is not a mesh axis of {mesh}; "
                             "pass None to disable that parallelism")
    tp = mesh.shape[tp_axis] if tp_axis else 1
    if spec.config["num_heads"] % tp:
        raise ValueError(f"num_heads {spec.config['num_heads']} not divisible "
                         f"by tp={tp} over mesh axis {tp_axis!r}")
    kv_heads = spec.config.get("num_kv_heads") or spec.config["num_heads"]
    if kv_heads % tp:
        raise ValueError(f"num_kv_heads {kv_heads} not divisible by tp={tp}: "
                         "the cache's head axis is the sharded one — use a "
                         "tp that divides the KV heads, or dp-only decoding")

    def fn(params, prompt, rng=None):
        if any(isinstance(l, QTensor) for l in jax.tree.leaves(
                params, is_leaf=lambda l: isinstance(l, QTensor))):
            raise ValueError("int8-quantized trees are not supported with "
                             "sharded decoding (v1): per-channel scale dims "
                             "don't carry the Megatron partition specs; use "
                             "make_generate_fn (single-program) instead")
        if dp_axis and prompt.shape[0] % mesh.shape[dp_axis]:
            raise ValueError(f"batch {prompt.shape[0]} not divisible by "
                             f"dp={mesh.shape[dp_axis]}")
        pspecs = lm_param_specs(params, tp_axis if tp > 1 else None)
        params = jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P)))
        prompt = jax.device_put(jnp.asarray(prompt), NamedSharding(
            mesh, P(dp_axis) if dp_axis else P()))
        return inner(params, prompt, rng)

    return fn


def generate(model: Model, prompt: jnp.ndarray, max_new_tokens: int,
             *, temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
             eos_id: Optional[int] = None, pad_id: int = 0,
             seed: int = 0) -> jnp.ndarray:
    """Convenience one-shot: generate ``max_new_tokens`` continuations of
    ``prompt`` [B, P] from a trained ``Model``; returns [B, max_new_tokens].

    For repeated generation build the fn once with :func:`make_generate_fn`
    (this wrapper rebuilds — and therefore recompiles — every call).
    """
    fn = make_generate_fn(model.spec, max_new_tokens, temperature=temperature,
                          top_k=top_k, top_p=top_p, eos_id=eos_id, pad_id=pad_id)
    return fn(model.params, jnp.asarray(prompt), jax.random.PRNGKey(seed))
