"""Embedding-bag CTR classifier — the row-sparse PS workload (ISSUE 9).

dist-keras's heritage is Spark-ML tabular pipelines; the modern version of
that workload is CTR/recommender training, where one embedding table
dwarfs the dense model and every batch touches only the few hundred rows
its categorical ids name.  This module is the minimal faithful shape of
that family: ``fields`` categorical id columns over ONE shared vocabulary,
an embedding-bag reduce (sum over fields), and a small dense head.

The ``EmbeddingTable`` leaf kind is declared DECLARATIVELY: the module
class lists the param-path names of its row-sparse ``[rows, dim]`` tables
in ``sparse_param_names``, and :func:`sparse_leaf_indices` (models/base)
resolves them to flat-leaf indices — the metadata the async trainers
thread into the PS stack (``sparse_tables="auto"``) so pull/commit traffic
moves only the rows a batch touches.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import ModelSpec, register_model


@register_model("embedding_classifier")
class EmbeddingBagClassifier(nn.Module):
    """Shared-vocabulary embedding bag + MLP head (logits out).

    Input: int ids ``[batch, fields]`` in ``[0, rows)``.  Each field's id
    indexes the ONE ``[rows, dim]`` table (flax ``nn.Embed``; its param is
    named ``embedding`` — the name ``sparse_param_names`` declares); the
    field vectors are mean-reduced (an "embedding bag"), then a small
    dense stack emits class logits.  Under any gradient step only the
    rows present in the batch receive nonzero gradient — the property the
    row-sparse PS commit path is built on."""

    rows: int
    dim: int = 16
    hidden_sizes: Sequence[int] = (32,)
    num_outputs: int = 2

    # param-path leaf names that are row-sparse [rows, dim] tables — the
    # EmbeddingTable declaration sparse_leaf_indices() resolves
    sparse_param_names = ("embedding",)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        emb = nn.Embed(self.rows, self.dim, name="table")(x.astype(jnp.int32))
        h = emb.mean(axis=1)  # [batch, dim] — the bag reduce
        for hsz in self.hidden_sizes:
            h = nn.relu(nn.Dense(hsz)(h))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(h)


@register_model("multi_embedding_classifier")
class MultiTableCTRClassifier(nn.Module):
    """Per-field embedding tables with INDEPENDENT vocabularies (the
    hyperscale tier's multi-table shape, ISSUE 15).

    Input: int ids ``[batch, fields]`` where column ``f`` indexes its own
    ``[vocab_sizes[f], dim]`` table — user ids, item ids and context ids
    are different id spaces with different sizes and different hot
    shapes, exactly what one shared vocabulary cannot express.  The field
    vectors are mean-reduced and fed to the same dense head as the
    single-table classifier.

    Each table is a separate flax submodule ``table_<f>`` whose param is
    named ``embedding`` (``sparse_param_names``); ``sparse_field_map``
    (built lazily per instance — the map depends only on ``fields``)
    tells the async trainers which feature column feeds which table, so
    every table's pull/commit id set is computed — and validated —
    against ITS vocabulary."""

    vocab_sizes: Sequence[int]
    dim: int = 16
    hidden_sizes: Sequence[int] = (32,)
    num_outputs: int = 2

    sparse_param_names = ("embedding",)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        xi = x.astype(jnp.int32)
        vecs = [
            nn.Embed(int(rows), self.dim, name=f"table_{f}")(xi[:, f])
            for f, rows in enumerate(self.vocab_sizes)]
        h = jnp.stack(vecs, axis=1).mean(axis=1)
        for hsz in self.hidden_sizes:
            h = nn.relu(nn.Dense(hsz)(h))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(h)


# column f feeds table_f — the declaration models.base.sparse_table_fields
# resolves.  A plain class attribute keyed by module name: the map is a
# function of the field ORDINALS only, so one generous upper bound serves
# every fields count (unknown names are simply never matched)
MultiTableCTRClassifier.sparse_field_map = {
    f"table_{f}": (f,) for f in range(64)}


def ctr_embedding_spec(rows, dim: int = 16, fields: int = 4,
                       hidden_sizes: Sequence[int] = (32,),
                       num_outputs: int = 2) -> ModelSpec:
    """Spec for the synthetic-CTR example/bench: ``fields`` int32 id
    columns in, click/no-click logits out.

    ``rows`` as an int keeps the PR-9 single-shared-vocabulary
    architecture byte-identical; a SEQUENCE of ints declares one
    independent vocabulary per field (``multi_embedding_classifier`` —
    ``fields`` is then implied by the sequence length)."""
    if isinstance(rows, (list, tuple)):
        return ModelSpec(name="multi_embedding_classifier",
                         config={"vocab_sizes": tuple(int(r) for r in rows),
                                 "dim": int(dim),
                                 "hidden_sizes": tuple(hidden_sizes),
                                 "num_outputs": int(num_outputs)},
                         input_shape=(len(rows),),
                         input_dtype="int32")
    return ModelSpec(name="embedding_classifier",
                     config={"rows": int(rows), "dim": int(dim),
                             "hidden_sizes": tuple(hidden_sizes),
                             "num_outputs": int(num_outputs)},
                     input_shape=(int(fields),),
                     input_dtype="int32")
