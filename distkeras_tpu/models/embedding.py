"""Embedding-bag CTR classifier — the row-sparse PS workload (ISSUE 9).

dist-keras's heritage is Spark-ML tabular pipelines; the modern version of
that workload is CTR/recommender training, where one embedding table
dwarfs the dense model and every batch touches only the few hundred rows
its categorical ids name.  This module is the minimal faithful shape of
that family: ``fields`` categorical id columns over ONE shared vocabulary,
an embedding-bag reduce (sum over fields), and a small dense head.

The ``EmbeddingTable`` leaf kind is declared DECLARATIVELY: the module
class lists the param-path names of its row-sparse ``[rows, dim]`` tables
in ``sparse_param_names``, and :func:`sparse_leaf_indices` (models/base)
resolves them to flat-leaf indices — the metadata the async trainers
thread into the PS stack (``sparse_tables="auto"``) so pull/commit traffic
moves only the rows a batch touches.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import ModelSpec, register_model


@register_model("embedding_classifier")
class EmbeddingBagClassifier(nn.Module):
    """Shared-vocabulary embedding bag + MLP head (logits out).

    Input: int ids ``[batch, fields]`` in ``[0, rows)``.  Each field's id
    indexes the ONE ``[rows, dim]`` table (flax ``nn.Embed``; its param is
    named ``embedding`` — the name ``sparse_param_names`` declares); the
    field vectors are mean-reduced (an "embedding bag"), then a small
    dense stack emits class logits.  Under any gradient step only the
    rows present in the batch receive nonzero gradient — the property the
    row-sparse PS commit path is built on."""

    rows: int
    dim: int = 16
    hidden_sizes: Sequence[int] = (32,)
    num_outputs: int = 2

    # param-path leaf names that are row-sparse [rows, dim] tables — the
    # EmbeddingTable declaration sparse_leaf_indices() resolves
    sparse_param_names = ("embedding",)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        emb = nn.Embed(self.rows, self.dim, name="table")(x.astype(jnp.int32))
        h = emb.mean(axis=1)  # [batch, dim] — the bag reduce
        for hsz in self.hidden_sizes:
            h = nn.relu(nn.Dense(hsz)(h))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(h)


def ctr_embedding_spec(rows: int, dim: int = 16, fields: int = 4,
                       hidden_sizes: Sequence[int] = (32,),
                       num_outputs: int = 2) -> ModelSpec:
    """Spec for the synthetic-CTR example/bench: ``fields`` int32 id
    columns in, click/no-click logits out."""
    return ModelSpec(name="embedding_classifier",
                     config={"rows": int(rows), "dim": int(dim),
                             "hidden_sizes": tuple(hidden_sizes),
                             "num_outputs": int(num_outputs)},
                     input_shape=(int(fields),),
                     input_dtype="int32")
