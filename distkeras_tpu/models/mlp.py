"""MLP architecture (the reference's MNIST-MLP example model family).

Reference parity: the reference's examples built Keras ``Sequential``
Dense stacks (``examples/mnist.py``); here the equivalent is a registered
Flax module so it round-trips through the architecture registry.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


@register_model("mlp")
class MLP(nn.Module):
    """Dense stack: hidden layers with ReLU, linear head (logits out)."""

    hidden_sizes: Sequence[int] = (500, 500)
    num_outputs: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.num_outputs)(x)


def mnist_mlp_spec():
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp", config={"hidden_sizes": (500, 500), "num_outputs": 10}, input_shape=(784,))
