"""MLP architecture (the reference's MNIST-MLP example model family).

Reference parity: the reference's examples built Keras ``Sequential``
Dense stacks (``examples/mnist.py``); here the equivalent is a registered
Flax module so it round-trips through the architecture registry.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


@register_model("mlp")
class MLP(nn.Module):
    """Dense stack: hidden layers with ReLU, linear head (logits out).

    ``compute_dtype`` (e.g. ``"bfloat16"``) runs the hidden matmuls and
    activations in that dtype with float32 params/optimizer — the LM
    stack's mixed-precision scheme (models/transformer.py), measured
    1.35x on the CNN headline (see BASELINE.md round 5).  The head
    always emits float32 logits (softmax-CE stability).  ``None`` keeps
    everything float32 (the historical default; parity-tested)."""

    hidden_sizes: Sequence[int] = (500, 500)
    num_outputs: int = 10
    compute_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cdt = jnp.dtype(self.compute_dtype or "float32")
        x = x.reshape((x.shape[0], -1)).astype(cdt)
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h, dtype=cdt)(x))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(x)


def mnist_mlp_spec(compute_dtype: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(name="mlp",
                     config={"hidden_sizes": (500, 500), "num_outputs": 10,
                             "compute_dtype": compute_dtype},
                     input_shape=(784,))
