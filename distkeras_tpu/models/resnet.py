"""ResNet-20 (CIFAR variant) — the BASELINE.md config-5 model.

Classic 3-stage CIFAR ResNet (He et al. 2015): 6n+2 layers with n=3.
Uses GroupNorm instead of BatchNorm: batch statistics are a cross-replica
dependency that would force an extra collective per norm layer on a TPU
mesh and make the per-replica divergent-weights algorithms (EASGD family)
ill-defined; GroupNorm is batch-independent, so every parallelism mode
sees identical semantics.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


class ResidualBlock(nn.Module):
    channels: int
    strides: int = 1
    compute_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cdt = jnp.dtype(self.compute_dtype or "float32")
        residual = x
        y = nn.Conv(self.channels, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=cdt)(x)
        # flax GroupNorm computes its statistics in float32 regardless of
        # dtype, so bf16 here costs one rounding of the normalized output
        y = nn.GroupNorm(num_groups=min(8, self.channels), dtype=cdt)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", use_bias=False,
                    dtype=cdt)(y)
        y = nn.GroupNorm(num_groups=min(8, self.channels), dtype=cdt)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.channels, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=cdt)(x)
        return nn.relu(y + residual)


@register_model("resnet")
class ResNet(nn.Module):
    """CIFAR-style ResNet; depth = 6*blocks_per_stage + 2.

    ``compute_dtype`` follows the family scheme (see models/cnn.py):
    bf16 convs/norms/activations over float32 params, float32 logits."""

    blocks_per_stage: int = 3
    base_channels: int = 16
    num_outputs: int = 10
    compute_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cdt = jnp.dtype(self.compute_dtype or "float32")
        x = x.astype(cdt)
        x = nn.Conv(self.base_channels, (3, 3), padding="SAME", use_bias=False,
                    dtype=cdt)(x)
        x = nn.GroupNorm(num_groups=min(8, self.base_channels), dtype=cdt)(x)
        x = nn.relu(x)
        for stage, ch in enumerate([self.base_channels, self.base_channels * 2, self.base_channels * 4]):
            for block in range(self.blocks_per_stage):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = ResidualBlock(channels=ch, strides=strides,
                                  compute_dtype=self.compute_dtype)(x)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(x)


def resnet20_spec(num_outputs: int = 100, compute_dtype: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="resnet",
        config={"blocks_per_stage": 3, "base_channels": 16,
                "num_outputs": num_outputs, "compute_dtype": compute_dtype},
        input_shape=(32, 32, 3),
    )
