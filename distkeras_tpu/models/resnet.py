"""ResNet-20 (CIFAR variant) — the BASELINE.md config-5 model.

Classic 3-stage CIFAR ResNet (He et al. 2015): 6n+2 layers with n=3.
Uses GroupNorm instead of BatchNorm: batch statistics are a cross-replica
dependency that would force an extra collective per norm layer on a TPU
mesh and make the per-replica divergent-weights algorithms (EASGD family)
ill-defined; GroupNorm is batch-independent, so every parallelism mode
sees identical semantics.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model


class ResidualBlock(nn.Module):
    channels: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = nn.Conv(self.channels, (3, 3), strides=(self.strides, self.strides), padding="SAME", use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(8, self.channels))(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(8, self.channels))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.channels, (1, 1), strides=(self.strides, self.strides), use_bias=False)(x)
        return nn.relu(y + residual)


@register_model("resnet")
class ResNet(nn.Module):
    """CIFAR-style ResNet; depth = 6*blocks_per_stage + 2."""

    blocks_per_stage: int = 3
    base_channels: int = 16
    num_outputs: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(self.base_channels, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.GroupNorm(num_groups=min(8, self.base_channels))(x)
        x = nn.relu(x)
        for stage, ch in enumerate([self.base_channels, self.base_channels * 2, self.base_channels * 4]):
            for block in range(self.blocks_per_stage):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = ResidualBlock(channels=ch, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_outputs)(x)


def resnet20_spec(num_outputs: int = 100):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="resnet",
        config={"blocks_per_stage": 3, "base_channels": 16, "num_outputs": num_outputs},
        input_shape=(32, 32, 3),
    )
