"""Recurrent models (LSTM / GRU) — the reference's Keras-RNN family.

dist-keras trained whatever Keras models users handed it, and the Keras-1
era zoo was heavy on LSTMs (SURVEY.md §2.1: the trainer holds an arbitrary
serialized Keras model); this module gives the registry the recurrent
members so that surface carries over.

TPU notes: recurrence is the anti-MXU shape — a serial chain of small
matmuls — so the implementation leans on what XLA *can* do well:
``flax.linen.RNN`` lowers the time loop to one ``lax.scan`` (single
compiled program, no per-step dispatch), the input/recurrent projections
inside ``OptimizedLSTMCell`` are fused gate matmuls ([F, 4H] rather than
four [F, H]s), and the whole batch rides each step so the MXU sees
[B, F] x [F, 4H] tiles.  Long-context work belongs to the transformer
family (ring/flash attention); this exists for model-zoo parity, not
sequence scale.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.base import register_model


def _carry_like(cell: nn.RNNCellBase, x: jnp.ndarray):
    """Initial carry whose varying-manual-axes match ``x``.

    Under ``shard_map`` (the distributed trainers) the inputs vary over the
    replica axis but the cell's default zero carry does not, and the time
    ``lax.scan`` rejects the carry-type mismatch; ``pcast`` the zeros to
    x's vma.  Outside shard_map vma is empty and this is the identity.
    """
    carry = cell.initialize_carry(jax.random.PRNGKey(0), x[:, 0].shape)
    vma = tuple(jax.typeof(x).vma)
    if not vma:
        return carry
    return jax.tree.map(lambda c: lax.pcast(c, vma, to="varying"), carry)


@register_model("rnn")
class RNNClassifier(nn.Module):
    """Token or feature sequences -> class logits.

    Input is [B, T] int32 tokens when ``vocab_size > 0`` (embedded to
    ``embed_dim``), else [B, T, F] float features.  Stacked recurrent
    layers (``cell_type`` "lstm" or "gru"); the last layer's final hidden
    state feeds the dense head (Keras ``LSTM(return_sequences=False)``
    convention).
    """

    vocab_size: int = 0
    embed_dim: int = 128
    hidden_sizes: Sequence[int] = (128,)
    cell_type: str = "lstm"
    num_outputs: int = 2
    compute_dtype: jnp.dtype = jnp.float32  # recurrent cells are small; the
                                            # scan's serial latency, not
                                            # matmul rate, bounds throughput

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.cell_type not in ("lstm", "gru"):
            raise ValueError(f"cell_type must be 'lstm' or 'gru', got {self.cell_type!r}")
        if self.vocab_size:
            x = nn.Embed(self.vocab_size, self.embed_dim,
                         dtype=self.compute_dtype)(x)
        else:
            x = x.astype(self.compute_dtype)
        for i, h in enumerate(self.hidden_sizes):
            cell = (nn.OptimizedLSTMCell(h, dtype=self.compute_dtype)
                    if self.cell_type == "lstm"
                    else nn.GRUCell(h, dtype=self.compute_dtype))
            last = i == len(self.hidden_sizes) - 1
            # return_carry gives the final state without materializing the
            # [B, T, H] output sequence read we'd immediately discard
            if last:
                carry, _ = nn.RNN(cell, return_carry=True, name=f"rnn_{i}")(
                    x, initial_carry=_carry_like(cell, x))
                x = carry[1] if self.cell_type == "lstm" else carry
            else:
                x = nn.RNN(cell, name=f"rnn_{i}")(
                    x, initial_carry=_carry_like(cell, x))
        return nn.Dense(self.num_outputs, dtype=jnp.float32)(x)


def lstm_classifier_spec(vocab_size: int = 1024, seq_len: int = 64,
                         embed_dim: int = 128, hidden_sizes: Sequence[int] = (128,),
                         num_outputs: int = 2, cell_type: str = "lstm"):
    """Token-sequence classifier (IMDB-style sentiment shapes)."""
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="rnn",
        config={"vocab_size": vocab_size, "embed_dim": embed_dim,
                "hidden_sizes": tuple(hidden_sizes), "cell_type": cell_type,
                "num_outputs": num_outputs},
        input_shape=(seq_len,),
        input_dtype="int32",
    )


def feature_rnn_spec(seq_len: int = 32, feature_dim: int = 8,
                     hidden_sizes: Sequence[int] = (64,), num_outputs: int = 2,
                     cell_type: str = "gru"):
    """Float-feature sequence classifier (sensor/time-series shapes)."""
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="rnn",
        config={"vocab_size": 0, "hidden_sizes": tuple(hidden_sizes),
                "cell_type": cell_type, "num_outputs": num_outputs},
        input_shape=(seq_len, feature_dim),
    )
