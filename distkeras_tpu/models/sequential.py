"""``Sequential`` — arbitrary layer-stack models, the Keras ``Sequential``
equivalent.

The reference trains *any* Keras model the user hands it (SURVEY §2.1: the
trainer holds a serialized model; §2.19: ``serialize_keras_model`` ships
``{json architecture, weights}``).  The registry's named families (mlp,
cnn, resnet, ...) cover the example zoo but not that open-endedness; this
module restores it: an architecture is a JSON-safe list of layer dicts, so
user-defined stacks serialize/deserialize through the same
``Model.serialize`` path as every built-in, with no Python code shipped.

Layer kinds (constructor sugar below builds the dicts):

- ``dense(units, activation=None)``
- ``conv2d(filters, kernel_size, strides=1, padding="SAME", activation=None)``
  — NHWC, the TPU-preferred conv layout
- ``max_pool2d(window, strides=None)`` / ``avg_pool2d(window, strides=None)``
- ``global_avg_pool()`` — mean over spatial dims
- ``flatten()``
- ``activation(name)`` — relu | gelu | tanh | sigmoid | softmax | elu |
  leaky_relu
- ``layer_norm()``
- ``dropout(rate)`` — real inverted dropout during training: trainers
  whose step plumbs a PRNG key (``SingleTrainer`` and the sync
  distributed family — ``ModelSpec.needs_rng`` drives the plumbing) pass
  ``train=True`` + an rng; inference and ``Model.apply`` stay
  deterministic.  Paths without rng plumbing (ZeRO/async, v1) refuse
  dropout specs loudly instead of silently skipping regularization.
- ``embed(vocab_size, dim)`` — int tokens [B, T] -> [B, T, dim]

BatchNorm is deliberately absent: it needs mutable ``batch_stats``
threaded through every trainer; use ``layer_norm`` (the TPU-era norm) —
an explicit error points there.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model

_ACTIVATIONS = {
    "relu": nn.relu, "gelu": nn.gelu, "tanh": jnp.tanh,
    "sigmoid": nn.sigmoid, "softmax": nn.softmax, "elu": nn.elu,
    "leaky_relu": nn.leaky_relu,
}

# allowed keys per layer kind — hand-written dicts are the advertised
# interface, so a typo'd key ('stride', 'pad') must fail loudly instead of
# silently falling back to a default
_ALLOWED_KEYS = {
    "dense": {"units", "activation"},
    "conv2d": {"filters", "kernel_size", "strides", "padding", "activation"},
    "max_pool2d": {"window", "strides"},
    "avg_pool2d": {"window", "strides"},
    "global_avg_pool": set(),
    "flatten": set(),
    "activation": {"name"},
    "layer_norm": set(),
    "dropout": {"rate"},
    "embed": {"vocab_size", "dim"},
    "batch_norm": set(),
}


def _activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; one of {sorted(_ACTIVATIONS)}") from None


def _pair(v) -> Tuple[int, int]:
    return (int(v), int(v)) if isinstance(v, int) else (int(v[0]), int(v[1]))


@register_model("sequential")
class Sequential(nn.Module):
    """Applies ``layers`` (a tuple of layer-config dicts) in order."""

    layers: Tuple[Dict[str, Any], ...] = ()
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        if not self.layers:
            raise ValueError("sequential model needs at least one layer")
        for i, layer in enumerate(self.layers):
            kind = layer.get("kind")
            if kind in _ALLOWED_KEYS:
                extra = set(layer) - _ALLOWED_KEYS[kind] - {"kind"}
                if extra:
                    raise ValueError(
                        f"layer {i}: unknown key(s) {sorted(extra)} for kind "
                        f"{kind!r}; allowed: {sorted(_ALLOWED_KEYS[kind])}")
            if kind == "dense":
                x = nn.Dense(int(layer["units"]), dtype=self.compute_dtype,
                             name=f"dense_{i}")(x)
            elif kind == "conv2d":
                x = nn.Conv(int(layer["filters"]), _pair(layer["kernel_size"]),
                            strides=_pair(layer.get("strides", 1)),
                            padding=layer.get("padding", "SAME"),
                            dtype=self.compute_dtype, name=f"conv_{i}")(x)
            elif kind == "max_pool2d":
                w = _pair(layer["window"])
                x = nn.max_pool(x, w, strides=_pair(layer.get("strides") or layer["window"]))
            elif kind == "avg_pool2d":
                w = _pair(layer["window"])
                x = nn.avg_pool(x, w, strides=_pair(layer.get("strides") or layer["window"]))
            elif kind == "global_avg_pool":
                x = x.mean(axis=tuple(range(1, x.ndim - 1)))
            elif kind == "flatten":
                x = x.reshape((x.shape[0], -1))
            elif kind == "activation":
                x = _activation(layer["name"])(x)
            elif kind == "layer_norm":
                x = nn.LayerNorm(dtype=self.compute_dtype, name=f"ln_{i}")(x)
            elif kind == "dropout":
                x = nn.Dropout(float(layer["rate"]))(x, deterministic=not train)
            elif kind == "embed":
                x = nn.Embed(int(layer["vocab_size"]), int(layer["dim"]),
                             dtype=self.compute_dtype, name=f"embed_{i}")(x)
            elif kind == "batch_norm":
                raise ValueError(
                    "batch_norm is not supported (mutable batch_stats don't "
                    "thread through the compiled trainers); use layer_norm")
            else:
                raise ValueError(f"layer {i}: unknown kind {kind!r}")
            act = layer.get("activation")
            if act and kind in ("dense", "conv2d"):
                x = _activation(act)(x)
        return x


# -- layer-dict constructors (the user-facing sugar) --------------------------

def dense(units: int, activation: Optional[str] = None) -> dict:
    return {"kind": "dense", "units": units, "activation": activation}


def conv2d(filters: int, kernel_size: Union[int, Sequence[int]],
           strides: Union[int, Sequence[int]] = 1, padding: str = "SAME",
           activation: Optional[str] = None) -> dict:
    return {"kind": "conv2d", "filters": filters, "kernel_size": kernel_size,
            "strides": strides, "padding": padding, "activation": activation}


def max_pool2d(window: Union[int, Sequence[int]],
               strides: Union[int, Sequence[int], None] = None) -> dict:
    return {"kind": "max_pool2d", "window": window, "strides": strides}


def avg_pool2d(window: Union[int, Sequence[int]],
               strides: Union[int, Sequence[int], None] = None) -> dict:
    return {"kind": "avg_pool2d", "window": window, "strides": strides}


def global_avg_pool() -> dict:
    return {"kind": "global_avg_pool"}


def flatten() -> dict:
    return {"kind": "flatten"}


def activation(name: str) -> dict:
    return {"kind": "activation", "name": name}


def layer_norm() -> dict:
    return {"kind": "layer_norm"}


def dropout(rate: float) -> dict:
    return {"kind": "dropout", "rate": rate}


def embed(vocab_size: int, dim: int) -> dict:
    return {"kind": "embed", "vocab_size": vocab_size, "dim": dim}


def sequential_spec(layers: Sequence[dict], input_shape: Sequence[int],
                    input_dtype: str = "float32"):
    """ModelSpec for a layer stack: the Keras-``Sequential`` entry point.

    >>> spec = sequential_spec(
    ...     [conv2d(32, 3, activation="relu"), max_pool2d(2),
    ...      flatten(), dense(128, "relu"), dense(10)],
    ...     input_shape=(28, 28, 1))
    """
    from distkeras_tpu.models.base import ModelSpec

    layers = [dict(l) for l in layers]
    return ModelSpec(
        name="sequential",
        config={"layers": layers},
        input_shape=tuple(input_shape),
        input_dtype=input_dtype,
    )
