"""Speculative decoding: draft-model lookahead with exact target parity.

No reference counterpart (the reference predates LMs) — TPU-native
inference headroom on top of ``models/decode.py``: a small draft model
proposes ``k`` tokens autoregressively, the target model scores the whole
proposal in ONE k+1-token cached forward (an MXU-shaped matmul instead of
k+1 serial single-token steps), and the longest agreeing prefix commits.
Greedy acceptance makes every committed token the argmax of a target
forward over the true committed prefix — the output is a greedy decode of
the target by construction; the draft changes the schedule, never the
distribution.  In float32 it is bit-identical to ``make_generate_fn``'s
single-token path (the test invariant, ``tests/test_speculative``); in
bfloat16 the k+1-window forward can flip argmax near-ties relative to the
single-token forward (different matmul shapes accumulate differently), so
the two equally-valid greedy trajectories may diverge after such a tie.

Measured on v5e (8-layer/512-dim bf16 target, 2-layer/256-dim draft,
k=4, 256 new tokens): 1.17-1.41x over plain greedy decoding depending on
acceptance rate.

Per loop iteration, with m = number of accepted draft tokens (0..k):
``m + 1`` tokens commit (the accepted prefix plus the target's correction
— or, when all k agree, its bonus token from the same forward).  Serial
target steps per committed token: 1/(m+1).

KV-cache bookkeeping exploits the decode module's position masking: cache
rows beyond the current write position are dead (masked by
``key_pos <= q_pos``), so rejecting a speculation is just *not advancing*
the position — the stale rows get overwritten when decoding resumes
there.  After each iteration one extra draft token-forward fills the one
cache row sequential drafting didn't write, so both caches stay
row-aligned with the committed sequence.

The whole generation — both prefills and the while-loop of
draft/verify/commit iterations — is one compiled program.

Batched decoding commits in LOCKSTEP: each round accepts the batch
MINIMUM agreeing prefix, so every row advances the shared cache write
position together and the cache machinery stays identical to batch 1.
Rows whose own prefix was longer commit tokens that their verification
already endorsed (their accepted draft token equals their greedy token at
every committed position), so per-row outputs remain exact greedy decodes
— the batch minimum costs throughput (expected accepted prefix shrinks
as agreement^batch per position), never correctness.

``temperature > 0`` switches from greedy verification to exact
speculative SAMPLING (:func:`speculative_accept`): proposals are sampled
from the draft and accepted with prob ``min(1, p/q)``, rejections
resample the residual — committed tokens are exact temperature-T target
samples, in distribution rather than bit-equality.

``eos_id`` enables EOS with the plain decoder's exact semantics (EOS
kept, pads after, per row) and the loop exits EARLY once every row is
done; finished rows are credited a full accept so their pad-fed drafts
cannot throttle the live rows' lockstep minimum.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.base import ModelSpec
from distkeras_tpu.models.decode import (KVCache, _sample, dequant_embed,
                                         forward_with_cache, fused_token_forward,
                                         init_cache, make_fused_state)


def speculative_accept(key, target_probs, draft_probs, drafted):
    """One row's exact speculative-SAMPLING acceptance (the standard
    accept/residual scheme: Leviathan et al. / Chen et al. 2023).

    ``target_probs`` [k+1, V] — the target distribution after each prefix
    position of the verification window; ``draft_probs`` [k, V] — the
    draft distribution each proposal was sampled from; ``drafted`` [k].
    Returns ``(m, token_m)``: the number of accepted proposals and the
    token to commit at position ``m``.

    Rule: proposal i is accepted iff ``u_i * q(x_i) < p(x_i)`` (i.e.
    ``u_i < min(1, p/q)``); on the first rejection the committed token is
    sampled from the normalized residual ``max(p - q, 0)``; if all k are
    accepted it is a bonus sample from ``target_probs[k]`` (the residual
    expression reduces to exactly that because q is set to 0 there).
    Per-position committed-token marginals equal the target distribution
    — the property ``tests/test_speculative.py`` checks in closed form
    and statistically.
    """
    k_ = drafted.shape[0]
    u = jax.random.uniform(jax.random.fold_in(key, 0), (k_,))
    p_x = jnp.take_along_axis(target_probs[:k_], drafted[:, None], 1)[:, 0]
    q_x = jnp.take_along_axis(draft_probs, drafted[:, None], 1)[:, 0]
    # u*q < p  <=>  u < p/q, and stays well-defined at q == 0 (accept iff
    # p > 0 — a zero-probability proposal can only appear through argmax
    # ties or numerics, and the rule still keeps the output exact)
    accept = (u * q_x < p_x).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(accept))
    p_m = jnp.take(target_probs, m, axis=0)
    q_m = jnp.where(m < k_,
                    jnp.take(draft_probs, jnp.minimum(m, k_ - 1), axis=0), 0.0)
    residual = jnp.maximum(p_m - q_m, 0.0)
    token = jax.random.categorical(jax.random.fold_in(key, 1),
                                   jnp.log(residual + 1e-30))
    return m, token.astype(jnp.int32)


def make_speculative_generate_fn(target_spec: ModelSpec, draft_spec: ModelSpec,
                                 max_new_tokens: int, *, k: int = 4,
                                 temperature: float = 0.0,
                                 eos_id: Optional[int] = None, pad_id: int = 0,
                                 with_stats: bool = False,
                                 draft_step_impl: Optional[str] = None,
                                 quantize_cache: bool = False):
    """Build a jitted ``(target_params, draft_params, prompt [B, P]) ->
    tokens [B, max_new_tokens]`` — greedy; bit-identical to
    ``make_generate_fn(target_spec, ...)`` in float32 (see module docstring
    for the bfloat16 near-tie caveat and the batched lockstep-commit rule).

    ``k`` = draft tokens proposed per verification step.  The two specs
    must share vocab; the draft is typically a smaller ``num_layers``/
    ``model_dim`` model (possibly int8-quantized — both param trees ride
    the decode module's QTensor support).

    ``eos_id`` enables EOS handling with ``make_generate_fn``'s exact
    semantics: the EOS token itself is kept, rows past it emit ``pad_id``,
    and the loop exits EARLY once every row is done (the committed-token
    contract makes the pre-EOS prefix identical to the plain decoder's,
    so the two paths stay output-equal with or without EOS).

    ``temperature > 0`` switches to exact speculative SAMPLING: the draft
    samples its proposals from ``softmax(logits/T)`` and each proposal is
    accepted/resampled by :func:`speculative_accept`, so every committed
    token is distributed exactly as a plain temperature-``T`` sample from
    the target (the draft changes the schedule, never the distribution —
    same contract as the greedy path, now in distribution rather than
    bit-equality).  The returned fn then takes an optional ``rng`` last
    argument (default ``PRNGKey(0)``).  Batched sampling uses the same
    lockstep batch-minimum commit as greedy.

    ``draft_step_impl``: the draft's k sequential single-token proposal
    steps are the serial bottleneck of every round, and they run on a
    SMALL model — exactly the regime where the fused Pallas decode-step
    kernel (``ops/decode_step.py``) beats the XLA step (2.1x at
    2-layer/128-dim, v5e device time).  ``None`` auto-selects it on TPU
    at batch 1 for draft shapes inside the kernel's measured win region;
    ``"fused"``/``"xla"`` pin the path.  The target's k+1-token verify
    window is MXU-shaped and always stays XLA.

    ``quantize_cache=True`` stores BOTH models' KV int8 with per-(position,
    head) scales (:class:`~distkeras_tpu.models.decode.QKVCache`), exactly
    like ``make_generate_fn``'s flag: cache HBM traffic halves — the
    dominant batched-decode cost, 1.91x on the plain b64 leg — at one
    rounding step per K/V row.  Rewound draft rows re-quantize on
    overwrite (per-position state, so the rewind semantics are
    unchanged).  Requires the XLA draft step (the fused kernel's slabs
    are bf16), so it suits the BATCHED regime where the fused draft
    would not be auto-selected anyway.

    ``with_stats=True`` returns ``(tokens, iterations)`` where
    ``iterations`` is the number of draft/verify rounds the while-loop ran.
    Without EOS the loop commits ``max_new_tokens - 1`` tokens (the first
    output token comes from the prompt prefill, before the loop), each
    round committing ``m + 1``, so mean accepted draft tokens per round is
    ``(max_new_tokens - 1)/iterations - 1`` and the acceptance rate is
    that divided by ``k`` — the number a benchmark must report for a
    speculative-decoding claim to mean anything.  (Under an EOS early
    exit fewer tokens are committed, so that formula UNDERSTATES nothing
    but the benchmarks run without EOS.)
    """
    from distkeras_tpu.models.decode import validate_decode_spec

    t_cfg = validate_decode_spec(target_spec, "target decoding")
    d_cfg = validate_decode_spec(draft_spec, "draft decoding")
    if t_cfg["vocab_size"] != d_cfg["vocab_size"]:
        raise ValueError(f"vocab mismatch: target {t_cfg['vocab_size']} vs "
                         f"draft {d_cfg['vocab_size']}")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if not temperature >= 0.0:  # also rejects NaN
        raise ValueError(f"temperature must be >= 0, got {temperature} "
                         "(a negative value would silently select greedy)")
    if draft_step_impl not in (None, "fused", "xla"):
        raise ValueError(f"unknown draft_step_impl {draft_step_impl!r}; "
                         "use None, 'fused' or 'xla'")
    if quantize_cache and draft_step_impl == "fused":
        raise ValueError("quantize_cache requires the XLA draft step: the "
                         "fused kernel's slabs are bf16 (draft_step_impl="
                         "'xla' or None)")
    if quantize_cache:
        from distkeras_tpu.models.decode import warn_quantized_cache_gqa

        # both caches quantize; warn per model so the message names which
        # spec carries the GQA config (the draft rarely does)
        warn_quantized_cache_gqa(t_cfg, "make_speculative_generate_fn (target)")
        warn_quantized_cache_gqa(d_cfg, "make_speculative_generate_fn (draft)")

    sampling = temperature > 0.0

    @functools.partial(jax.jit, static_argnames=("prompt_len", "d_impl"))
    def run(t_params, d_params, prompt, rng, prompt_len, d_impl):
        n = max_new_tokens
        b = prompt.shape[0]
        total = prompt_len + n + k + 1  # speculative writes may run past n
        for name, cfg in (("target", t_cfg), ("draft", d_cfg)):
            # learned positional tables bound the reachable positions; rope
            # models have no table (cache sizing is the only capacity here)
            if ((cfg.get("positional") or "learned") == "learned"
                    and total > cfg["max_seq_len"]):
                raise ValueError(
                    f"prompt + max_new_tokens + k = {total} exceeds the "
                    f"{name} positional table max_seq_len = "
                    f"{cfg['max_seq_len']}")
        t_params = dequant_embed(t_params)
        d_params = dequant_embed(d_params)
        d_total = total
        if d_impl == "fused":
            from distkeras_tpu.ops.decode_step import round_cache_len

            d_total = round_cache_len(total)  # dead rows stay masked
        t_cache = init_cache(t_cfg, b, total, quantized=quantize_cache)
        d_cache = init_cache(d_cfg, b, d_total, quantized=quantize_cache)

        t_logits, t_cache = forward_with_cache(t_params, t_cfg, prompt, 0,
                                               t_cache, last_only=True)
        _, d_cache = forward_with_cache(d_params, d_cfg, prompt, 0, d_cache,
                                        last_only=True)
        if d_impl == "fused":
            from distkeras_tpu.ops.decode_step import transpose_k_cache

            # built once (loop-invariant); draft K goes lane-major for the
            # fused kernel, exactly as in make_generate_fn's fused branch
            d_state = make_fused_state(d_params, d_cfg)
            d_cache = KVCache(transpose_k_cache(d_cache.k), d_cache.v)

        def draft_token_step(tok, pos_, cache):
            """One draft single-token forward: [B] -> (f32 logits [B, V],
            cache) via the fused kernel or the XLA step."""
            if d_impl == "fused":
                logits, k_t, v_all = fused_token_forward(
                    d_state, tok, pos_, cache.k, cache.v)
                return logits[:, -1].astype(jnp.float32), KVCache(k_t, v_all)
            logits, cache = forward_with_cache(d_params, d_cfg, tok[:, None],
                                               pos_, cache)
            return logits[:, -1].astype(jnp.float32), cache
        if sampling:
            rng, sub = jax.random.split(rng)
            cur = _sample(t_logits[:, -1].astype(jnp.float32), sub,
                          temperature, 0)  # [B]
        else:
            cur = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)  # [B]

        # out buffer padded by k+1: each iteration writes a full k+1 slab at
        # n_out; uncommitted tail is overwritten by the next iteration
        out = jnp.zeros((b, n + k + 1), jnp.int32)
        out = lax.dynamic_update_slice(out, cur[:, None], (0, 0))
        pos = jnp.asarray(prompt_len, jnp.int32)  # cache rows valid below pos
        n_out = jnp.asarray(1, jnp.int32)
        iters = jnp.asarray(0, jnp.int32)
        # the EOS token itself is kept in the output; rows pad after it
        done = (jnp.zeros(b, bool) if eos_id is None else cur == eos_id)

        def cond(carry):
            # early exit once EVERY row is done — the speculative loop's
            # version of the plain decoder's carried-done convention
            return (carry[0] < n) & ~jnp.all(carry[8])

        def body(carry):
            n_out, cur, pos, out, iters, rng, t_cache, d_cache, done = carry
            if sampling:
                rng, k_draft, k_verify = jax.random.split(rng, 3)

            # 1. draft k tokens autoregressively from cur (whole batch):
            # greedy argmax, or (sampling) draws from softmax(logits/T)
            # with the full draft distribution recorded for the accept rule
            def draft_step(c, i):
                tok, cache = c
                logits, cache = draft_token_step(tok, pos + i, cache)
                if sampling:
                    scaled = logits / temperature
                    nxt = jax.random.categorical(
                        jax.random.fold_in(k_draft, i), scaled,
                        axis=-1).astype(jnp.int32)
                    return (nxt, cache), (nxt, jax.nn.softmax(scaled, axis=-1))
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), (nxt, jnp.float32(0))

            (_, d_cache), (drafted, d_probs) = lax.scan(
                draft_step, (cur, d_cache), jnp.arange(k))
            drafted = drafted.T  # [B, k]

            # 2. target scores the whole window [cur, d_1..d_k] in one pass
            window = jnp.concatenate([cur[:, None], drafted], axis=1)  # [B, k+1]
            t_logits, t_cache = forward_with_cache(t_params, t_cfg, window,
                                                   pos, t_cache)

            # 3. per-row accepted-prefix length m_r and the token each row
            # would commit at its own boundary
            if sampling:
                t_probs = jax.nn.softmax(
                    t_logits.astype(jnp.float32) / temperature, axis=-1)
                row_keys = jax.vmap(jax.random.fold_in, (None, 0))(
                    k_verify, jnp.arange(b))
                m_rows, token_rows = jax.vmap(speculative_accept)(
                    row_keys, t_probs, d_probs.transpose(1, 0, 2), drafted)
            else:
                greedy = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
                matches = (drafted == greedy[:, :k]).astype(jnp.int32)
                m_rows = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                token_rows = None  # greedy[:, m] is taken after m is known

            if eos_id is not None:
                # rows that finished BEFORE this round draft pad-fed
                # garbage; letting their arbitrary m_r into the batch
                # minimum would throttle every live row toward 1 token/
                # round.  Their slab is fully pad-masked below, so
                # crediting them a full accept is safe and removes the drag
                m_rows = jnp.where(done, k, m_rows)

            # lockstep commit: truncate every row to the batch MINIMUM so
            # all rows advance the shared cache position together.
            # Positions < m are accepted by EVERY row; at position m a row
            # whose private prefix ran longer (m_r > m) commits its own
            # ACCEPTED proposal drafted[r, m] (== its greedy token in the
            # greedy mode; an exact-marginal sample in sampling mode),
            # and a row with m_r == m commits its correction/residual
            # token — so each row's output stays an exact greedy decode /
            # exact temperature-T sample of the target.  Batch-1 reduces
            # to the classic per-row rule (min over 1 row).
            m = jnp.min(m_rows)
            if sampling:
                own = jnp.take(drafted, jnp.minimum(m, k - 1), axis=1)
                token_m = jnp.where(m_rows > m, own, token_rows)
            else:
                token_m = jnp.take(greedy, m, axis=1)
            idx = jnp.arange(k + 1)
            padded = jnp.concatenate([drafted, drafted[:, -1:]], axis=1)
            slab = jnp.where(idx[None, :] < m, padded,
                             token_m[:, None])  # [B, k+1]
            if eos_id is not None:
                # committed positions strictly AFTER a row's first EOS (or
                # every position of an already-done row) become pad_id;
                # EOS beyond the committed prefix is dead weight and must
                # not latch `done`.  Rows whose pre-EOS tokens are exact
                # stay exact — only the padded tail differs from the raw
                # slab, exactly like the plain decoder's carried-done rule.
                committed_mask = idx[None, :] <= m
                is_eos = (slab == eos_id) & committed_mask
                eos_before = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                              - is_eos.astype(jnp.int32)) > 0
                after = done[:, None] | eos_before
                slab = jnp.where(after, pad_id, slab)
                done = done | jnp.any(is_eos, axis=1)
            out = lax.dynamic_update_slice(out, slab, (0, n_out))
            committed = m + 1
            cur = jnp.take(slab, m, axis=1)  # [B]

            # 4. complete the draft cache: sequential drafting wrote rows
            # pos..pos+k-1 for [cur, d_1..d_{k-1}]; only the d_k row at
            # pos+k is missing, so ONE draft token-forward fills it (K/V
            # rows depend only on (token, position)).  Rows past
            # pos+committed are dead until decoding resumes there.  (On
            # the fused path the unused logits' unembed matmul is DCE'd.)
            _, d_cache = draft_token_step(drafted[:, -1], pos + k, d_cache)
            return (n_out + committed, cur, pos + committed, out, iters + 1,
                    rng, t_cache, d_cache, done)

        n_out, cur, pos, out, iters, _, _, _, done = lax.while_loop(
            cond, body,
            (n_out, cur, pos, out, iters, rng, t_cache, d_cache, done))
        if eos_id is not None:
            # an early exit leaves columns n_out..n unwritten (zeros);
            # they belong to all-done rows and must read as pad_id
            out = jnp.where(jnp.arange(n + k + 1)[None, :] < n_out, out, pad_id)
        if with_stats:
            return out[:, :n], iters
        return out[:, :n]

    def generate_fn(t_params, d_params, prompt, rng=None):
        from distkeras_tpu.ops.decode_step import resolve_step_impl

        prompt = jnp.asarray(prompt)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if quantize_cache:
            impl = "xla"  # QKVCache slabs are int8; the fused kernel's bf16
        else:
            impl = resolve_step_impl(
                d_cfg, prompt.shape[0],
                prompt.shape[1] + max_new_tokens + k + 1,
                draft_step_impl, what="draft_step_impl")
        return run(t_params, d_params, prompt, rng, prompt.shape[1], impl)

    return generate_fn
