"""Decoder-only TransformerLM — the framework's flagship long-context model.

Not present in the reference (it predates transformers; SURVEY.md §5) —
this is the TPU-native headroom model exercising the sequence-parallel
(ring attention) and tensor-parallel paths.  Designed MXU-first: all
matmuls are [*, model_dim] x [model_dim, *] with dims that tile 128 lanes;
``param_dtype`` float32 with bfloat16 activations via ``compute_dtype``.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.base import register_model
from distkeras_tpu.ops.attention import attention


class TransformerBlock(nn.Module):
    model_dim: int
    num_heads: int
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None  # mesh axis name for ring attention
    attn_impl: Optional[str] = None  # None=auto | "flash" (pallas) | "dense";
                                     # must stay None when seq_axis is set
                                     # (ring attention governs that path)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        head_dim = self.model_dim // self.num_heads
        y = nn.LayerNorm(dtype=self.compute_dtype)(x)
        qkv = nn.Dense(3 * self.model_dim, use_bias=False, dtype=self.compute_dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, l = q.shape[0], q.shape[1]
        q = q.reshape(b, l, self.num_heads, head_dim)
        k = k.reshape(b, l, self.num_heads, head_dim)
        v = v.reshape(b, l, self.num_heads, head_dim)
        o = attention(q, k, v, causal=True, axis_name=self.seq_axis, impl=self.attn_impl)
        o = o.reshape(b, l, self.model_dim)
        x = x + nn.Dense(self.model_dim, use_bias=False, dtype=self.compute_dtype, name="proj")(o)
        y = nn.LayerNorm(dtype=self.compute_dtype)(x)
        y = nn.Dense(self.mlp_ratio * self.model_dim, use_bias=False, dtype=self.compute_dtype, name="up")(y)
        y = nn.gelu(y)
        y = nn.Dense(self.model_dim, use_bias=False, dtype=self.compute_dtype, name="down")(y)
        return x + y


@register_model("transformer_lm")
class TransformerLM(nn.Module):
    """Causal LM over integer tokens [B, L] -> logits [B, L, vocab].

    When ``seq_axis`` is set the module must be called under ``shard_map``
    with the sequence dim sharded over that axis; position embeddings are
    then indexed by global position (handled inside the block's ring
    attention; the learned positional table here is sized for the *global*
    sequence and sliced by the caller-provided offset).
    """

    vocab_size: int = 32000
    model_dim: int = 512
    num_heads: int = 8
    num_layers: int = 6
    max_seq_len: int = 2048
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None
    attn_impl: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        b, l = tokens.shape
        embed = nn.Embed(self.vocab_size, self.model_dim, dtype=self.compute_dtype, name="embed")
        pos_table = self.param("pos_embed", nn.initializers.normal(0.02), (self.max_seq_len, self.model_dim))
        x = embed(tokens)
        pos = jnp.arange(l) + pos_offset
        x = x + pos_table[pos].astype(self.compute_dtype)
        for i in range(self.num_layers):
            x = TransformerBlock(
                model_dim=self.model_dim,
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                seq_axis=self.seq_axis,
                attn_impl=self.attn_impl,
                compute_dtype=self.compute_dtype,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        logits = embed.attend(x.astype(jnp.float32))
        return logits


def small_lm_spec(vocab_size: int = 1024, model_dim: int = 256, num_heads: int = 4,
                  num_layers: int = 4, max_seq_len: int = 512, seq_axis: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    return ModelSpec(
        name="transformer_lm",
        config={
            "vocab_size": vocab_size,
            "model_dim": model_dim,
            "num_heads": num_heads,
            "num_layers": num_layers,
            "max_seq_len": max_seq_len,
            "seq_axis": seq_axis,
        },
        input_shape=(max_seq_len,),
        input_dtype="int32",
    )
