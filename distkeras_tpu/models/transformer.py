"""Decoder-only TransformerLM — the framework's flagship long-context model.

Not present in the reference (it predates transformers; SURVEY.md §5) —
this is the TPU-native headroom model exercising the sequence-parallel
(ring attention) and tensor-parallel paths.  Designed MXU-first: all
matmuls are [*, model_dim] x [model_dim, *] with dims that tile 128 lanes;
``param_dtype`` float32 with bfloat16 activations via ``compute_dtype``.

Tensor parallelism (Megatron split, expressed in shard_map types):
- qkv is column-parallel over heads (kernel [E, 3, H, Dh], H sharded over
  the ``tp`` mesh axis), attention runs on the local head shard;
- proj is row-parallel (kernel [H, Dh, E]) producing a partial sum that is
  ``psum``'d over tp;
- MLP up is column-parallel ([E, F], F sharded), down row-parallel
  ([F, E]) followed by the second tp ``psum``.
Initialization always builds the FULL parameter tree (``tp_size=1``
semantics); the training step shards it onto the mesh and applies a module
configured with the LOCAL sizes (``tp_size=t``) inside ``shard_map`` —
see ``parallel/lm.py :: lm_param_specs``.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.base import register_model
from distkeras_tpu.ops.attention import attention


def _maybe_psum(x: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    """psum over ``axis_name`` when it is bound by an enclosing shard_map;
    identity when traced outside one (init, single-device eval)."""
    if axis_name is None or axis_name not in jax.typeof(x).vma:
        return x
    return lax.psum(x, axis_name)


class TransformerBlock(nn.Module):
    model_dim: int
    num_heads: int            # GLOBAL head count; local = num_heads // tp_size
    num_kv_heads: Optional[int] = None  # grouped-query attention (GQA,
                              # Ainslie et al. 2023): K/V projected to this
                              # many heads, each shared by num_heads/
                              # num_kv_heads query heads.  None = MHA (the
                              # fused qkv projection and its param layout
                              # are preserved exactly); set => separate
                              # "q" and "kv" projections.  The win is the
                              # decode KV cache (num_kv_heads/num_heads
                              # the bytes) and the ring's ICI traffic
    mlp_ratio: int = 4
    positional: str = "learned"  # "learned" (table added at embed) | "rope"
                                 # (q/k rotated here by ABSOLUTE position —
                                 # pos_offset carries the caller's global
                                 # offset, e.g. rank * L_local under sp)
    seq_axis: Optional[str] = None  # mesh axis name for ring attention
    tp_axis: Optional[str] = None   # mesh axis name for tensor parallelism
    tp_size: int = 1
    attn_impl: Optional[str] = None  # None=auto | "flash" (pallas) | "dense";
                                     # must stay None when seq_axis is set
                                     # (ring attention governs that path)
    moe_experts: int = 0       # > 0 replaces the dense FFN with a Switch
    moe_capacity: int = 0      # MoE layer (see parallel/moe.py); capacity
    moe_top_k: int = 1         # is per-expert slots per shard; top_k 1=
    ep_axis: Optional[str] = None   # Switch, 2 = GShard-style gating
    ep_size: int = 1
    moe_dispatch: str = "auto"  # "dense" | "sorted" | "auto" dispatch path
                                # (parallel/moe.py resolve_dispatch_impl)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        if self.num_heads % self.tp_size:
            raise ValueError(f"num_heads {self.num_heads} not divisible by tp_size {self.tp_size}")
        if self.positional not in ("learned", "rope"):
            raise ValueError(f"positional must be 'learned' or 'rope', "
                             f"got {self.positional!r}")
        if self.moe_experts and self.tp_size > 1:
            raise ValueError("MoE FFN does not compose with tensor parallelism (v1); "
                             "use either moe_experts or tp_size")
        if self.moe_experts and self.seq_axis is not None:
            raise ValueError("MoE FFN does not compose with sequence parallelism "
                             "(v1); train MoE LMs with make_moe_lm_train_step")
        heads_local = self.num_heads // self.tp_size
        head_dim = self.model_dim // self.num_heads
        ffn_local = self.mlp_ratio * self.model_dim // self.tp_size
        kv_heads = self.num_kv_heads or self.num_heads
        if self.num_heads % kv_heads:
            raise ValueError(f"num_heads {self.num_heads} not a multiple of "
                             f"num_kv_heads {kv_heads}")

        y = nn.LayerNorm(dtype=self.compute_dtype)(x)
        if kv_heads == self.num_heads:
            qkv = nn.DenseGeneral((3, heads_local, head_dim), use_bias=False,
                                  dtype=self.compute_dtype, name="qkv")(y)  # [B, L, 3, Hl, Dh]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            if kv_heads % self.tp_size:
                raise ValueError(f"num_kv_heads {kv_heads} not divisible by "
                                 f"tp_size {self.tp_size}")
            q = nn.DenseGeneral((heads_local, head_dim), use_bias=False,
                                dtype=self.compute_dtype, name="q")(y)
            kv = nn.DenseGeneral((2, kv_heads // self.tp_size, head_dim),
                                 use_bias=False, dtype=self.compute_dtype,
                                 name="kv")(y)
            k, v = kv[:, :, 0], kv[:, :, 1]
        if self.positional == "rope":
            from distkeras_tpu.ops.rotary import rope_rotate

            # pos_offset is the caller's GLOBAL offset of this sequence
            # block: the sp training step passes rank * L_local (the same
            # offset contract the learned table's slicing uses), decoding
            # rotates inside its own cache path, and plain training passes 0
            pos = pos_offset + jnp.arange(x.shape[1])
            q, k = rope_rotate(q, pos), rope_rotate(k, pos)
        o = attention(q, k, v, causal=True, axis_name=self.seq_axis, impl=self.attn_impl)
        o = nn.DenseGeneral(self.model_dim, axis=(-2, -1), use_bias=False,
                            dtype=self.compute_dtype, name="proj")(o)  # [B, L, E] partial
        x = x + _maybe_psum(o, self.tp_axis)

        y = nn.LayerNorm(dtype=self.compute_dtype)(x)
        if self.moe_experts:
            from distkeras_tpu.parallel.moe import MoEMLP

            b, l, e = y.shape
            # default capacity: factor-2 over the balanced share per expert
            # (capacity T would make dispatch [T, E, T] — O(T^2) memory)
            cap = self.moe_capacity or -(-2 * b * l // self.moe_experts)
            moe_out, aux = MoEMLP(
                num_experts=self.moe_experts, model_dim=self.model_dim,
                hidden_dim=self.mlp_ratio * self.model_dim,
                capacity=cap,
                ep_axis=self.ep_axis, ep_size=self.ep_size,
                router_top_k=self.moe_top_k,
                dispatch_impl=self.moe_dispatch,
                compute_dtype=self.compute_dtype, name="moe")(y.reshape(b * l, e))
            self.sow("aux_loss", "load_balance", aux)
            return x + moe_out.reshape(b, l, e)
        y = nn.Dense(ffn_local, use_bias=False, dtype=self.compute_dtype, name="up")(y)
        y = nn.gelu(y)
        y = nn.Dense(self.model_dim, use_bias=False, dtype=self.compute_dtype, name="down")(y)
        return x + _maybe_psum(y, self.tp_axis)


@register_model("transformer_lm")
class TransformerLM(nn.Module):
    """Causal LM over integer tokens [B, L] -> logits [B, L, vocab].

    When ``seq_axis`` is set the module must be called under ``shard_map``
    with the sequence dim sharded over that axis; position embeddings are
    then indexed by global position (handled inside the block's ring
    attention; the learned positional table here is sized for the *global*
    sequence and sliced by the caller-provided offset).  When ``tp_axis``/
    ``tp_size`` are set the module expects the LOCAL parameter shards
    (see module docstring).
    """

    vocab_size: int = 32000
    model_dim: int = 512
    num_heads: int = 4   # head_dim 128 = model_dim/num_heads: the v5e-
                         # recommended config (BASELINE.md head-dim study:
                         # at IDENTICAL FLOPs, head_dim 128 contracts the
                         # attention matmuls over the MXU's full 128-wide
                         # systolic dim and halves per-score VPU overhead —
                         # 0.577 vs 0.389 MFU at 2k tokens vs head_dim 64)
    num_kv_heads: Optional[int] = None  # GQA (see TransformerBlock); None = MHA
    num_layers: int = 6
    max_seq_len: int = 2048  # positional-table size under "learned"; under
                             # "rope" only the decode cache-sizing bound
    mlp_ratio: int = 4
    positional: str = "learned"  # "learned" | "rope" (see TransformerBlock)
    seq_axis: Optional[str] = None
    tp_axis: Optional[str] = None
    tp_size: int = 1
    attn_impl: Optional[str] = None
    remat: bool = False  # rematerialize each block in the backward pass:
                         # activation memory O(layers) -> O(1) blocks, the
                         # standard FLOPs-for-HBM trade for long sequences
    moe_experts: int = 0       # > 0: every block's FFN becomes a Switch MoE
    moe_capacity: int = 0      # (0 = default to 2x the balanced share per
                               # expert; imbalanced routing beyond that
                               # still drops tokens to the residual path)
    moe_top_k: int = 1         # 1 = Switch routing, 2 = GShard-style top-2
    moe_dispatch: str = "auto"  # dispatch path: "dense" | "sorted" | "auto"
    ep_axis: Optional[str] = None
    ep_size: int = 1
    compute_dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        # attribute names ARE the param-tree keys: "embed", "pos_embed",
        # "block_0..N-1" (list attr `block` -> `block_{i}`), "final_norm".
        # parallel/pipeline.py splits on the block_ prefix and shards the
        # rest as replicated "outer" leaves.  NOTE: "final_norm" replaces
        # the compact-era auto-name "LayerNorm_0" — an intentional
        # serialized-format break (no published checkpoints predate it).
        self.embed = nn.Embed(self.vocab_size, self.model_dim, dtype=self.compute_dtype)
        if self.positional == "learned":
            self.pos_embed = self.param(
                "pos_embed", nn.initializers.normal(0.02), (self.max_seq_len, self.model_dim))
        self.block = [
            TransformerBlock(
                model_dim=self.model_dim,
                num_heads=self.num_heads,
                num_kv_heads=self.num_kv_heads,
                mlp_ratio=self.mlp_ratio,
                seq_axis=self.seq_axis,
                tp_axis=self.tp_axis,
                tp_size=self.tp_size,
                attn_impl=self.attn_impl,
                moe_experts=self.moe_experts,
                moe_capacity=self.moe_capacity,
                moe_top_k=self.moe_top_k,
                moe_dispatch=self.moe_dispatch,
                ep_axis=self.ep_axis,
                ep_size=self.ep_size,
                positional=self.positional,
                compute_dtype=self.compute_dtype,
            )
            for _ in range(self.num_layers)
        ]
        self.final_norm = nn.LayerNorm(dtype=self.compute_dtype)

    def embed_tokens(self, tokens: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        """Token (+ learned positional) embedding: [B, L] int32 -> [B, L, E].

        A real bound method (not a free function passed to
        ``apply(method=...)``) so the pipeline-parallel step can run the
        embedding alone against the same param leaves as ``__call__``.
        Under ``positional="rope"`` there is no table — position enters
        through the per-block q/k rotation instead.
        """
        x = self.embed(tokens)
        if self.positional != "learned":
            return x
        pos = jnp.arange(tokens.shape[1]) + pos_offset
        return x + self.pos_embed[pos].astype(self.compute_dtype)

    def head(self, x: jnp.ndarray) -> jnp.ndarray:
        """Final norm + tied unembedding: [B, L, E] -> [B, L, vocab] logits."""
        x = self.final_norm(x)
        return self.embed.attend(x.astype(jnp.float32))

    def _trunk(self, tokens: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        """Embedding + blocks, BEFORE the final norm: [B, L] -> [B, L, E]."""
        x = self.embed_tokens(tokens, pos_offset)
        # pos_offset rides as a DYNAMIC remat arg: under sequence
        # parallelism it is a traced axis_index expression, not a constant
        run = (nn.remat(lambda m, y, po: m(y, po), prevent_cse=False)
               if self.remat else (lambda m, y, po: m(y, po)))
        for blk in self.block:
            x = run(blk, x, pos_offset)
        return x

    def hidden(self, tokens: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        """Forward WITHOUT the unembed: [B, L] -> final-normed [B, L, E].

        Train-loss entry point: pair with ``ops.losses.unembed_cross_entropy``
        (against ``params['embed']['embedding']``) so the [B, L, vocab]
        float32 logits tensor is computed chunkwise in bfloat16 instead of
        materialized by ``head``'s float32 ``attend`` — kills the
        half-rate f32 unembed matmul and O(B*L*V) activation memory.
        """
        return self.final_norm(self._trunk(tokens, pos_offset))

    def __call__(self, tokens: jnp.ndarray, pos_offset: int = 0) -> jnp.ndarray:
        return self.head(self._trunk(tokens, pos_offset))


def small_lm_spec(vocab_size: int = 1024, model_dim: int = 256, num_heads: int = 2,
                  num_layers: int = 4, max_seq_len: int = 512, seq_axis: Optional[str] = None,
                  tp_axis: Optional[str] = None, remat: bool = False,
                  moe_experts: int = 0, moe_capacity: int = 0,
                  moe_top_k: int = 1, moe_dispatch: str = "auto",
                  num_kv_heads: Optional[int] = None,
                  positional: str = "learned",
                  attn_impl: Optional[str] = None):
    from distkeras_tpu.models.base import ModelSpec

    # num_heads defaults keep head_dim = model_dim/num_heads at 128, the
    # v5e-recommended config (see TransformerLM.num_heads); pass num_heads
    # explicitly when a different head_dim is the point (A/B experiments,
    # tp_size divisibility)
    return ModelSpec(
        name="transformer_lm",
        config={
            "vocab_size": vocab_size,
            "model_dim": model_dim,
            "num_heads": num_heads,
            "num_kv_heads": num_kv_heads,
            "positional": positional,
            "num_layers": num_layers,
            "max_seq_len": max_seq_len,
            "seq_axis": seq_axis,
            "tp_axis": tp_axis,
            "remat": remat,
            "moe_experts": moe_experts,
            "moe_capacity": moe_capacity,
            "moe_top_k": moe_top_k,
            "moe_dispatch": moe_dispatch,
            # None = auto-select per ops.attention.attention (flash on TPU
            # at L >= 2048, device-time validated across head_dim 64/128);
            # "flash"/"dense" pin the kernel for A/B measurement
            "attn_impl": attn_impl,
        },
        input_shape=(max_seq_len,),
        input_dtype="int32",
    )
