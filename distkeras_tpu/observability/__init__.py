"""Unified telemetry: metrics registry + span tracing + exporters.

The subsystem ISSUE #1 specified — a dependency-free observability layer
threaded through every runtime layer (trainers, window engine, PS hub,
async engine, feed path, MoE router, punchcard daemon):

- :mod:`.metrics` — process-wide registry of counters / gauges /
  log-bucket histograms; thread-safe; near-zero cost while disabled.
- :mod:`.tracing` — context-manager spans in a bounded ring buffer,
  exportable as Chrome ``trace_event`` JSON and JSONL.
- :mod:`.sinks` — periodic JSONL flusher + Prometheus text exposition
  (label values escaped per the text-format spec).
- :mod:`.distributed` — fleet-wide tracing (ISSUE #5): per-worker
  :class:`~.distributed.TraceContext` propagated over the PS wire,
  NTP-style clock alignment from PS round trips,
  :func:`~.distributed.merge_traces` (one Chrome trace for a whole job)
  and :func:`~.distributed.fleet_report` (straggler + staleness
  attribution).  Exposed lazily here (``obs.TraceContext`` etc.) so
  importing the package stays dependency- and cycle-free.

Telemetry is **disabled by default** (instrumented call sites cost one
branch).  Turn it on with :func:`enable` — or set ``DKT_TELEMETRY=1`` in
the environment, which enables it at import time (the no-code-change
switch for daemons and bench runs)::

    from distkeras_tpu import observability as obs

    obs.enable()
    trainer.train(ds)                       # every layer records as it runs
    obs.snapshot()                          # {"counters": ..., "gauges": ...}
    obs.TRACER.export_chrome("trace.json")  # load in chrome://tracing
    print(obs.render_prometheus())          # text exposition

Module-level ``counter``/``gauge``/``histogram``/``span`` bind to the
process-default ``REGISTRY``/``TRACER``; hot paths cache the returned
instrument objects (creation is a dict lookup, mutation is lock-free when
disabled).
"""

from __future__ import annotations

import os

from distkeras_tpu.observability.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    TimeSeries,
)
from distkeras_tpu.observability.sinks import JsonlFlusher
from distkeras_tpu.observability.tracing import SpanTracer

REGISTRY = MetricsRegistry(enabled=False)
TRACER = SpanTracer(enabled=False)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "TimeSeries", "SpanTracer", "JsonlFlusher", "REGISTRY", "TRACER",
    "enable", "disable", "enabled", "counter", "gauge", "histogram", "span",
    "snapshot", "chrome_trace", "render_prometheus", "reset",
    "track", "untrack", "series", "tracked_snapshot",
]


def enable() -> None:
    """Turn on the process-default registry AND tracer."""
    REGISTRY.enabled = True
    TRACER.enabled = True


def disable() -> None:
    REGISTRY.enabled = False
    TRACER.enabled = False


def enabled() -> bool:
    return REGISTRY.enabled


def counter(name: str, **labels: str) -> Counter:
    return REGISTRY.counter(name, **labels)


def gauge(name: str, **labels: str) -> Gauge:
    return REGISTRY.gauge(name, **labels)


def histogram(name: str, **labels: str) -> Histogram:
    return REGISTRY.histogram(name, **labels)


def span(name: str, **attrs):
    return TRACER.span(name, **attrs)


def track(name: str, window_s: float = 60.0, max_samples: int = 512) -> None:
    """Opt a metric name into sliding-window time series (ISSUE 8): every
    mutation of that instrument also lands one ``(monotonic_ts, value)``
    sample in an attached :class:`TimeSeries`, read back with
    :func:`series`/:func:`tracked_snapshot`.  Near-zero for untracked
    names (one ``is None`` branch per mutation)."""
    REGISTRY.track(name, window_s=window_s, max_samples=max_samples)


def untrack(name: str) -> None:
    REGISTRY.untrack(name)


def series(name: str, **labels: str):
    return REGISTRY.series(name, **labels)


def tracked_snapshot():
    return REGISTRY.tracked_snapshot()


def snapshot():
    return REGISTRY.snapshot()


def chrome_trace():
    return TRACER.chrome_trace()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def reset() -> None:
    """Drop all recorded metrics and spans (enabled flags unchanged)."""
    REGISTRY.reset()
    TRACER.clear()


# lazy access to the distributed-tracing layer (PEP 562): obs.TraceContext,
# obs.merge_traces(...), obs.fleet_report(...) resolve on first touch so the
# package import graph stays acyclic (distributed imports obs helpers back)
_DISTRIBUTED_EXPORTS = (
    "TraceContext", "new_span_id", "new_job_id", "activate", "deactivate",
    "current", "current_span_attrs", "record_clock_sync", "clock_sync_state",
    "flush_process_trace", "merge_traces", "export_merged", "load_trace_dir",
    "fleet_report",
)

# the fleet health plane (ISSUE 8), same lazy pattern: obs.HealthCollector,
# obs.health_snapshot() etc. resolve on first touch
_HEALTH_EXPORTS = (
    "HealthCollector", "HealthEvent", "HealthMonitor", "health_snapshot",
    "render_top",
)


def __getattr__(name: str):
    if name == "distributed" or name in _DISTRIBUTED_EXPORTS:
        import importlib

        # importlib (not ``from ... import``): the from-import machinery
        # resolves the submodule THROUGH this very __getattr__ before it
        # exists as an attribute, which would recurse forever
        distributed = importlib.import_module(
            "distkeras_tpu.observability.distributed")
        globals()["distributed"] = distributed
        return distributed if name == "distributed" else getattr(distributed, name)
    if name == "health" or name in _HEALTH_EXPORTS:
        import importlib

        health = importlib.import_module(
            "distkeras_tpu.observability.health")
        globals()["health"] = health
        return health if name == "health" else getattr(health, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


if os.environ.get("DKT_TELEMETRY", "").strip().lower() in ("1", "true", "on", "yes"):
    enable()
