"""Fleet-wide distributed tracing: context propagation, clock-aligned
trace merge, and straggler/staleness attribution.

PR 1's telemetry is strictly per-process: a worker's ``ps.pull_latency_ms``
and the hub's ``ps_commit_staleness`` cannot be joined into one causal
picture.  The paper lineage demands exactly that join — "How to scale
distributed deep learning?" (arXiv:1611.04581) attributes async-SGD
quality loss to *per-worker* staleness and stragglers, and elastic-PS work
(arXiv:2204.03211) makes membership churn a first-class signal.  This
module is the cross-process layer:

- :class:`TraceContext` — a ``(job_id, worker_id, span_id)`` identity each
  worker announces over the PS protocol (wire action ``T``,
  :mod:`distkeras_tpu.runtime.networking`), so hub-side spans
  (``ps.handle_commit``, ``ps.handle_pull``, snapshot, eviction) are
  attributable to the worker that caused them.  The context is carried
  thread-locally (:func:`activate` / :func:`current`) because async
  workers are threads of one process.
- **Clock alignment** — every process traces on its own monotonic clock
  (``time.perf_counter_ns``).  Worker processes estimate their offset to
  the hub's clock from the ``T`` announce round trips, NTP-style: the hub
  stamps its clock into the reply, and ``offset = hub_ts - (t0 + t1)/2``
  with error bound ``rtt/2`` for the minimum-RTT sample
  (:func:`record_clock_sync` keeps the best estimate per process).
- :func:`flush_process_trace` / :func:`merge_traces` — each process
  flushes its span ring as JSONL (one ``meta`` line with the offset
  estimate, then one line per span); the merge shifts every process onto
  the hub timeline and emits one Chrome trace with per-process tracks.

  **Alignment-error bound** (documented contract): a merged timestamp is
  off the hub timeline by at most its process's ``clock_error_ns``
  (= min-RTT/2 of its sync samples), so the relative error between spans
  of two processes is bounded by the SUM of their two error bounds —
  ``merge_traces`` reports the per-process bounds and their max in
  ``otherData``.  Same-process spans keep exact relative order (one
  clock, one shift).
- :func:`fleet_report` — joins hub commit records (per-commit staleness,
  attributed worker) with worker window spans to rank stragglers,
  attribute ADAG/DynSGD staleness to specific workers, and flag reconnect
  storms.  Exposed remotely via the punchcard ``telemetry`` action
  (``fetch_telemetry(..., fleet=True)``).

Dependency-free (stdlib only) and import-cycle-free: this module imports
only its :mod:`.metrics`/:mod:`.tracing` siblings; the runtime imports it,
never the reverse.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import random
import socket as _socket
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TraceContext", "new_span_id", "new_job_id",
    "activate", "deactivate", "current", "current_span_attrs",
    "record_clock_sync", "clock_sync_state", "reset_clock_sync",
    "flush_process_trace", "merge_traces", "export_merged", "load_trace_dir",
    "fleet_report",
]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Per-worker trace identity, announced once per PS connection (wire
    action ``T``) and attached to both ends' spans.  ``worker_id`` is the
    worker ordinal within the job; ``span_id`` is a random 63-bit id that
    distinguishes two incarnations of the same worker (a supervisor
    restart gets a fresh ``span_id``)."""

    job_id: str
    worker_id: int
    span_id: int

    def to_json(self) -> str:
        return json.dumps({"job_id": self.job_id,
                           "worker_id": int(self.worker_id),
                           "span_id": int(self.span_id)})

    @classmethod
    def from_json(cls, raw: bytes) -> "TraceContext":
        d = json.loads(raw if isinstance(raw, str) else bytes(raw).decode("utf-8"))
        return cls(job_id=str(d.get("job_id", "")),
                   worker_id=int(d.get("worker_id", -1)),
                   span_id=int(d.get("span_id", 0)))

    def span_attrs(self) -> Dict[str, Any]:
        """The attrs every span tagged with this context carries."""
        return {"job": self.job_id, "worker": int(self.worker_id),
                "ctx_span": int(self.span_id)}


def new_span_id() -> int:
    return random.getrandbits(63)


def new_job_id() -> str:
    """A fresh job id: short, unique enough for one trace directory."""
    return f"job-{random.getrandbits(32):08x}"


# -- thread-local context (workers are threads of one process) -----------------

_tls = threading.local()
_process_default: Optional[TraceContext] = None


def activate(ctx: Optional[TraceContext], process_default: bool = False) -> None:
    """Bind ``ctx`` to the calling thread (and optionally as the process
    fallback for threads that never activate one)."""
    global _process_default
    _tls.ctx = ctx
    if process_default:
        _process_default = ctx


def deactivate() -> None:
    _tls.ctx = None


def current() -> Optional[TraceContext]:
    """The calling thread's context, falling back to the process default.
    Hub code running IN a worker's thread (the inproc transport's
    ``commit_direct``) reads the committing worker's identity here."""
    return getattr(_tls, "ctx", None) or _process_default


def current_span_attrs() -> Dict[str, Any]:
    ctx = current()
    return ctx.span_attrs() if ctx is not None else {}


# -- clock sync (process-local best estimate) ----------------------------------

_clock_lock = threading.Lock()
_clock_offset_ns = 0
_clock_error_ns: Optional[int] = None


def record_clock_sync(offset_ns: int, error_ns: int) -> None:
    """Record one NTP-style offset estimate (local -> hub timeline:
    ``t_hub = t_local + offset_ns``; ``error_ns`` = rtt/2 of the sample).
    The process keeps the LOWEST-error estimate seen — every PSClient in
    the process syncs, and the tightest round trip wins."""
    global _clock_offset_ns, _clock_error_ns
    with _clock_lock:
        if _clock_error_ns is None or error_ns < _clock_error_ns:
            _clock_offset_ns = int(offset_ns)
            _clock_error_ns = int(error_ns)


def clock_sync_state() -> Tuple[int, Optional[int]]:
    """(best offset_ns, its error_ns or None if never synced)."""
    with _clock_lock:
        return _clock_offset_ns, _clock_error_ns


def reset_clock_sync() -> None:
    global _clock_offset_ns, _clock_error_ns
    with _clock_lock:
        _clock_offset_ns, _clock_error_ns = 0, None


# -- per-process trace flush ---------------------------------------------------

def flush_process_trace(directory: str, job_id: Optional[str] = None,
                        role: str = "process",
                        tracer: Any = None) -> str:
    """Write this process's span ring to ``directory`` as one JSONL file:
    first a ``{"kind": "meta", ...}`` line (pid, role, clock offset +
    error bound), then one ``{"kind": "span", ...}`` line per recorded
    span (timestamps stay on the LOCAL monotonic clock; the merge applies
    the offset).  Returns the written path.  The ``DKT_TRACE_DIR``
    environment knob points trainers and the standalone hub daemon here.
    """
    if tracer is None:
        from distkeras_tpu import observability as _obs

        tracer = _obs.TRACER
    os.makedirs(directory, exist_ok=True)
    offset_ns, error_ns = clock_sync_state()
    pid = os.getpid()
    host = _socket.gethostname()
    meta = {
        "kind": "meta",
        "pid": pid,
        "role": role,
        "job_id": job_id,
        "host": host,
        "clock_offset_ns": offset_ns,
        "clock_error_ns": error_ns,
        "wall_time": time.time(),
        "dropped_spans": getattr(tracer, "dropped", 0),
    }
    # hostname in the name: a shared multi-host trace dir must never let
    # two hosts with colliding PIDs overwrite each other's flush
    safe_host = "".join(c if c.isalnum() or c in "-_" else "_" for c in host)
    path = os.path.join(
        directory, f"trace-{job_id or 'nojob'}-{role}-{safe_host}-{pid}.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(meta) + "\n")
        for event in tracer.events():
            f.write(json.dumps(dict(event, kind="span")) + "\n")
    return path


# -- clock-aligned merge -------------------------------------------------------

def load_trace_dir(directory: str) -> Tuple[List[Dict[str, Any]],
                                            List[Dict[str, Any]]]:
    """Read every ``trace-*.jsonl`` under ``directory``: returns
    ``(metas, spans)`` where each span is tracer-shaped (``name``,
    ``ts_us``, ``dur_us``, ``tid``, ``attrs``) with its timestamps ALREADY
    shifted onto the hub timeline and a ``pid`` track key attached.  The
    track key is the file's ORDINAL, not the OS pid — two hosts flushing
    into one shared dir may collide on raw pids, and each file must stay
    its own track.  Unreadable lines are skipped (a process killed
    mid-flush loses only its own tail)."""
    metas: List[Dict[str, Any]] = []
    spans: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(directory, "trace-*.jsonl"))):
        meta: Dict[str, Any] = {"role": "unknown"}
        file_spans: List[Dict[str, Any]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail from a crashed flush
                if rec.get("kind") == "meta":
                    meta = rec
                elif rec.get("kind") == "span":
                    file_spans.append(rec)
        off_us = int(meta.get("clock_offset_ns") or 0) // 1000
        track = len(metas)
        for s in file_spans:
            s = dict(s)
            s["ts_us"] = int(s["ts_us"]) + off_us
            s["pid"] = track
            spans.append(s)
        meta = dict(meta, path=path, span_count=len(file_spans), track=track)
        metas.append(meta)
    return metas, spans


def merge_traces(directory: str) -> Dict[str, Any]:
    """One clock-aligned Chrome ``trace_event`` object for a whole job:
    every process flushed by :func:`flush_process_trace` becomes a track
    (``pid``), threads within it stay separate ``tid`` lanes, and all
    timestamps are shifted onto the hub timeline by each process's
    recorded offset.  ``otherData.alignment_error_us`` documents the
    worst-case single-process error bound (see module docstring for the
    pairwise bound — the sum of the two processes' bounds)."""
    metas, spans = load_trace_dir(directory)
    events: List[Dict[str, Any]] = []
    for meta in metas:
        label = f"{meta.get('role', 'process')}"
        if meta.get("job_id"):
            label += f" {meta['job_id']}"
        if meta.get("host"):
            label += f" {meta['host']}"
        label += f" (pid {meta.get('pid', '?')})"
        events.append({"ph": "M", "name": "process_name",
                       "pid": meta.get("track"), "tid": 0,
                       "args": {"name": label}})
    span_events = []
    for s in spans:
        span_events.append({
            "name": s["name"],
            "ph": "X",
            "ts": s["ts_us"],
            "dur": s.get("dur_us", 0),
            "pid": s["pid"],
            "tid": s.get("tid", 0),
            "args": dict(s.get("attrs") or {}, depth=s.get("depth", 0),
                         thread=s.get("thread", "")),
        })
    span_events.sort(key=lambda e: e["ts"])
    errors = {m.get("track"): m.get("clock_error_ns")
              for m in metas if m.get("clock_error_ns") is not None}
    max_err_ns = max(errors.values(), default=0)
    return {
        "traceEvents": events + span_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "processes": len(metas),
            "spans": len(span_events),
            "clock_error_ns_by_track": errors,
            "alignment_error_us": max_err_ns // 1000,
        },
    }


def export_merged(directory: str, path: str) -> str:
    """Write :func:`merge_traces`' Chrome trace to ``path``."""
    with open(path, "w") as f:
        json.dump(merge_traces(directory), f)
    return path


# -- straggler + staleness attribution -----------------------------------------

def _span_records(events: Optional[Iterable[Dict[str, Any]]],
                  trace_dir: Optional[str]) -> List[Dict[str, Any]]:
    if events is not None:
        return list(events)
    if trace_dir:
        metas, spans = load_trace_dir(trace_dir)
        if metas:
            return spans
        # the dir exists but nothing has flushed yet (processes flush at
        # END of run): fall back to this process's live ring so mid-job
        # pulls (punchcard fleet=True) still report
    from distkeras_tpu import observability as _obs

    return _obs.TRACER.events()


def fleet_report(events: Optional[Iterable[Dict[str, Any]]] = None,
                 trace_dir: Optional[str] = None,
                 storm_threshold: int = 3,
                 live: Optional[Any] = None) -> Dict[str, Any]:
    """Join hub commit records with worker window spans into one
    per-worker attribution table.

    Sources (first match wins): explicit tracer-shaped ``events``, a
    flushed ``trace_dir`` (clock-aligned across processes), else this
    process's live span ring.  Consumes:

    - ``async.window`` spans (worker attr) -> straggler ranking by mean
      window wall time;
    - ``ps.handle_commit`` spans (worker + staleness attrs, from the
      Python hub's handlers, ``commit_direct``, or the C++ hub's drained
      commit log) -> per-worker staleness attribution and the
      context-coverage ratio;
    - ``ps.reconnect`` spans (worker attr) -> reconnect storms (a worker
      with ``>= storm_threshold`` reconnects is flagged);
    - ``ps.failover`` spans (worker attr, from/to addresses) -> per-worker
      failover counts plus ``failovers_total`` and mean/max
      ``failover_ms`` — the hub-HA availability numbers;
    - ``ps.promote`` spans -> ``promotions`` (which standby hubs took
      over, at what clock);
    - ``ps.stripe_lost`` spans (shard + address attrs) -> ``stripes_lost``,
      so a striped client dying on ONE shard is attributed to that shard's
      hub instead of reading as a generic connection error.

    Sharded-hub runs (spans carry a ``shard`` attr): one LOGICAL commit
    lands as one per-shard span per shard, so per-worker commit counts and
    the coverage ratio are computed over shard-0 spans only (shard 0 is
    present in every plan; unsharded spans carry no attr and count as
    before) — aggregation across shards without double-counting — while
    the per-shard ``shards`` table consumes every span, ranking shards by
    mean commit-handler time so a slow shard is as nameable as a slow
    worker.

    Live mode (ISSUE 8): pass ``live=`` a
    :class:`~distkeras_tpu.observability.health.HealthCollector` and the
    report additionally carries its sliding-window snapshot under
    ``live`` — per-worker rolling rates/means the span join cannot see
    mid-run — and the ``coverage`` verdict accounts for it.

    Every report carries a ``coverage`` field saying explicitly WHY it is
    empty or partial (``status``: ``"empty"`` | ``"partial"`` | ``"ok"``
    plus human-readable ``reasons``): a zero-span trace dir, commits with
    no announced worker contexts, workers with window spans but no commit
    records, or a live collector whose series are too short for rates all
    name themselves instead of relying on join luck.

    Returns a JSON-safe dict: ``workers`` (per-worker stats),
    ``stragglers`` (worker ids, slowest first), ``top_straggler``,
    ``commit_context_coverage``, ``reconnect_storms``, ``coverage``,
    optionally ``live``, and — when any span names a shard — ``shards``,
    ``shards_ranked`` and ``slowest_shard``."""
    spans = _span_records(events, trace_dir)

    def bucket(worker: Any) -> Dict[str, Any]:
        key = str(worker)
        if key not in workers:
            workers[key] = {"windows": 0, "window_ms_sum": 0.0,
                            "window_ms_max": 0.0, "commits": 0,
                            "staleness_sum": 0, "staleness_max": 0,
                            "reconnects": 0, "failovers": 0}
        return workers[key]

    def shard_bucket(shard: Any) -> Dict[str, Any]:
        key = str(shard)
        if key not in shards:
            shards[key] = {"commits": 0, "staleness_sum": 0,
                           "commit_ms_sum": 0.0}
        return shards[key]

    workers: Dict[str, Dict[str, Any]] = {}
    shards: Dict[str, Dict[str, Any]] = {}
    # multi-job hub (ISSUE 19): per-job commit attribution — the span
    # "job" attr is the trace job id for default-namespace sessions and
    # the admitted job namespace for job-scoped ones
    jobs: Dict[str, Dict[str, Any]] = {}
    window_spans = 0
    commits_total = 0
    commits_with_ctx = 0
    # row-sparse embedding traffic (ISSUE 9): rows moved, summed over
    # every shard's spans — per-shard row ranges are disjoint, so the sum
    # IS the logical row count (no shard-0 dedup needed)
    sparse_rows_pulled = 0
    sparse_rows_committed = 0
    failover_ms: List[float] = []
    promotions: List[Dict[str, Any]] = []
    stripes_lost: List[Dict[str, Any]] = []
    for s in spans:
        attrs = s.get("attrs") or {}
        name = s.get("name")
        if name == "ps.handle_pull" and "sparse_rows" in attrs:
            sparse_rows_pulled += int(attrs.get("sparse_rows") or 0)
        elif name == "ps.handle_commit" and "sparse_rows" in attrs:
            sparse_rows_committed += int(attrs.get("sparse_rows") or 0)
        if name == "async.window" and "worker" in attrs:
            window_spans += 1
            b = bucket(attrs["worker"])
            ms = s.get("dur_us", 0) / 1000.0
            b["windows"] += 1
            b["window_ms_sum"] += ms
            b["window_ms_max"] = max(b["window_ms_max"], ms)
        elif name == "ps.handle_commit":
            stale = int(attrs.get("staleness", 0) or 0)
            shard = attrs.get("shard")
            if shard is not None:
                sb = shard_bucket(shard)
                sb["commits"] += 1
                sb["staleness_sum"] += stale
                sb["commit_ms_sum"] += s.get("dur_us", 0) / 1000.0
            if shard is not None and int(shard) != 0:
                # per-shard copies of one logical commit: counted in the
                # shards table above, skipped here so worker totals and
                # coverage stay logical-commit-denominated
                continue
            commits_total += 1
            job = attrs.get("job")
            if job is not None:
                jb = jobs.setdefault(str(job), {
                    "commits": 0, "staleness_sum": 0, "commit_ms_sum": 0.0})
                jb["commits"] += 1
                jb["staleness_sum"] += stale
                jb["commit_ms_sum"] += s.get("dur_us", 0) / 1000.0
            worker = attrs.get("worker")
            if worker is None or int(worker) < 0:
                continue
            commits_with_ctx += 1
            b = bucket(worker)
            b["commits"] += 1
            b["staleness_sum"] += stale
            b["staleness_max"] = max(b["staleness_max"], stale)
        elif name == "ps.reconnect" and "worker" in attrs:
            bucket(attrs["worker"])["reconnects"] += 1
        elif name == "ps.failover":
            failover_ms.append(s.get("dur_us", 0) / 1000.0)
            if "worker" in attrs:
                bucket(attrs["worker"])["failovers"] += 1
        elif name == "ps.promote":
            promotions.append({"clock": attrs.get("clock"),
                               "reason": attrs.get("reason"),
                               "shard": attrs.get("shard")})
        elif name == "ps.stripe_lost":
            stripes_lost.append({"shard": attrs.get("shard"),
                                 "address": attrs.get("address"),
                                 "worker": attrs.get("worker")})

    for b in workers.values():
        b["mean_window_ms"] = round(b["window_ms_sum"] / b["windows"], 3) \
            if b["windows"] else None
        b["mean_staleness"] = round(b["staleness_sum"] / b["commits"], 3) \
            if b["commits"] else None
        b["window_ms_sum"] = round(b["window_ms_sum"], 3)
        b["window_ms_max"] = round(b["window_ms_max"], 3)

    for sb in shards.values():
        sb["mean_staleness"] = (round(sb["staleness_sum"] / sb["commits"], 3)
                                if sb["commits"] else None)
        sb["mean_commit_ms"] = (round(sb["commit_ms_sum"] / sb["commits"], 4)
                                if sb["commits"] else None)
        sb["commit_ms_sum"] = round(sb["commit_ms_sum"], 3)

    ranked = sorted((w for w, b in workers.items()
                     if b["mean_window_ms"] is not None),
                    key=lambda w: workers[w]["mean_window_ms"], reverse=True)
    storms = sorted(w for w, b in workers.items()
                    if b["reconnects"] >= storm_threshold)
    shards_ranked = sorted(
        (k for k, sb in shards.items() if sb["mean_commit_ms"] is not None),
        key=lambda k: shards[k]["mean_commit_ms"], reverse=True)
    report = {
        "workers": workers,
        "stragglers": ranked,
        "top_straggler": ranked[0] if ranked else None,
        "total_commits": commits_total,
        "commit_context_coverage": (round(commits_with_ctx / commits_total, 4)
                                    if commits_total else None),
        "reconnect_storms": storms,
        "failovers_total": len(failover_ms),
        "failover_ms_mean": (round(sum(failover_ms) / len(failover_ms), 3)
                             if failover_ms else None),
        "failover_ms_max": (round(max(failover_ms), 3)
                            if failover_ms else None),
        "promotions": promotions,
        "stripes_lost": stripes_lost,
    }
    if sparse_rows_pulled or sparse_rows_committed:
        report["sparse"] = {"rows_pulled": sparse_rows_pulled,
                            "rows_committed": sparse_rows_committed}
    if shards:
        report["shards"] = shards
        report["shards_ranked"] = shards_ranked
        report["slowest_shard"] = shards_ranked[0] if shards_ranked else None
    if len(jobs) >= 2:
        # per-job fairness (ISSUE 19), only when the hub actually served
        # multiple jobs — single-job reports keep their exact prior shape.
        # share = fraction of attributed hub commits: an admission-
        # controlled hub should hold shares near each job's worker share,
        # so a job starving the others is nameable from the report alone
        attributed = sum(jb["commits"] for jb in jobs.values())
        for jb in jobs.values():
            jb["mean_staleness"] = (round(jb["staleness_sum"]
                                          / jb["commits"], 3)
                                    if jb["commits"] else None)
            jb["mean_commit_ms"] = (round(jb["commit_ms_sum"]
                                          / jb["commits"], 4)
                                    if jb["commits"] else None)
            jb["commit_ms_sum"] = round(jb["commit_ms_sum"], 3)
            jb["share"] = (round(jb["commits"] / attributed, 4)
                           if attributed else None)
        shares = sorted(jobs, key=lambda j: jobs[j]["commits"],
                        reverse=True)
        report["jobs"] = {
            "per_job": jobs,
            "ranked": shares,
            "dominant": shares[0] if shares else None,
            "max_share": (jobs[shares[0]]["share"] if shares else None),
            "min_share": (jobs[shares[-1]]["share"] if shares else None),
        }
    live_snap = None
    if live is not None:
        try:
            live_snap = live.snapshot()
        except Exception:
            live_snap = None  # a half-built collector degrades to span-only
        if live_snap is not None:
            report["live"] = live_snap
            adaptive = _adaptive_block(live_snap)
            if adaptive is not None:
                report["adaptive"] = adaptive
            hot = _hot_tier_block(live_snap)
            if hot is not None:
                report.setdefault("sparse", {})["hot_tier"] = hot
            transport = _transport_block(live_snap)
            if transport is not None:
                report["transport"] = transport
    report["coverage"] = _report_coverage(
        len(spans), window_spans, commits_total, commits_with_ctx,
        workers, live_snap)
    return report


def _adaptive_block(live_snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """ISSUE 10: the adaptive hub's live state pulled out of the
    collector snapshot into one block — per-worker APPLIED commit scale
    (the rate controller's multiplicative factor, 1.0 = unscaled) and
    the hub pseudo-workers' merge-queue batch depth.  ``None`` when the
    run carries no adaptive series at all (``adaptive=False``), so
    non-adaptive reports stay byte-identical."""
    workers = live_snap.get("workers") or {}
    scales: Dict[str, Any] = {}
    merge: Dict[str, Any] = {}
    for w, entry in workers.items():
        metrics = entry.get("metrics") or {}
        s = metrics.get("adaptive_scale")
        if s and s.get("n"):
            scales[w] = {"last": s.get("last"), "mean": s.get("mean")}
        q = metrics.get("merge_queue_depth")
        if q and q.get("n"):
            merge[w] = {"last": q.get("last"), "mean": q.get("mean"),
                        "p95": q.get("p95")}
    if not scales and not merge:
        return None
    return {"active": True, "worker_scales": scales, "merge_queue": merge}


def _hot_tier_block(live_snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """ISSUE 15: the hyperscale embedding tier's live state — per-worker
    client cache HIT RATE (hits / (hits + misses), from the cumulative
    series each hot-tier worker reports) and the hub pseudo-workers'
    cumulative sparse replication bytes.  ``None`` when the run carries
    no hot-tier series at all, so pre-ISSUE-15 reports stay
    byte-identical."""
    workers = live_snap.get("workers") or {}
    rates: Dict[str, Any] = {}
    repl_bytes = 0.0
    seen = False
    for w, entry in workers.items():
        metrics = entry.get("metrics") or {}
        h = metrics.get("sparse_cache_hits_total")
        m = metrics.get("sparse_cache_misses_total")
        if (h and h.get("n")) or (m and m.get("n")):
            hits = (h or {}).get("last") or 0.0
            misses = (m or {}).get("last") or 0.0
            total = hits + misses
            rates[w] = {"hits": hits, "misses": misses,
                        "hit_rate": (round(hits / total, 4) if total
                                     else None)}
            seen = True
        r = metrics.get("repl_sparse_bytes_total")
        if r and r.get("n"):
            repl_bytes += r.get("last") or 0.0
            seen = True
    if not seen:
        return None
    return {"cache": rates, "repl_sparse_bytes_total": round(repl_bytes)}


def _transport_block(live_snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """ISSUE 18: which transport each worker's PS client is riding —
    ``"shm"`` (shared-memory frame rings), ``"tcp"``, ``"inproc"``, or
    ``"mixed"`` (a sharded client whose shards negotiated differently)
    — plus a fleet-level tally, from the ``transport`` meta the health
    reports carry.  ``None`` when no worker reports one, so pre-ISSUE-18
    reports stay byte-identical."""
    workers = live_snap.get("workers") or {}
    by_worker: Dict[str, str] = {}
    for w, entry in workers.items():
        t = (entry.get("meta") or {}).get("transport")
        if t is not None:
            by_worker[w] = str(t)
    if not by_worker:
        return None
    counts: Dict[str, int] = {}
    for t in by_worker.values():
        counts[t] = counts.get(t, 0) + 1
    return {"workers": by_worker, "counts": counts}


def _report_coverage(n_spans: int, window_spans: int, commits_total: int,
                     commits_with_ctx: int,
                     workers: Dict[str, Dict[str, Any]],
                     live_snap: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The explicit why-is-this-empty/partial verdict every
    :func:`fleet_report` carries (ISSUE 8 satellite): each way the join
    can silently come up short names itself as a reason instead of the
    caller inferring it from missing keys."""
    reasons: List[str] = []
    if n_spans == 0:
        reasons.append("no spans: telemetry disabled, empty trace dir, or "
                       "nothing recorded yet")
    else:
        if window_spans == 0:
            reasons.append("no async.window spans: worker window timings "
                           "missing, straggler ranking is empty")
        if commits_total == 0:
            reasons.append("no ps.handle_commit spans: hub commit records "
                           "missing, staleness attribution is empty")
        elif commits_with_ctx == 0:
            reasons.append("commits carry no worker context: clients never "
                           "announced trace contexts (action T) — a join "
                           "miss, not an absence of commits")
        orphans = sorted(w for w, b in workers.items()
                         if b["windows"] and not b["commits"])
        if commits_with_ctx and orphans:
            reasons.append(f"worker(s) {orphans} have window spans but no "
                           f"attributed commits: their exchanges never "
                           f"reached this hub's records")
    live_workers = insufficient = None
    if live_snap is not None:
        live = live_snap.get("workers") or {}
        live_workers = len(live)
        insufficient = sorted(
            w for w, e in live.items()
            if all((m or {}).get("n", 0) < 2
                   for m in (e.get("metrics") or {}).values()))
        if not live:
            # health reporting is opt-in: its absence must not mark a
            # COMPLETE span join "partial" forever (the punchcard always
            # passes the collector).  Only when there are no spans either
            # does the empty collector explain anything — say so then
            if n_spans == 0:
                reasons.append("live collector holds no workers: no health "
                               "report ever arrived (health_interval_s "
                               "unset, or the run has not started)")
        elif insufficient:
            reasons.append(f"live series for worker(s) {insufficient} hold "
                           f"< 2 samples: rates and baselines not yet "
                           f"computable")
    empty = n_spans == 0 and not (live_snap and live_snap.get("workers"))
    out: Dict[str, Any] = {
        "status": "empty" if empty else ("partial" if reasons else "ok"),
        "spans": n_spans,
        "window_spans": window_spans,
        "commits": commits_total,
        "commits_with_context": commits_with_ctx,
        "reasons": reasons,
    }
    if live_snap is not None:
        out["live_workers"] = live_workers
        out["live_insufficient"] = insufficient
    return out
