"""Live fleet health plane: streaming collector, online anomaly
detection, and the ``distkeras-top`` console (ISSUE 8).

PR 1/PR 5 telemetry is point-in-time (registry ``snapshot()``) or
post-hoc (``merge_traces``/``fleet_report`` after the run): nobody can
watch staleness climb or a reconnect storm build WHILE a fleet trains.
The paper lineage needs exactly that live view — elastic-PS work
(arXiv:2204.03211) treats membership churn and per-worker health as
online signals of the service, and the staleness analysis of
arXiv:1611.04581 is only actionable as a moving distribution.  This
module is the receiving half of that plane:

- :class:`HealthCollector` — folds compact per-worker metric reports
  (pushed over the opt-in PS wire action ``M``, or ingested directly by
  co-located workers) into per-worker sliding-window
  :class:`~.metrics.TimeSeries`, keyed by the PR-5 ``TraceContext``
  worker identity and tagged with PR-6/7 shard labels.  Metric names
  ending ``_total``/``_sum`` are cumulative (``rate()`` =
  value-delta/dt); everything else is a point sample (rolling
  mean/p50/p95).
- :class:`HealthMonitor` — rolling detectors over the collected series:
  straggler (recent per-worker window wall vs fleet median), staleness
  spike vs rolling baseline, reconnect/failover storm, replication-lag
  growth, throughput regression vs the run-start EWMA.  Each firing is a
  structured :class:`HealthEvent` (kind, severity, worker, shard,
  evidence) kept in a bounded ring, recorded into the span ring as a
  ``health.event`` span (so the PR-5 trace/flush/merge pipeline carries
  it), and optionally appended to a JSONL sink.
- ``distkeras-top`` (:func:`main`) — a curses-free live console: polls a
  punchcard daemon's ``telemetry`` action with ``health=True`` and
  redraws a plain per-worker table (:func:`render_top`).

One process-default collector/monitor pair (:func:`collector` /
:func:`monitor`) is what the PS hubs fold wire reports into and what the
punchcard ``fetch_telemetry(..., health=True)`` pull reads — so the live
view works mid-job with zero plumbing.  Dependency-free at import
(stdlib + the :mod:`.metrics` sibling): the punchcard daemon and bare
tooling can import this without jax or numpy.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from distkeras_tpu.observability.metrics import TimeSeries

__all__ = [
    "HealthCollector", "HealthEvent", "HealthMonitor",
    "collector", "active_collector", "monitor", "reset_default",
    "health_snapshot", "render_top", "main",
]

DEFAULT_WINDOW_S = 120.0
DEFAULT_MAX_SAMPLES = 512


def _is_cumulative(name: str) -> bool:
    """Naming convention shared with the registry: ``*_total``/``*_sum``
    are running totals, everything else is a point sample."""
    return name.endswith("_total") or name.endswith("_sum")


class HealthCollector:
    """Per-worker sliding-window series store.

    ``ingest`` takes one wire report — ``{"job": ..., "worker": ...,
    "seq": n, "t_wall": ..., "metrics": {name: value, ...}}`` — and folds
    each metric into that worker's :class:`TimeSeries` (created on first
    sight).  ``observe`` is the direct single-sample form the hub uses to
    fold ITS OWN per-commit signals (staleness, replication lag) into the
    same per-worker view.  Thread-safe: hub handler threads ingest
    concurrently with punchcard snapshot reads."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 max_samples: int = DEFAULT_MAX_SAMPLES):
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        # worker key -> {"meta": {...}, "series": {metric: TimeSeries}}
        self._workers: Dict[str, Dict[str, Any]] = {}

    def _entry(self, worker: str) -> Dict[str, Any]:
        key = str(worker)
        entry = self._workers.get(key)
        if entry is None:
            entry = {"meta": {"first_seen_mono": time.monotonic(),
                              "reports": 0},
                     "series": {}}
            self._workers[key] = entry
        return entry

    def _series_for(self, entry: Dict[str, Any], metric: str) -> TimeSeries:
        series = entry["series"].get(metric)
        if series is None:
            series = TimeSeries(
                window_s=self.window_s, max_samples=self.max_samples,
                kind="cumulative" if _is_cumulative(metric) else "sample")
            entry["series"][metric] = series
        return series

    def observe(self, worker: str, metric: str, value: float,
                shard: Optional[int] = None, ts: Optional[float] = None) -> None:
        """Fold one sample for one worker (hub-side signals: per-commit
        staleness, replication lag)."""
        with self._lock:
            entry = self._entry(worker)
            meta = entry["meta"]
            meta["last_seen_mono"] = time.monotonic()
            if shard is not None:
                meta["shard"] = int(shard)
            series = self._series_for(entry, metric)
        series.append(float(value), ts=ts)

    def ingest(self, report: Dict[str, Any],
               shard: Optional[int] = None) -> None:
        """Fold one wire report.  Malformed reports are dropped silently —
        health collection must never take down the connection carrying
        it (mirrors the hub's malformed-``T`` rule)."""
        try:
            worker = str(report["worker"])
            metrics = report.get("metrics") or {}
            items = [(str(k), float(v)) for k, v in metrics.items()
                     if v is not None]
        except (KeyError, TypeError, ValueError, AttributeError):
            return
        with self._lock:
            entry = self._entry(worker)
            meta = entry["meta"]
            meta["last_seen_mono"] = time.monotonic()
            meta["reports"] += 1
            if shard is not None:
                meta["shard"] = int(shard)
            if report.get("job") is not None:
                meta["job"] = str(report["job"])
            if report.get("seq") is not None:
                try:
                    meta["seq"] = int(report["seq"])
                except (TypeError, ValueError):
                    pass
            if report.get("t_wall") is not None:
                try:
                    meta["last_wall"] = float(report["t_wall"])
                except (TypeError, ValueError):
                    pass
            # which transport the worker's PS client is riding ("tcp",
            # "shm", "inproc", "mixed") — surfaced as distkeras-top's
            # TRANS column and fleet_report's transport block (ISSUE 18)
            if report.get("transport") is not None:
                meta["transport"] = str(report["transport"])
            series = [(self._series_for(entry, name), value)
                      for name, value in items]
        for s, value in series:
            s.append(value)

    # -- reads -----------------------------------------------------------------
    def workers(self) -> List[str]:
        with self._lock:
            return sorted(self._workers)

    def series(self, worker: str, metric: str) -> Optional[TimeSeries]:
        with self._lock:
            entry = self._workers.get(str(worker))
            if entry is None:
                return None
            return entry["series"].get(metric)

    def meta(self, worker: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._workers.get(str(worker))
            return dict(entry["meta"]) if entry is not None else None

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe live view: per worker the meta (job, shard, seconds
        since last report) plus every series' reduced summary."""
        now = time.monotonic()
        with self._lock:
            items = [(w, dict(e["meta"]), dict(e["series"]))
                     for w, e in self._workers.items()]
        workers: Dict[str, Any] = {}
        for w, meta, series in items:
            last = meta.pop("last_seen_mono", None)
            meta.pop("first_seen_mono", None)
            meta["age_s"] = round(now - last, 3) if last is not None else None
            workers[w] = {
                "meta": meta,
                "metrics": {name: s.summary(now) for name, s in series.items()},
            }
        return {"ts_wall": time.time(), "ts_monotonic": now,
                "n_workers": len(workers), "workers": workers}

    def clear(self) -> None:
        with self._lock:
            self._workers.clear()


@dataclasses.dataclass
class HealthEvent:
    """One detector firing: what went wrong, on whom, with the evidence
    that triggered it — the structured record the span ring, the JSONL
    sink and ``distkeras-top`` all consume."""

    kind: str                    # straggler | staleness_spike | ...
    severity: str                # "warning" | "critical"
    worker: Optional[str] = None
    shard: Optional[int] = None
    ts_wall: float = 0.0
    evidence: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "severity": self.severity,
                "worker": self.worker, "shard": self.shard,
                "ts_wall": self.ts_wall, "evidence": dict(self.evidence)}


class HealthMonitor:
    """Online detectors over a :class:`HealthCollector`.

    ``check()`` runs every detector and returns the NEW events (cooldown
    suppresses a repeat of the same ``(kind, worker)`` within
    ``cooldown_s``); ``maybe_check()`` is the rate-limited form the hub
    calls from its ingest path, so detection runs continuously without a
    dedicated thread.  ``emit()`` records an externally-detected event
    (e.g. a hub promotion, a client failover) through the same pipeline.

    Every event lands in a bounded ring (``events()``), in the process
    span ring as a ``health.event`` span when tracing is enabled (the
    PR-5 flush/merge/report pipeline then carries it), and — when
    ``jsonl_path`` is set — as one appended JSON line.

    Detector definitions and default thresholds (see ARCHITECTURE.md
    "Fleet health plane"):

    - **straggler**: a worker's rolling mean ``window_wall_ms`` exceeds
      ``straggler_factor``x the fleet median, with at least
      ``min_fleet`` reporting workers and ``min_samples`` samples.
    - **staleness_spike**: a worker's latest staleness exceeds
      ``staleness_factor``x its rolling median baseline AND the absolute
      floor ``staleness_min`` (small-number noise must not page anyone).
    - **staleness_drift** (ISSUE 10): a worker's ROLLING MEAN staleness
      exceeds ``drift_factor``x the fleet median mean (same
      ``min_fleet``/``min_samples``/``staleness_min`` gates).  The spike
      detector compares a worker to its OWN baseline, so a worker that
      is ALWAYS behind never spikes — this fleet-relative form is the
      signal the adaptive hub's DynSGD-style rate scaling keys off.
    - **reconnect_storm** / **failover_storm**: ``reconnects_total`` /
      ``failovers_total`` grew by >= ``storm_threshold`` within the
      window.
    - **replication_lag**: the newest-half mean of ``replication_lag``
      exceeds ``lag_growth_factor``x the oldest-half mean and the latest
      value is >= ``lag_min`` — lag that is both large and GROWING.
    - **throughput_regression**: fleet windows/s (summed per-worker
      ``windows_total`` rates) fell below ``(1 - throughput_drop)``x the
      run-start baseline (the EWMA frozen after ``baseline_checks``
      checks with data)."""

    def __init__(self, collector: HealthCollector,
                 capacity: int = 256,
                 cooldown_s: float = 10.0,
                 straggler_factor: float = 2.0,
                 min_fleet: int = 3,
                 min_samples: int = 3,
                 staleness_factor: float = 3.0,
                 staleness_min: float = 4.0,
                 drift_factor: float = 2.0,
                 storm_threshold: int = 3,
                 lag_growth_factor: float = 2.0,
                 lag_min: float = 8.0,
                 throughput_drop: float = 0.5,
                 baseline_checks: int = 3,
                 check_interval_s: float = 2.0,
                 jsonl_path: Optional[str] = None):
        self.collector = collector
        self.cooldown_s = float(cooldown_s)
        self.straggler_factor = float(straggler_factor)
        self.min_fleet = int(min_fleet)
        self.min_samples = int(min_samples)
        self.staleness_factor = float(staleness_factor)
        self.staleness_min = float(staleness_min)
        self.drift_factor = float(drift_factor)
        self.storm_threshold = int(storm_threshold)
        self.lag_growth_factor = float(lag_growth_factor)
        self.lag_min = float(lag_min)
        self.throughput_drop = float(throughput_drop)
        self.baseline_checks = int(baseline_checks)
        self.check_interval_s = float(check_interval_s)
        self.jsonl_path = jsonl_path
        self._lock = threading.Lock()
        self._events: "deque[HealthEvent]" = deque(maxlen=int(capacity))
        self._subs: List[Any] = []
        self._last_fired: Dict[Any, float] = {}
        self._last_check = 0.0
        # run-start throughput baseline: EWMA over the first
        # baseline_checks checks that saw data, then frozen
        self._thr_ewma: Optional[float] = None
        self._thr_seen = 0
        self._thr_baseline: Optional[float] = None

    # -- event pipeline --------------------------------------------------------
    def emit(self, kind: str, severity: str = "warning",
             worker: Optional[str] = None, shard: Optional[int] = None,
             dedup: Optional[str] = None,
             **evidence: Any) -> Optional[HealthEvent]:
        """Record one event through the full pipeline (ring + span ring +
        JSONL), subject to the same per-``(kind, worker)`` cooldown as
        detector firings.  ``dedup`` extends the cooldown key for events
        with no worker identity (an untraced client's failover, a hub
        promotion): distinct sources each record, while the SAME source
        re-firing within the cooldown is still suppressed — without it,
        every worker-less event of one kind in a process would collapse
        to the first.  Returns the event, or None when suppressed."""
        now = time.monotonic()
        key = (kind, worker, dedup)
        with self._lock:
            last = self._last_fired.get(key)
            if last is not None and now - last < self.cooldown_s:
                return None
            if len(self._last_fired) >= 1024:
                # per-client dedup keys churn with the fleet (each
                # short-lived PSClient is a new key): drop entries past
                # the cooldown — they can never suppress anything again —
                # so a long-lived hub's map stays bounded
                cutoff = now - self.cooldown_s
                self._last_fired = {k: t for k, t in
                                    self._last_fired.items() if t >= cutoff}
            self._last_fired[key] = now
        event = HealthEvent(kind=kind, severity=severity,
                            worker=None if worker is None else str(worker),
                            shard=None if shard is None else int(shard),
                            ts_wall=time.time(), evidence=dict(evidence))
        self._record(event)
        return event

    def subscribe(self, callback: Any) -> Any:
        """Register ``callback(event)`` to run on EVERY event recorded
        through this monitor — detector firings and :meth:`emit` alike.
        This is the push hook reactive components attach to instead of
        polling :meth:`events` (ISSUE 10: the adaptive hub's per-worker
        rate controller and storm backpressure).  Callbacks run on the
        thread that recorded the event, outside the monitor lock;
        exceptions are swallowed — a broken subscriber must never take
        down detection or the path that emitted.  Returns ``callback``
        as the :meth:`unsubscribe` handle.  Subscriptions survive
        :meth:`clear` (a run-boundary reset must not silently unhook a
        live hub)."""
        with self._lock:
            self._subs.append(callback)
        return callback

    def unsubscribe(self, callback: Any) -> None:
        with self._lock:
            try:
                self._subs.remove(callback)
            except ValueError:
                pass

    def _record(self, event: HealthEvent) -> None:
        with self._lock:
            self._events.append(event)
            subs = list(self._subs)
        for cb in subs:
            try:
                cb(event)
            except Exception:
                pass  # a broken subscriber must not break the pipeline
        # into the span ring: the PR-5 trace pipeline (flush, merge,
        # fleet_report) carries health events like any other span.  Lazy
        # import keeps this module import-light for the punchcard daemon
        from distkeras_tpu import observability as _obs

        if _obs.TRACER.enabled:
            t = time.perf_counter_ns()
            attrs = {"kind": event.kind, "severity": event.severity}
            if event.worker is not None:
                attrs["worker"] = event.worker
            if event.shard is not None:
                attrs["shard"] = event.shard
            for k, v in event.evidence.items():
                attrs[f"ev_{k}"] = v
            _obs.TRACER.record_span("health.event", t, t, **attrs)
        if self.jsonl_path:
            try:
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(event.to_dict()) + "\n")
            except OSError:
                pass  # a full disk must not take down the hub ingesting

    def events(self) -> List[Dict[str, Any]]:
        """All ringed events, oldest first, JSON-safe."""
        with self._lock:
            return [e.to_dict() for e in self._events]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._last_fired.clear()
            self._last_check = 0.0
            self._thr_ewma = None
            self._thr_seen = 0
            self._thr_baseline = None

    # -- detection -------------------------------------------------------------
    def maybe_check(self, now: Optional[float] = None) -> List[HealthEvent]:
        """Rate-limited :meth:`check` (at most once per
        ``check_interval_s``) — the hub ingest path's hook, cheap enough
        to call per report."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if now - self._last_check < self.check_interval_s:
                return []
            self._last_check = now
        return self.check(now)

    def check(self, now: Optional[float] = None) -> List[HealthEvent]:
        now = time.monotonic() if now is None else float(now)
        fired: List[HealthEvent] = []
        for detect in (self._detect_stragglers, self._detect_staleness,
                       self._detect_staleness_drift,
                       self._detect_storms, self._detect_replication_lag,
                       self._detect_throughput):
            try:
                fired.extend(detect(now))
            except Exception:
                # one broken detector (half-written series mid-churn) must
                # not silence the others
                continue
        return fired

    def _worker_series(self, metric: str) -> Dict[str, TimeSeries]:
        out = {}
        for w in self.collector.workers():
            s = self.collector.series(w, metric)
            if s is not None:
                out[w] = s
        return out

    def _shard_of(self, worker: str) -> Optional[int]:
        meta = self.collector.meta(worker)
        return None if meta is None else meta.get("shard")

    def _detect_stragglers(self, now: float) -> List[HealthEvent]:
        means = {}
        for w, s in self._worker_series("window_wall_ms").items():
            if len(s.samples(now)) >= self.min_samples:
                means[w] = s.mean(now)
        if len(means) < self.min_fleet:
            return []
        ordered = sorted(means.values())
        median = ordered[len(ordered) // 2]
        if median <= 0:
            return []
        fired = []
        for w, m in means.items():
            if m > self.straggler_factor * median:
                ev = self.emit("straggler", "warning", worker=w,
                               shard=self._shard_of(w),
                               window_wall_ms=round(m, 3),
                               fleet_median_ms=round(median, 3),
                               factor=round(m / median, 2))
                if ev is not None:
                    fired.append(ev)
        return fired

    def _detect_staleness(self, now: float) -> List[HealthEvent]:
        fired = []
        for w, s in self._worker_series("staleness").items():
            pts = s.samples(now)
            if len(pts) < max(self.min_samples, 4):
                continue
            last = pts[-1][1]
            baseline = sorted(v for _, v in pts[:-1])[(len(pts) - 1) // 2]
            if (last >= self.staleness_min
                    and last > self.staleness_factor * max(baseline, 1.0)):
                ev = self.emit("staleness_spike", "warning", worker=w,
                               shard=self._shard_of(w),
                               staleness=last, baseline=baseline)
                if ev is not None:
                    fired.append(ev)
        return fired

    def _detect_staleness_drift(self, now: float) -> List[HealthEvent]:
        """Persistent-straggler staleness (ISSUE 10): fleet-relative
        rolling means, so a worker that is ALWAYS behind — invisible to
        the spike detector, whose baseline is the worker's own history —
        still names itself.  The event's evidence carries exactly what
        the adaptive hub's DynSGD-style rate rule needs."""
        means = {}
        for w, s in self._worker_series("staleness").items():
            if len(s.samples(now)) >= self.min_samples:
                means[w] = s.mean(now)
        if len(means) < self.min_fleet:
            return []
        ordered = sorted(means.values())
        median = ordered[len(ordered) // 2]
        fired = []
        for w, m in means.items():
            if m >= self.staleness_min \
                    and m > self.drift_factor * max(median, 1.0):
                ev = self.emit("staleness_drift", "warning", worker=w,
                               shard=self._shard_of(w),
                               staleness_mean=round(m, 2),
                               fleet_median=round(median, 2))
                if ev is not None:
                    fired.append(ev)
        return fired

    def _detect_storms(self, now: float) -> List[HealthEvent]:
        fired = []
        for metric, kind in (("reconnects_total", "reconnect_storm"),
                             ("failovers_total", "failover_storm")):
            for w, s in self._worker_series(metric).items():
                # reset-aware growth: a storm straddling an elastic worker
                # restart (counter back to zero mid-window) must still sum,
                # not read as negative growth and mask itself
                grew = s.increase(now)
                if grew is None:
                    continue
                if grew >= self.storm_threshold:
                    ev = self.emit(kind, "critical", worker=w,
                                   shard=self._shard_of(w),
                                   count=grew, window_s=s.window_s)
                    if ev is not None:
                        fired.append(ev)
        return fired

    def _detect_replication_lag(self, now: float) -> List[HealthEvent]:
        fired = []
        for w, s in self._worker_series("replication_lag").items():
            pts = s.samples(now)
            if len(pts) < max(self.min_samples, 4):
                continue
            half = len(pts) // 2
            old = sum(v for _, v in pts[:half]) / half
            new = sum(v for _, v in pts[half:]) / (len(pts) - half)
            if pts[-1][1] >= self.lag_min and new > self.lag_growth_factor * max(old, 1.0):
                ev = self.emit("replication_lag", "critical", worker=w,
                               shard=self._shard_of(w),
                               lag=pts[-1][1], recent_mean=round(new, 2),
                               earlier_mean=round(old, 2))
                if ev is not None:
                    fired.append(ev)
        return fired

    def _detect_throughput(self, now: float) -> List[HealthEvent]:
        rates = [s.rate(now)
                 for s in self._worker_series("windows_total").values()]
        rates = [r for r in rates if r is not None]
        if not rates:
            return []
        fleet_rate = sum(rates)
        with self._lock:
            if self._thr_baseline is None:
                # run-start EWMA: settle over the first baseline_checks
                # data-bearing checks, then freeze it as THE baseline
                self._thr_ewma = (fleet_rate if self._thr_ewma is None
                                  else 0.5 * fleet_rate + 0.5 * self._thr_ewma)
                self._thr_seen += 1
                if self._thr_seen >= self.baseline_checks:
                    self._thr_baseline = self._thr_ewma
                return []
            baseline = self._thr_baseline
        if baseline > 0 and fleet_rate < (1.0 - self.throughput_drop) * baseline:
            ev = self.emit("throughput_regression", "warning",
                           windows_per_s=round(fleet_rate, 3),
                           baseline_windows_per_s=round(baseline, 3))
            return [ev] if ev is not None else []
        return []


# -- process defaults ----------------------------------------------------------
# One collector/monitor pair per process (mirrors REGISTRY/TRACER): the
# hubs fold wire reports here, the punchcard telemetry action reads here.

_default_lock = threading.Lock()
_collector: Optional[HealthCollector] = None
_monitor: Optional[HealthMonitor] = None


def collector() -> HealthCollector:
    global _collector
    with _default_lock:
        if _collector is None:
            _collector = HealthCollector()
        return _collector


def active_collector() -> Optional[HealthCollector]:
    """The process-default collector IF one was ever created, else None —
    never creates.  Shard-N hubs poll this to bind their own pseudo-worker
    folds (replication lag) lazily: wire reports only ever land on shard 0,
    so shard N must join an ALREADY-active plane without activating one.
    Lock-free ON PURPOSE: callers peek per replicated commit; reading one
    global reference is atomic, and the benign race (missing a collector
    created this instant) only delays the bind by one call."""
    return _collector


def monitor() -> HealthMonitor:
    global _monitor
    # resolve the collector BEFORE taking the lock: collector() takes it
    # too and threading.Lock does not re-enter — taking it twice on the
    # first-ever monitor() call would deadlock the calling hub thread
    c = collector()
    with _default_lock:
        if _monitor is None:
            _monitor = HealthMonitor(c)
        return _monitor


def reset_default() -> None:
    """Drop the process-default collector's series and the monitor's
    events/baselines (tests; a fresh run's clean slate)."""
    with _default_lock:
        if _collector is not None:
            _collector.clear()
        if _monitor is not None:
            _monitor.clear()


def health_snapshot() -> Dict[str, Any]:
    """The live view the punchcard ``telemetry`` action returns under
    ``health`` (and ``distkeras-top`` renders): collector snapshot plus
    the monitor's ringed events."""
    mon = monitor()
    mon.maybe_check()
    return {"fleet": collector().snapshot(), "events": mon.events()}


# -- console (distkeras-top) ---------------------------------------------------

def _fmt(value: Any, nd: int = 1) -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.{nd}f}"
    return str(value)


def render_top(health: Dict[str, Any], width: int = 100) -> str:
    """One plain-text frame of the live fleet view: a per-worker table
    (windows/s, rolling window wall, staleness, reconnects, age) and the
    most recent events.  Pure function of the ``health_snapshot()`` shape
    so it unit-tests without a daemon."""
    fleet = health.get("fleet") or {}
    workers = fleet.get("workers") or {}
    events = health.get("events") or []
    # fleet size + job census (ISSUE 19): the title line says how many
    # workers are live and how many distinct jobs they report under, so
    # a multi-job hub's console names the tenancy at a glance
    job_census = {str((e.get("meta") or {}).get("job"))
                  for e in workers.values()
                  if (e.get("meta") or {}).get("job") is not None}
    lines = [
        f"distkeras-top — fleet {len(workers)} worker(s), "
        f"{len(job_census)} job(s), "
        f"{len(events)} event(s)  [{time.strftime('%H:%M:%S')}]",
        f"{'WORKER':>8} {'JOB':>10} {'SHARD':>5} {'TRANS':>6} {'WIN/S':>7} "
        f"{'WALL MS':>9} {'P95 MS':>9} {'STALE':>6} {'SCALE':>6} "
        f"{'RECON':>6} {'ROW/S':>8} {'HIT%':>5} {'RΔ/S':>8} {'MQ':>4} "
        f"{'AGE S':>6}",
    ]

    def sort_key(item):
        w = item[0]
        return (0, int(w)) if w.lstrip("-").isdigit() else (1, w)

    for w, entry in sorted(workers.items(), key=sort_key):
        meta = entry.get("meta") or {}
        m = entry.get("metrics") or {}
        wall = m.get("window_wall_ms") or {}
        windows = m.get("windows_total") or {}
        stale = m.get("staleness") or {}
        recon = m.get("reconnects_total") or {}
        # row-sparse embedding traffic (ISSUE 9): committed rows/s from
        # the worker's cumulative sparse_rows_total series; "-" for
        # workers (or whole fleets) that move dense leaves only
        sparse = m.get("sparse_rows_total") or {}
        # adaptive aggregation (ISSUE 10): the hub-applied per-worker
        # commit scale (workers) and the merge-queue batch depth (the
        # hub pseudo-worker rows); "-" when the hub is not adaptive
        scale = m.get("adaptive_scale") or {}
        mq = m.get("merge_queue_depth") or {}
        # hyperscale embedding tier (ISSUE 15): HIT% = the worker's
        # hot-tier client cache hit rate (cumulative hits/misses series);
        # RΔ/S = sparse replication bytes per second (the hub
        # pseudo-worker's cumulative repl_sparse_bytes_total series).
        # "-" for fleets that run dense, full-cache, or unreplicated
        hits = (m.get("sparse_cache_hits_total") or {}).get("last")
        misses = (m.get("sparse_cache_misses_total") or {}).get("last")
        hit_pct = None
        if hits is not None or misses is not None:
            total = (hits or 0.0) + (misses or 0.0)
            hit_pct = (100.0 * (hits or 0.0) / total) if total else None
        repl = m.get("repl_sparse_bytes_total") or {}
        # JOB (ISSUE 19): the job this worker reports under — the trace
        # job id, or the admitted job namespace on a multi-job hub;
        # truncated from the left so the distinguishing suffix survives
        job = meta.get("job")
        job_cell = ("-" if job is None
                    else str(job)[-10:])
        lines.append(
            f"{w:>8} {job_cell:>10} {_fmt(meta.get('shard')):>5} "
            # TRANS (ISSUE 18): the worker's PS transport — "shm" rows
            # are riding shared-memory rings, "tcp" plain sockets,
            # "inproc" the direct in-process path, "mixed" a sharded
            # client whose shards negotiated differently
            f"{_fmt(meta.get('transport')):>6} "
            f"{_fmt(windows.get('rate'), 2):>7} "
            f"{_fmt(wall.get('mean')):>9} {_fmt(wall.get('p95')):>9} "
            f"{_fmt(stale.get('last'), 0):>6} "
            f"{_fmt(scale.get('last'), 2):>6} "
            f"{_fmt(recon.get('last'), 0):>6} "
            f"{_fmt(sparse.get('rate'), 0):>8} "
            f"{_fmt(hit_pct, 1):>5} "
            f"{_fmt(repl.get('rate'), 0):>8} "
            f"{_fmt(mq.get('last'), 0):>4} "
            f"{_fmt(meta.get('age_s')):>6}")
    if events:
        lines.append("recent events:")
        for e in events[-8:]:
            who = f" worker={e['worker']}" if e.get("worker") is not None else ""
            ev = " ".join(f"{k}={v}" for k, v in (e.get("evidence") or {}).items())
            lines.append(f"  [{e['severity']:>8}] {e['kind']}{who} {ev}"[:width])
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> None:
    """``distkeras-top``: live per-worker fleet health from a running
    punchcard daemon (curses-free; each tick clears and reprints)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="live dist-keras-tpu fleet health console")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True,
                        help="punchcard daemon port")
    parser.add_argument("--secret", required=True,
                        help="punchcard shared secret")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between redraws")
    parser.add_argument("--iterations", type=int, default=0,
                        help="redraw this many times then exit (0 = forever)")
    parser.add_argument("--no-clear", action="store_true",
                        help="append frames instead of clearing the screen")
    args = parser.parse_args(argv)

    # lazy: the console is stdlib + the punchcard client; importing at
    # main() keeps `import health` free of the runtime package
    from distkeras_tpu.runtime.job_deployment import fetch_telemetry

    i = 0
    try:
        while True:
            try:
                resp = fetch_telemetry(args.host, args.port, args.secret,
                                       health=True)
                frame = render_top(resp.get("health") or {})
                if not resp.get("enabled", True):
                    frame += "\n(telemetry disabled in the daemon — " \
                             "set DKT_TELEMETRY=1 or obs.enable())"
            except (OSError, ValueError) as e:
                frame = f"distkeras-top: daemon unreachable: {e}"
            if not args.no_clear:
                print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            i += 1
            if args.iterations and i >= args.iterations:
                return
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
