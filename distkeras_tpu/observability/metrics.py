"""Process-wide metrics registry: counters, gauges, log-bucket histograms.

The measurement substrate ISSUE #1 asked for: the paper lineage's core
quantities (staleness, commit rates, window wall-vs-device time — EASGD
arXiv:1412.6651, "How to scale distributed deep learning?"
arXiv:1611.04581) were computed all over the runtime and dropped on the
floor; this registry is where every layer now records them.

Design constraints (all load-bearing):

- **Dependency-free.**  stdlib only — the punchcard daemon and the data
  loaders must stay importable without jax, and the PS hub's handler
  threads must not pull a metrics client library onto the commit path.
- **Thread-safe.**  PS handler threads, async worker threads, the prefetch
  producer and the snapshot daemon all write concurrently; every
  instrument takes its own small lock.
- **Near-zero when disabled.**  Telemetry is OFF by default: every mutator
  is a single attribute check and early return, so instrumented hot paths
  (per-RPC, per-window, per-chunk) cost one branch.  The ≤2% bench
  overhead budget in ISSUE #1 is met by construction — nothing allocates,
  formats, or locks until ``enable()`` has run.

Naming convention (see ARCHITECTURE.md "Observability"): metric names are
``<layer>_<quantity>[_<unit>|_total]`` — e.g. ``ps_commits_total``,
``async_window_wall_seconds``, ``feed_queue_depth`` — with identity
dimensions (worker index, trainer class) as labels, never baked into the
name.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

# Fixed log-scale histogram bounds: 3 buckets per decade from 1e-6 to
# ~1e8 (microseconds-as-seconds through day-long waits; also spans byte
# counts when observed in MB).  FIXED — not configurable per histogram —
# so every exported histogram is mergeable with every other and the
# exposition format never needs per-metric schema.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** (exp10 + frac / 3.0), 10)
    for exp10 in range(-6, 9)
    for frac in range(3)
)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_name(name: str, labels: _LabelKey) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _prometheus_name(name: str) -> str:
    """Map a registry name onto the Prometheus metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots etc. become underscores)."""
    sanitized = "".join(c if (c.isascii() and (c.isalnum() or c in "_:"))
                        else "_" for c in name)
    if not sanitized or not (sanitized[0].isascii()
                             and (sanitized[0].isalpha()
                                  or sanitized[0] in "_:")):
        sanitized = "_" + sanitized
    return sanitized


class Counter:
    """Monotonic counter.  ``inc`` is a no-op while the owning registry is
    disabled."""

    __slots__ = ("name", "labels", "_registry", "_lock", "_value")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Last-written value (queue depths, staleness, rates)."""

    __slots__ = ("name", "labels", "_registry", "_lock", "_value")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed log-scale-bucket histogram (see ``DEFAULT_BUCKETS``).

    Stores per-bucket counts plus count/sum/min/max; ``observe`` is one
    bisect + one locked increment.  Bucket counts are NON-cumulative
    internally; snapshots/expositions render the Prometheus cumulative
    ``le`` form.
    """

    __slots__ = ("name", "labels", "_registry", "_lock", "_counts",
                 "_count", "_sum", "_min", "_max")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._counts = [0] * (len(DEFAULT_BUCKETS) + 1)  # +1 overflow
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, value: float) -> None:
        if not self._registry.enabled:
            return
        value = float(value)
        if value != value:
            # NaN: bisect against it is undefined ordering and it would
            # poison sum/mean forever — drop the observation (a NaN
            # latency is an upstream bug, not a data point)
            return
        # bisect_left: a value equal to a bound belongs to that bound's
        # bucket (Prometheus ``le`` is inclusive); anything past the last
        # bound (incl. +inf) lands in the explicit overflow bucket, which
        # renders as ``le="+Inf"``
        idx = bisect_left(DEFAULT_BUCKETS, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    def observe_n(self, value: float, n: int) -> None:
        """Record ``n`` identical observations with ONE lock acquisition —
        the bulk path for replaying an external histogram (the C++ hub's
        staleness counts) without an O(n) observe loop."""
        if not self._registry.enabled or n <= 0:
            return
        value = float(value)
        if value != value:
            return  # NaN: same contract as observe()
        idx = bisect_left(DEFAULT_BUCKETS, value)
        with self._lock:
            self._counts[idx] += n
            self._count += n
            self._sum += value * n
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def summary(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            out: Dict[str, object] = {
                "count": self._count,
                "sum": round(self._sum, 9),
                "min": self._min,
                "max": self._max,
                "mean": (self._sum / self._count) if self._count else None,
            }
        # sparse cumulative buckets: only boundaries with mass, so a
        # snapshot of many histograms stays a small JSON object
        cum = 0
        buckets: List[List[object]] = []
        for i, c in enumerate(counts):
            cum += c
            if c:
                le = DEFAULT_BUCKETS[i] if i < len(DEFAULT_BUCKETS) else "+Inf"
                buckets.append([le, cum])
        out["buckets"] = buckets
        return out

    def _zero(self) -> None:
        with self._lock:
            self._counts = [0] * (len(DEFAULT_BUCKETS) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create instrument store keyed by (name, labels).

    One process-wide default instance lives in
    ``distkeras_tpu.observability`` (module helpers ``counter``/``gauge``/
    ``histogram`` bind to it); tests and embedded uses can construct
    private always-enabled registries.
    """

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, _LabelKey], object] = {}
        self._kinds: Dict[str, str] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, str]):
        key = (name, _label_key(labels))
        inst = self._instruments.get(key)
        if inst is not None:
            if type(inst) is not _KINDS[kind]:
                raise TypeError(
                    f"metric {name!r} already registered as a "
                    f"{self._kinds[name]}, requested as a {kind}")
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                prev = self._kinds.get(name)
                if prev is not None and prev != kind:
                    raise TypeError(
                        f"metric {name!r} already registered as a {prev}, "
                        f"requested as a {kind}")
                self._kinds[name] = kind
                inst = _KINDS[kind](name, key[1], self)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get("histogram", name, labels)

    # -- introspection ---------------------------------------------------------
    def instruments(self) -> List[object]:
        with self._lock:
            return list(self._instruments.values())

    def value(self, name: str, **labels: str) -> Optional[float]:
        """Current value of a counter/gauge, None if never created (a
        convenience for tests and snapshot consumers — does NOT create)."""
        inst = self._instruments.get((name, _label_key(labels)))
        return None if inst is None else getattr(inst, "value", None)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """JSON-safe point-in-time view::

            {"counters":   {"ps_commits_total": 12.0, ...},
             "gauges":     {'ps_staleness{conn="0"}': 3.0, ...},
             "histograms": {"async_window_wall_seconds": {count, sum, min,
                            max, mean, buckets: [[le, cumcount], ...]}, ...}}
        """
        out: Dict[str, Dict[str, object]] = {
            "counters": {}, "gauges": {}, "histograms": {}}
        for inst in self.instruments():
            key = _render_name(inst.name, inst.labels)
            if isinstance(inst, Counter):
                out["counters"][key] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][key] = inst.value
            else:
                out["histograms"][key] = inst.summary()
        return out

    def kind_of(self, name: str) -> Optional[str]:
        """``"counter"``/``"gauge"``/``"histogram"`` for a registered
        metric name (exposition renderers need the TYPE line)."""
        return self._kinds.get(name)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4, rendered on demand —
        the pull-style sink (no server here; the punchcard daemon's
        ``telemetry`` action and any embedding HTTP handler just return
        this string).  The renderer lives in :mod:`.sinks` (label-value
        escaping and name sanitization are exposition-format concerns);
        snapshots and the punchcard JSON keep the raw registry spelling."""
        from distkeras_tpu.observability.sinks import render_prometheus

        return render_prometheus(self)

    def reset(self) -> None:
        """Zero every instrument IN PLACE (tests; a fresh run's clean
        slate).  Registrations are kept deliberately: hot paths are told to
        cache instrument objects, so dropping them here would orphan those
        references and silently lose all their subsequent writes."""
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            inst._zero()
