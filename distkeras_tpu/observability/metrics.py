"""Process-wide metrics registry: counters, gauges, log-bucket histograms.

The measurement substrate ISSUE #1 asked for: the paper lineage's core
quantities (staleness, commit rates, window wall-vs-device time — EASGD
arXiv:1412.6651, "How to scale distributed deep learning?"
arXiv:1611.04581) were computed all over the runtime and dropped on the
floor; this registry is where every layer now records them.

Design constraints (all load-bearing):

- **Dependency-free.**  stdlib only — the punchcard daemon and the data
  loaders must stay importable without jax, and the PS hub's handler
  threads must not pull a metrics client library onto the commit path.
- **Thread-safe.**  PS handler threads, async worker threads, the prefetch
  producer and the snapshot daemon all write concurrently; every
  instrument takes its own small lock.
- **Near-zero when disabled.**  Telemetry is OFF by default: every mutator
  is a single attribute check and early return, so instrumented hot paths
  (per-RPC, per-window, per-chunk) cost one branch.  The ≤2% bench
  overhead budget in ISSUE #1 is met by construction — nothing allocates,
  formats, or locks until ``enable()`` has run.

Naming convention (see ARCHITECTURE.md "Observability"): metric names are
``<layer>_<quantity>[_<unit>|_total]`` — e.g. ``ps_commits_total``,
``async_window_wall_seconds``, ``feed_queue_depth`` — with identity
dimensions (worker index, trainer class) as labels, never baked into the
name.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Dict, List, Optional, Tuple

# Fixed log-scale histogram bounds: 3 buckets per decade from 1e-6 to
# ~1e8 (microseconds-as-seconds through day-long waits; also spans byte
# counts when observed in MB).  FIXED — not configurable per histogram —
# so every exported histogram is mergeable with every other and the
# exposition format never needs per-metric schema.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** (exp10 + frac / 3.0), 10)
    for exp10 in range(-6, 9)
    for frac in range(3)
)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_name(name: str, labels: _LabelKey) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _prometheus_name(name: str) -> str:
    """Map a registry name onto the Prometheus metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots etc. become underscores)."""
    sanitized = "".join(c if (c.isascii() and (c.isalnum() or c in "_:"))
                        else "_" for c in name)
    if not sanitized or not (sanitized[0].isascii()
                             and (sanitized[0].isalpha()
                                  or sanitized[0] in "_:")):
        sanitized = "_" + sanitized
    return sanitized


class TimeSeries:
    """Bounded sliding window of ``(monotonic_ts, value)`` samples — the
    live complement to the lifetime instruments (ISSUE 8): a counter says
    "12 000 commits ever", the attached series says "38 commits/s over the
    last minute, and falling".

    Attached to an instrument by :meth:`MetricsRegistry.track` (opt-in PER
    NAME — an untracked instrument pays one ``is None`` check per
    mutation, nothing else).  The ring holds at most ``max_samples``
    samples and reducers only consider samples newer than ``window_s``
    (pruned lazily on append/read), so memory and read cost are bounded
    regardless of run length.

    ``kind`` fixes the rate semantics: ``"cumulative"`` (counters, and
    gauges whose value is a running total) reduces ``rate()`` as
    value-delta / time-delta across the window; ``"sample"`` (histogram
    observations, point-in-time gauges) reduces it as samples / second.
    All reducers return ``None`` when the window holds too few samples to
    answer — callers (detectors, ``distkeras-top``) treat None as
    "insufficient data", never as zero."""

    __slots__ = ("window_s", "max_samples", "kind", "_samples", "_lock")

    def __init__(self, window_s: float = 60.0, max_samples: int = 512,
                 kind: str = "sample"):
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        if max_samples <= 1:
            raise ValueError(f"max_samples must be > 1, got {max_samples}")
        if kind not in ("cumulative", "sample"):
            raise ValueError(f"kind must be 'cumulative' or 'sample', "
                             f"got {kind!r}")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self.kind = kind
        self._samples: "deque[Tuple[float, float]]" = deque(maxlen=self.max_samples)
        self._lock = threading.Lock()

    def append(self, value: float, ts: Optional[float] = None) -> None:
        ts = time.monotonic() if ts is None else float(ts)
        with self._lock:
            # lazy prune: drop the expired head so a long-idle series does
            # not hand reducers a window full of stale samples
            cutoff = ts - self.window_s
            samples = self._samples
            while samples and samples[0][0] < cutoff:
                samples.popleft()
            samples.append((ts, float(value)))

    def samples(self, now: Optional[float] = None) -> List[Tuple[float, float]]:
        """The samples inside the window, oldest first."""
        now = time.monotonic() if now is None else float(now)
        cutoff = now - self.window_s
        with self._lock:
            return [(t, v) for t, v in self._samples if t >= cutoff]

    def __len__(self) -> int:
        return len(self._samples)

    def last(self) -> Optional[float]:
        with self._lock:
            return self._samples[-1][1] if self._samples else None

    def increase(self, now: Optional[float] = None) -> Optional[float]:
        """Reset-aware growth of a cumulative series over the window
        (Prometheus ``increase()`` semantics): sums consecutive positive
        deltas; a NEGATIVE delta is a counter reset — an elastic worker
        restart re-entered at zero — counted as the post-reset value, so
        growth never goes negative and never subtracts the pre-restart
        total.  None below 2 samples, or for sample-kind series."""
        if self.kind != "cumulative":
            return None
        pts = self.samples(now)
        if len(pts) < 2:
            return None
        return self._grown(pts)

    @staticmethod
    def _grown(pts: List[Tuple[float, float]]) -> float:
        # the ONE reset-aware summation (increase() and rate() both use
        # it, over one snapshot each — growth and dt must come from the
        # SAME samples or a concurrent append inflates the rate)
        grown = 0.0
        for (_, prev), (_, cur) in zip(pts, pts[1:]):
            d = cur - prev
            grown += d if d >= 0 else max(cur, 0.0)
        return grown

    @staticmethod
    def _rate_of(pts: List[Tuple[float, float]], kind: str) -> Optional[float]:
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        if kind == "cumulative":
            return TimeSeries._grown(pts) / dt
        return (len(pts) - 1) / dt

    @staticmethod
    def _ewma_of(pts: List[Tuple[float, float]], alpha: float) -> float:
        acc = pts[0][1]
        for _, v in pts[1:]:
            acc = alpha * v + (1.0 - alpha) * acc
        return acc

    @staticmethod
    def _nearest_rank(values: List[float], q: float) -> float:
        idx = min(len(values) - 1,
                  max(0, int(round(q / 100.0 * (len(values) - 1)))))
        return values[idx]

    def rate(self, now: Optional[float] = None) -> Optional[float]:
        """Per-second rate over the window: reset-aware value growth / dt
        for cumulative series (see :meth:`increase` — a worker restart's
        counter reset must not produce a huge negative rate), samples/dt
        for sample series.  None below 2 samples (no interval to divide
        by)."""
        return self._rate_of(self.samples(now), self.kind)

    def mean(self, now: Optional[float] = None) -> Optional[float]:
        pts = self.samples(now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def ewma(self, alpha: float = 0.3, now: Optional[float] = None) -> Optional[float]:
        """Exponentially-weighted mean over the windowed samples (newest
        weighted heaviest)."""
        pts = self.samples(now)
        if not pts:
            return None
        return self._ewma_of(pts, alpha)

    def percentile(self, q: float, now: Optional[float] = None) -> Optional[float]:
        """Nearest-rank percentile (``q`` in [0, 100]) over the windowed
        samples.  Exact within the window — tighter than the lifetime
        histogram's log-bucket resolution, because the ring keeps raw
        values."""
        pts = self.samples(now)
        if not pts:
            return None
        return self._nearest_rank(sorted(v for _, v in pts), q)

    def summary(self, now: Optional[float] = None) -> Dict[str, object]:
        """JSON-safe reduced view — what ``tracked_snapshot`` and the
        health plane export per series.  One ``samples()`` snapshot and
        one sort feed every reducer: each flusher/console poll pays one
        lock/copy pass per series, not six."""
        now = time.monotonic() if now is None else float(now)
        pts = self.samples(now)
        n = len(pts)
        out: Dict[str, object] = {"n": n, "window_s": self.window_s,
                                  "kind": self.kind}
        if not n:
            return out
        out["last"] = pts[-1][1]
        out["rate"] = self._rate_of(pts, self.kind)
        out["mean"] = sum(v for _, v in pts) / n
        if self.kind == "sample":
            values = sorted(v for _, v in pts)
            out["p50"] = self._nearest_rank(values, 50)
            out["p95"] = self._nearest_rank(values, 95)
            out["ewma"] = self._ewma_of(pts, 0.3)
        return out

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


class Counter:
    """Monotonic counter.  ``inc`` is a no-op while the owning registry is
    disabled."""

    __slots__ = ("name", "labels", "_registry", "_lock", "_value", "series")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0
        self.series: Optional[TimeSeries] = None  # attached by track()

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            self._value += amount
            # append INSIDE the instrument lock: two concurrent incs
            # appending outside it can land out of order, and the
            # reset-aware increase()/rate() would read the negative
            # delta as a counter reset (nested series lock is fine —
            # nothing acquires them in the reverse order).  Local binding:
            # untrack() nulls self.series under the registry lock only, so
            # a double read here could AttributeError mid-mutation
            series = self.series
            if series is not None:
                series.append(self._value)

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0
        series = self.series
        if series is not None:
            series.clear()


class Gauge:
    """Last-written value (queue depths, staleness, rates)."""

    __slots__ = ("name", "labels", "_registry", "_lock", "_value", "series")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0
        self.series: Optional[TimeSeries] = None  # attached by track()

    def set(self, value: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value = float(value)
            # inside the lock: last() must reflect the last WRITE (the
            # same ordering rule as Counter.inc); local binding vs a
            # concurrent untrack(), same as Counter.inc
            series = self.series
            if series is not None:
                series.append(self._value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value += amount
            series = self.series
            if series is not None:
                series.append(self._value)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0
        series = self.series
        if series is not None:
            series.clear()


class Histogram:
    """Fixed log-scale-bucket histogram (see ``DEFAULT_BUCKETS``).

    Stores per-bucket counts plus count/sum/min/max; ``observe`` is one
    bisect + one locked increment.  Bucket counts are NON-cumulative
    internally; snapshots/expositions render the Prometheus cumulative
    ``le`` form.
    """

    __slots__ = ("name", "labels", "_registry", "_lock", "_counts",
                 "_count", "_sum", "_min", "_max", "series")

    def __init__(self, name: str, labels: _LabelKey, registry: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._lock = threading.Lock()
        self._counts = [0] * (len(DEFAULT_BUCKETS) + 1)  # +1 overflow
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self.series: Optional[TimeSeries] = None  # attached by track()

    def observe(self, value: float) -> None:
        if not self._registry.enabled:
            return
        value = float(value)
        if value != value:
            # NaN: bisect against it is undefined ordering and it would
            # poison sum/mean forever — drop the observation (a NaN
            # latency is an upstream bug, not a data point)
            return
        # bisect_left: a value equal to a bound belongs to that bound's
        # bucket (Prometheus ``le`` is inclusive); anything past the last
        # bound (incl. +inf) lands in the explicit overflow bucket, which
        # renders as ``le="+Inf"``
        idx = bisect_left(DEFAULT_BUCKETS, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value
        series = self.series
        if series is not None:
            # raw observation into the sliding window: rolling p50/p95 are
            # then exact over the window, not log-bucket-quantized
            series.append(value)

    def observe_n(self, value: float, n: int) -> None:
        """Record ``n`` identical observations with ONE lock acquisition —
        the bulk path for replaying an external histogram (the C++ hub's
        staleness counts) without an O(n) observe loop."""
        if not self._registry.enabled or n <= 0:
            return
        value = float(value)
        if value != value:
            return  # NaN: same contract as observe()
        idx = bisect_left(DEFAULT_BUCKETS, value)
        with self._lock:
            self._counts[idx] += n
            self._count += n
            self._sum += value * n
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value
        series = self.series
        if series is not None:
            # one window sample per bulk replay (not n): the series is a
            # live view, and n identical samples would only skew quantiles
            series.append(value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def summary(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            out: Dict[str, object] = {
                "count": self._count,
                "sum": round(self._sum, 9),
                "min": self._min,
                "max": self._max,
                "mean": (self._sum / self._count) if self._count else None,
            }
        # sparse cumulative buckets: only boundaries with mass, so a
        # snapshot of many histograms stays a small JSON object
        cum = 0
        buckets: List[List[object]] = []
        for i, c in enumerate(counts):
            cum += c
            if c:
                le = DEFAULT_BUCKETS[i] if i < len(DEFAULT_BUCKETS) else "+Inf"
                buckets.append([le, cum])
        out["buckets"] = buckets
        return out

    def _zero(self) -> None:
        with self._lock:
            self._counts = [0] * (len(DEFAULT_BUCKETS) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
        series = self.series
        if series is not None:
            series.clear()


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create instrument store keyed by (name, labels).

    One process-wide default instance lives in
    ``distkeras_tpu.observability`` (module helpers ``counter``/``gauge``/
    ``histogram`` bind to it); tests and embedded uses can construct
    private always-enabled registries.
    """

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, _LabelKey], object] = {}
        self._kinds: Dict[str, str] = {}
        # per-NAME time-series opt-in (ISSUE 8): name -> (window_s,
        # max_samples).  Every current and future instrument of a tracked
        # name (all label sets) carries an attached TimeSeries
        self._tracked: Dict[str, Tuple[float, int]] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, str]):
        key = (name, _label_key(labels))
        inst = self._instruments.get(key)
        if inst is not None:
            if type(inst) is not _KINDS[kind]:
                raise TypeError(
                    f"metric {name!r} already registered as a "
                    f"{self._kinds[name]}, requested as a {kind}")
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                prev = self._kinds.get(name)
                if prev is not None and prev != kind:
                    raise TypeError(
                        f"metric {name!r} already registered as a {prev}, "
                        f"requested as a {kind}")
                self._kinds[name] = kind
                inst = _KINDS[kind](name, key[1], self)
                tracked = self._tracked.get(name)
                if tracked is not None:
                    inst.series = self._make_series(kind, *tracked)
                self._instruments[key] = inst
            return inst

    @staticmethod
    def _make_series(kind: str, window_s: float, max_samples: int) -> TimeSeries:
        # counters are running totals (rate() = value-delta/dt); gauge
        # writes and histogram observations are point samples (rolling
        # mean/p50/p95/ewma)
        return TimeSeries(window_s=window_s, max_samples=max_samples,
                          kind="cumulative" if kind == "counter" else "sample")

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get("histogram", name, labels)

    # -- time series (ISSUE 8) -------------------------------------------------
    def track(self, name: str, window_s: float = 60.0,
              max_samples: int = 512) -> None:
        """Opt the metric ``name`` (every label set, current and future)
        into sliding-window time series: each subsequent mutation appends
        one ``(monotonic_ts, value)`` sample to the instrument's attached
        :class:`TimeSeries`.  Untracked instruments keep paying only an
        ``is None`` check per mutation; re-tracking an already-tracked
        name re-attaches fresh (empty) series with the new parameters."""
        with self._lock:
            self._tracked[name] = (float(window_s), int(max_samples))
            kind = self._kinds.get(name)
            for (iname, _), inst in self._instruments.items():
                if iname == name:
                    inst.series = self._make_series(kind, float(window_s),
                                                    int(max_samples))

    def untrack(self, name: str) -> None:
        """Detach ``name``'s series (samples are dropped; the lifetime
        instrument values are untouched)."""
        with self._lock:
            self._tracked.pop(name, None)
            for (iname, _), inst in self._instruments.items():
                if iname == name:
                    inst.series = None

    def tracked(self) -> List[str]:
        with self._lock:
            return sorted(self._tracked)

    def series(self, name: str, **labels: str) -> Optional[TimeSeries]:
        """The attached series of one instrument, or None when the name is
        untracked / the instrument never created (does NOT create)."""
        inst = self._instruments.get((name, _label_key(labels)))
        return None if inst is None else inst.series

    def tracked_snapshot(self) -> Dict[str, Dict[str, object]]:
        """JSON-safe reduced view of every tracked series:
        ``{rendered_name: {n, last, rate, mean, p50, p95, ewma, ...}}``."""
        now = time.monotonic()
        out: Dict[str, Dict[str, object]] = {}
        for inst in self.instruments():
            series = getattr(inst, "series", None)
            if series is not None:
                out[_render_name(inst.name, inst.labels)] = series.summary(now)
        return out

    # -- introspection ---------------------------------------------------------
    def instruments(self) -> List[object]:
        with self._lock:
            return list(self._instruments.values())

    def value(self, name: str, **labels: str) -> Optional[float]:
        """Current value of a counter/gauge, None if never created (a
        convenience for tests and snapshot consumers — does NOT create)."""
        inst = self._instruments.get((name, _label_key(labels)))
        return None if inst is None else getattr(inst, "value", None)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """JSON-safe point-in-time view::

            {"ts_wall": ..., "ts_monotonic": ...,
             "counters":   {"ps_commits_total": 12.0, ...},
             "gauges":     {'ps_staleness{conn="0"}': 3.0, ...},
             "histograms": {"async_window_wall_seconds": {count, sum, min,
                            max, mean, buckets: [[le, cumcount], ...]}, ...}}

        Stamped with BOTH clocks (ISSUE 8 satellite): consecutive
        snapshots' monotonic stamps give exact rate denominators (wall
        time jumps under NTP slew; flush jitter made read-side
        re-derivation of dt unreliable), while the wall stamp keeps rows
        joinable to external logs."""
        out: Dict[str, Dict[str, object]] = {
            "ts_wall": time.time(), "ts_monotonic": time.monotonic(),
            "counters": {}, "gauges": {}, "histograms": {}}
        for inst in self.instruments():
            key = _render_name(inst.name, inst.labels)
            if isinstance(inst, Counter):
                out["counters"][key] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][key] = inst.value
            else:
                out["histograms"][key] = inst.summary()
        return out

    def kind_of(self, name: str) -> Optional[str]:
        """``"counter"``/``"gauge"``/``"histogram"`` for a registered
        metric name (exposition renderers need the TYPE line)."""
        return self._kinds.get(name)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4, rendered on demand —
        the pull-style sink (no server here; the punchcard daemon's
        ``telemetry`` action and any embedding HTTP handler just return
        this string).  The renderer lives in :mod:`.sinks` (label-value
        escaping and name sanitization are exposition-format concerns);
        snapshots and the punchcard JSON keep the raw registry spelling."""
        from distkeras_tpu.observability.sinks import render_prometheus

        return render_prometheus(self)

    def reset(self) -> None:
        """Zero every instrument IN PLACE (tests; a fresh run's clean
        slate).  Registrations are kept deliberately: hot paths are told to
        cache instrument objects, so dropping them here would orphan those
        references and silently lose all their subsequent writes."""
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            inst._zero()
