"""Telemetry sinks: periodic JSONL flusher + Prometheus text exposition.

Two pull/push shapes, both dependency-free:

- :class:`JsonlFlusher` — a daemon thread that appends one JSON line per
  interval to a file: ``{"ts": ..., "metrics": <registry snapshot>}``,
  plus a ``"spans"`` list when a tracer is attached (spans are DRAINED —
  each is flushed exactly once).  Crash-safe by construction: every line
  is self-contained, so a truncated final line loses only itself.
- :func:`render_prometheus` — the text exposition format, rendered on
  demand (no HTTP server here; the punchcard daemon's ``telemetry``
  action returns it, and any embedding web handler can too).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from distkeras_tpu.observability.metrics import MetricsRegistry
from distkeras_tpu.observability.tracing import SpanTracer


def render_prometheus(registry: MetricsRegistry) -> str:
    return registry.render_prometheus()


class JsonlFlusher:
    """Periodic JSONL metrics/span flusher.

    ``with JsonlFlusher(path, registry, tracer, interval=10): ...`` or
    explicit ``start()``/``stop()``; ``stop()`` performs a final flush so
    short runs always land at least one complete line.
    """

    def __init__(self, path: str, registry: MetricsRegistry,
                 tracer: Optional[SpanTracer] = None,
                 interval: float = 10.0):
        if interval <= 0:
            raise ValueError(f"interval must be positive, got {interval}")
        self.path = path
        self.registry = registry
        self.tracer = tracer
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._write_lock = threading.Lock()

    def flush(self) -> None:
        line = {"ts": time.time(), "metrics": self.registry.snapshot()}
        if self.tracer is not None:
            spans = self.tracer.drain()
            if spans:
                line["spans"] = spans
        # one locked append per flush: the periodic loop and a final
        # stop()-flush must not interleave half-lines
        with self._write_lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(line) + "\n")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except OSError:
                # a full/unmounted disk must not kill the training run the
                # flusher is observing; the next interval retries
                pass

    def start(self) -> "JsonlFlusher":
        if self._thread is not None:
            raise RuntimeError("flusher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-jsonl-flusher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.flush()
        except OSError:
            # same contract as the periodic loop: a full/unmounted disk at
            # shutdown must not crash the run the flusher was observing
            pass

    def __enter__(self) -> "JsonlFlusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
