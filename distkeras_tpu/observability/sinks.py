"""Telemetry sinks: periodic JSONL flusher + Prometheus text exposition.

Two pull/push shapes, both dependency-free:

- :class:`JsonlFlusher` — a daemon thread that appends one JSON line per
  interval to a file: ``{"ts": ..., "metrics": <registry snapshot>}``,
  plus a ``"spans"`` list when a tracer is attached (spans are DRAINED —
  each is flushed exactly once).  Crash-safe by construction: every line
  is self-contained, so a truncated final line loses only itself.
- :func:`render_prometheus` — the text exposition format 0.0.4, rendered
  on demand (no HTTP server here; the punchcard daemon's ``telemetry``
  action returns it, and any embedding web handler can too).  Metric
  names are sanitized onto the Prometheus grammar and label VALUES are
  escaped per the text-format spec (backslash, double-quote, newline) —
  an unescaped ``\\n`` or ``"`` in a label would corrupt the whole scrape.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from distkeras_tpu.observability.metrics import (
    DEFAULT_BUCKETS,
    Histogram,
    MetricsRegistry,
    _prometheus_name,
)
from distkeras_tpu.observability.tracing import SpanTracer


def escape_label_value(value: str) -> str:
    """Escape one label value per the Prometheus text exposition spec:
    backslash -> ``\\\\``, double-quote -> ``\\"``, line feed -> ``\\n``
    (backslash FIRST, or the other two would double-escape)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _exposition_name(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def render_prometheus(registry: MetricsRegistry) -> str:
    """Render ``registry`` in the text exposition format.  Histograms emit
    the full cumulative ``_bucket`` series (every fixed log bound plus the
    explicit ``le="+Inf"`` overflow) and ``_sum``/``_count``, so
    ``histogram_quantile()`` works on every exported histogram (e.g.
    ``ps_pull_latency_ms``)."""
    by_name: Dict[str, List[object]] = {}
    for inst in registry.instruments():
        by_name.setdefault(inst.name, []).append(inst)
    lines: List[str] = []
    for raw in sorted(by_name):
        kind = registry.kind_of(raw)
        name = _prometheus_name(raw)
        lines.append(f"# TYPE {name} {kind}")
        for inst in sorted(by_name[raw], key=lambda i: i.labels):
            if isinstance(inst, Histogram):
                s = inst.summary()
                cum = 0
                dense: Dict[object, int] = dict(
                    (le, c) for le, c in s["buckets"])
                for le in list(DEFAULT_BUCKETS) + ["+Inf"]:
                    if le in dense:
                        cum = dense[le]
                    labels = dict(inst.labels)
                    labels["le"] = "+Inf" if le == "+Inf" else f"{le:g}"
                    key = _exposition_name(
                        name + "_bucket", tuple(sorted(labels.items())))
                    lines.append(f"{key} {cum}")
                lines.append(
                    f"{_exposition_name(name + '_sum', inst.labels)} {s['sum']}")
                lines.append(
                    f"{_exposition_name(name + '_count', inst.labels)} {s['count']}")
            else:
                lines.append(f"{_exposition_name(name, inst.labels)} {inst.value}")
    return "\n".join(lines) + ("\n" if lines else "")


class JsonlFlusher:
    """Periodic JSONL metrics/span flusher.

    ``with JsonlFlusher(path, registry, tracer, interval=10): ...`` or
    explicit ``start()``/``stop()``; ``stop()`` performs a final flush so
    short runs always land at least one complete line.
    """

    def __init__(self, path: str, registry: MetricsRegistry,
                 tracer: Optional[SpanTracer] = None,
                 interval: float = 10.0):
        if interval <= 0:
            raise ValueError(f"interval must be positive, got {interval}")
        self.path = path
        self.registry = registry
        self.tracer = tracer
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._write_lock = threading.Lock()

    def flush(self) -> None:
        # both clocks on every record (ISSUE 8 satellite): "ts" (wall)
        # stays for log joins, "ts_monotonic" gives downstream rate/lag
        # computation an exact dt across flush jitter — the snapshot
        # itself carries the same pair, captured at ITS read time
        line = {"ts": time.time(), "ts_monotonic": time.monotonic(),
                "metrics": self.registry.snapshot()}
        series = self.registry.tracked_snapshot()
        if series:
            line["series"] = series
        if self.tracer is not None:
            spans = self.tracer.drain()
            if spans:
                line["spans"] = spans
        # one locked append per flush: the periodic loop and a final
        # stop()-flush must not interleave half-lines
        with self._write_lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(line) + "\n")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except OSError:
                # a full/unmounted disk must not kill the training run the
                # flusher is observing; the next interval retries
                pass

    def start(self) -> "JsonlFlusher":
        if self._thread is not None:
            raise RuntimeError("flusher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-jsonl-flusher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.flush()
        except OSError:
            # same contract as the periodic loop: a full/unmounted disk at
            # shutdown must not crash the run the flusher was observing
            pass

    def __enter__(self) -> "JsonlFlusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
