"""Span tracer: context-manager spans in a bounded ring buffer.

Spans are the wall-clock complement to the metrics registry: where a
histogram says "window wall time is bimodal", the trace says WHICH windows
were slow and what they overlapped with (the pull RPC? the H2D transfer?
another worker's commit?).  The round-5 wall-vs-device async decomposition
(371 ms vs 1.6 ms per window, VERDICT.md) was hand-instrumented exactly
this way; this module makes that measurement a permanent, exportable
signal.

Two export forms:

- **Chrome ``trace_event`` JSON** (``chrome_trace`` / ``export_chrome``):
  complete ``"ph": "X"`` events with per-thread tracks — load the file at
  ``chrome://tracing`` / https://ui.perfetto.dev and the async workers,
  PS handler threads and prefetch producer appear as parallel lanes.
- **JSONL** (``jsonl`` / ``drain``): one JSON object per span, for the
  periodic flusher and ad-hoc grepping.

The buffer is a fixed-capacity ring (``collections.deque(maxlen=...)``):
a long run keeps the most recent spans and counts what it evicted
(``dropped``) instead of growing without bound.  Like the registry,
recording is near-zero when disabled — ``span()`` returns a shared no-op
context manager.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    try:  # numpy / jax scalars quack like floats
        return float(value)
    except (TypeError, ValueError):
        return str(value)


class _NullSpan:
    """Shared disabled-mode span: enter/exit do nothing, no allocation."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "_t0", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        self._depth = len(stack)
        stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = time.perf_counter_ns()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        if exc_type is not None:
            # a span that ends by raising is an ERROR span, not a silent
            # close: error=1 makes failures countable/filterable in any
            # trace viewer, error_type names the exception class
            self.attrs["error"] = 1
            self.attrs["error_type"] = exc_type.__name__
        self._tracer._record(self.name, self._t0, t1, self._depth, self.attrs)


class SpanTracer:
    """Bounded-ring span recorder; one per process by default (the
    ``TRACER`` in ``distkeras_tpu.observability``)."""

    def __init__(self, capacity: int = 8192, enabled: bool = False):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.dropped = 0  # spans evicted by the ring since the last clear()

    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def span(self, name: str, **attrs: Any):
        """``with tracer.span("async.window", worker=idx): ...`` — records
        one complete event on exit.  Attrs must be JSON-representable (or
        float()-able/str()-able; coerced at export)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def _record(self, name: str, t0_ns: int, t1_ns: int, depth: int,
                attrs: Dict[str, Any], tid: Optional[Any] = None) -> None:
        event = {
            "name": name,
            "ts_us": int(t0_ns) // 1000,     # perf_counter epoch, process-local
            "dur_us": max((int(t1_ns) - int(t0_ns)) // 1000, 0),
            "tid": threading.get_ident() if tid is None else tid,
            "thread": (threading.current_thread().name if tid is None
                       else str(tid)),
            "depth": depth,
        }
        if attrs:
            event["attrs"] = {k: _json_safe(v) for k, v in attrs.items()}
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(event)

    def record_span(self, name: str, t0_ns: int, t1_ns: int,
                    tid: Optional[Any] = None, **attrs: Any) -> None:
        """Record a span with EXPLICIT timestamps (same monotonic epoch as
        ``time.perf_counter_ns``) — for spans measured outside Python,
        e.g. the C++ hub's commit log replayed by
        ``NativeParameterServer.sync_telemetry``.  ``tid`` overrides the
        track (default: the calling thread)."""
        if not self.enabled:
            return
        self._record(name, t0_ns, t1_ns, 0, attrs, tid=tid)

    # -- introspection / export ------------------------------------------------
    def __len__(self) -> int:
        return len(self._events)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def drain(self) -> List[Dict[str, Any]]:
        """Pop everything recorded so far (the periodic JSONL flusher's
        read: each span is exported exactly once)."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
            return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def chrome_trace(self) -> Dict[str, Any]:
        """The Chrome ``trace_event`` object (JSON-dumps-ready): complete
        ``X`` events, one track per recording thread."""
        pid = os.getpid()
        trace_events = []
        for e in self.events():
            trace_events.append({
                "name": e["name"],
                "ph": "X",
                "ts": e["ts_us"],
                "dur": e["dur_us"],
                "pid": pid,
                "tid": e["tid"],
                "args": dict(e.get("attrs") or {}, depth=e["depth"],
                             thread=e["thread"]),
            })
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path``; returns the path."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def jsonl(self) -> Iterator[str]:
        """One JSON line per recorded span (non-destructive)."""
        for e in self.events():
            yield json.dumps(e)

    def export_jsonl(self, path: str) -> str:
        with open(path, "w") as f:
            for line in self.jsonl():
                f.write(line + "\n")
        return path
