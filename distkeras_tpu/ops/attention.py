"""Attention ops: dense causal attention + ring attention (sequence parallel).

The reference framework predates transformers and has no long-context
machinery (SURVEY.md §5 "Long-context: absent entirely"); this module is
TPU-native headroom, built first-class per the framework's scaling goals.

Ring attention (Liu et al. 2023 pattern): shard the sequence over a mesh
axis; each device holds a query block and streams key/value blocks around
the ring with ``lax.ppermute``, accumulating softmax online (flash-style
running max / denominator), so attention over a sequence of length L costs
O(L/sp) memory per chip and the KV transfers ride the ICI ring.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def repeat_kv_heads(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray):
    """Broadcast grouped KV heads up to the query head count (GQA).

    q [B, Lq, H, D], k/v [B, Lk, Hkv, D] with H a multiple of Hkv: each
    group of H/Hkv query heads shares one KV head (Ainslie et al. 2023).
    Identity when the counts already match (MHA).  The repeat happens at
    the last possible moment — callers that MOVE k/v first (the ring's
    ppermute rotation, the decode cache's HBM reads) keep the Hkv-sized
    tensors on the wire/in memory, which is the point of GQA."""
    hq, hkv = q.shape[2], k.shape[2]
    if hq == hkv:
        return k, v
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True,
                    q_offset: int = 0, k_offset: int = 0) -> jnp.ndarray:
    """Plain softmax attention. Shapes: q [B, Lq, H, D], k/v [B, Lk, H, D]
    (or [B, Lk, Hkv, D] with grouped KV heads — broadcast up internally).

    ``q_offset``/``k_offset`` are the global positions of the first query /
    key element — needed when the caller holds only a shard of the sequence.
    """
    k, v = repeat_kv_heads(q, k, v)
    depth = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    if causal:
        # rows with no visible key (q_offset < k_offset shards) output exactly
        # 0 — softmax of an all-masked row would otherwise emit uniform(V);
        # ring/flash attention both use the zero convention
        any_visible = mask.any(axis=-1)  # [Lq]
        out = jnp.where(any_visible[None, :, None, None], out, 0)
    return out


def ring_block_impl(l_local: int, head_dim: int) -> str:
    """The per-block compute ``ring_attention`` auto-selects for a shard of
    ``l_local`` positions on TPU; dense-XLA below the crossover, the flash
    kernel above it (which also needs Mosaic-legal 128-divisible blocks).

    The crossover tracks per-block WORK, not length alone — v5e
    device-time measurements (fwd+bwd per block; bench ``ring`` legs
    track the hd-64 row): head_dim 64 flash/dense = 0.79x at l_local
    1024, 4.0x at 2048; head_dim 128 = 0.72x at 512, 1.05x at 1024,
    2.29x at 2048.  Both cross between 65k and 131k of l_local*head_dim,
    so the rule is area >= 2048*64.  Single source for the threshold —
    the bench imports this instead of restating it."""
    return ("flash" if (jax.default_backend() == "tpu"
                        and l_local * head_dim >= 2048 * 64
                        and l_local % 128 == 0)
            else "dense")


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, axis_name: str,
                   causal: bool = True, impl: Optional[str] = None) -> jnp.ndarray:
    """Sequence-parallel attention under ``shard_map`` over ``axis_name``.

    Each caller holds the local sequence shard: q/k/v [B, L_local, H, D].
    KV blocks rotate around the ring; the block held at step ``s`` is the
    one that originated on rank ``(my_rank - s) mod sp``.

    TPU-grade schedule (round 3):

    - the per-step block compute is the Pallas flash kernel via
      ``flash_attention_with_lse`` (bf16 matmuls at MXU rate, f32
      softmax stats) instead of dense f32 XLA attention;
    - under causal masking only step 0 needs a mask at all: a LIVE step
      ``s > 0`` holds kv from rank ``my - s`` — strictly the past, every
      position visible — so it runs the cheaper non-causal kernel, and a
      DEAD step (``src > my``: kv entirely in this rank's future, about
      half of all (rank, step) pairs) skips the kernel entirely behind
      ``lax.cond`` — the per-device predicate is local control flow, only
      the ``ppermute`` rotation stays unconditional;
    - per-step (o_s, lse_s) partials merge online in float32:
      ``out = sum_s o_s * exp(lse_s - M) / sum_s exp(lse_s - M)`` with a
      running max M, so per-chip memory stays O(L_local) and gradients
      flow exactly through both outputs (the lse cotangent folds into the
      flash backward as a delta shift).

    ``impl``: ``None`` auto-selects — the flash kernel on TPU for shards
    long enough to win (measured v5e per-block crossover, DEVICE time
    2026-07-31, tracked by ``bench.py``'s ``ring`` legs: 5.0x at
    l_local=4096, 4.0x at 2048, 0.79x at 1024 — small blocks can't
    amortize the kernel's VPU overhead), dense-XLA otherwise (including
    CPU meshes, where interpret-mode flash is also prohibitively slow for
    tests).  ``"flash"``/``"dense"`` force a path (CPU flash-ring
    composition tests; numerical cross-checks).
    """
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l_local, h, d = q.shape
    if impl is None:
        use_flash = ring_block_impl(l_local, d) == "flash"
    elif impl in ("flash", "dense"):
        use_flash = impl == "flash"
    else:
        raise ValueError(f"unknown ring impl {impl!r}: expected 'flash' or 'dense'")

    def block_attn(k_blk, v_blk, step_causal):
        # one (o, lse) partial for the local q block against one kv block;
        # lse is log-sum-exp of the scaled scores [B, H, Lq].  Grouped KV
        # heads (GQA) broadcast up HERE — after the ppermute rotation — so
        # the ICI ring carries only the Hkv-sized tensors.  The flash
        # kernel always runs causal=True: a live step s > 0 passes
        # q_offset=l_local so every key is provably in the past and the
        # kernel's mask takes its identity branch everywhere (same cost as
        # an unmasked kernel, and it sidesteps a pallas-interpreter vma
        # bug that trips the causal=False kernel under shard_map on CPU)
        k_blk, v_blk = repeat_kv_heads(q, k_blk, v_blk)
        if use_flash:
            from distkeras_tpu.ops.flash_attention import flash_attention_with_lse

            return flash_attention_with_lse(q, k_blk, v_blk, causal=True,
                                            q_offset=0 if step_causal else l_local,
                                            k_offset=0)
        scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k_blk.astype(jnp.float32)) * scale
        if step_causal:
            pos = jnp.arange(l_local)
            logits = jnp.where((pos[:, None] >= pos[None, :])[None, None], logits,
                               -jnp.inf)
        m = jnp.max(logits, axis=-1)
        p = jnp.exp(logits - m[..., None])
        l_sum = jnp.sum(p, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        # stay in f32: the merge accumulates in f32 anyway, and the dense
        # branch doubles as the exact reference for numerical cross-checks
        return o / l_sum.transpose(0, 2, 1)[..., None], m + jnp.log(l_sum)

    # constants entering per-device results must carry q's full varying set
    # (covers extra mesh axes like dp) or cond/accumulation types mismatch
    vma = tuple(jax.typeof(q).vma) or (axis_name,)

    def live_step(k_blk, v_blk, step_causal):
        o_s, lse_s = block_attn(k_blk, v_blk, step_causal)
        return o_s.astype(jnp.float32), lse_s

    def dead_step(k_blk, v_blk):
        return tuple(lax.pcast(x, vma, to="varying") for x in (
            jnp.zeros((b, l_local, h, d), jnp.float32),
            jnp.full((b, h, l_local), -jnp.inf, jnp.float32)))

    m0 = jnp.full((b, h, l_local), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, l_local), dtype=jnp.float32)
    acc0 = jnp.zeros((b, l_local, h, d), dtype=jnp.float32)
    m0, l0, acc0 = (lax.pcast(x, vma, to="varying") for x in (m0, l0, acc0))

    m, l_sum, acc = m0, l0, acc0
    k_blk, v_blk = k, v
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    # python loop: sp is static, and a static step index makes step 0 the
    # ONLY masked kernel (the scan-based version had to mask every step)
    for s in range(sp):
        if s:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (my - s) % sp  # global rank the current kv block came from
        if causal and s:
            # step_causal is static (False for s > 0: the kv block is
            # strictly in the past), so it closes over the branches rather
            # than riding the cond operands
            o_s, lse_s = lax.cond(src <= my,
                                  lambda kb, vb: live_step(kb, vb, False),
                                  dead_step, k_blk, v_blk)
        else:
            o_s, lse_s = live_step(k_blk, v_blk, causal)
        new_m = jnp.maximum(m, lse_s)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - safe_m))
        w = jnp.exp(jnp.where(jnp.isneginf(lse_s), -jnp.inf, lse_s - safe_m))
        l_sum = l_sum * corr + w
        wq = w.transpose(0, 2, 1)[..., None]      # [B, Lq, H, 1]
        corrq = corr.transpose(0, 2, 1)[..., None]
        acc = acc * corrq + o_s * wq
        m = new_m
    denom = jnp.maximum(l_sum, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def attention(q, k, v, causal: bool = True, axis_name: Optional[str] = None,
              impl: Optional[str] = None):
    """Dispatch: ring attention when a sequence mesh axis is given, else dense.

    A sequence-parallel model traced outside ``shard_map`` (e.g. parameter
    init, or single-device eval of the same spec) has no bound axis; fall
    back to dense attention — parameters and semantics are identical, only
    the schedule differs.  The fallback applies ONLY when no mesh axes are
    bound at all: inside a shard_map whose axes don't include ``axis_name``,
    falling back would silently attend within each local shard, so that is
    an error instead.

    ``impl``: ``"flash"`` forces the Pallas flash kernel, ``"dense"``
    forces plain XLA softmax attention, ``None`` auto-selects flash on TPU
    for sequences long enough to benefit (the kernel skips masked key
    blocks and never materializes [Lq, Lk]).  Under sequence parallelism
    the schedule is always ring attention and ``impl`` selects its
    per-block compute (``ring_attention``'s own crossover applies when
    ``None``).
    """
    if axis_name is not None and not jax.typeof(q).vma:
        axis_name = None  # traced outside any shard_map: dense is exact
    if axis_name is None:
        if impl is None:
            # flash wins on TPU whenever the sequence is long enough for
            # Mosaic-legal blocks: measured on v5e DEVICE time (fwd+bwd,
            # 2026-07-31 sweep) 1.1-1.9x at every L >= 2048 shape probed
            # (b1-b8, head_dim 64 and 128, 2k-8k tokens).  The round-3
            # rule additionally required B*L >= 16k tokens — that cutoff
            # was an artifact of WALL timing (relay dispatch noise on
            # small, fast steps); it cost the head_dim-128 LM legs 30-44%
            # (e.g. the 1024-dim leg: dense 126.8 ms/step vs flash 88.1).
            # Deliberately LENGTH-only, unlike ring_block_impl's area
            # rule: below 2048 the winner here flips with batch as well
            # (L=1024 device sweep: 0.77x at b2/hd64 but 2.09x at
            # b8/hd64; 0.92x at b2/hd128, 1.12x at b8/hd128), so there
            # is no clean sub-2048 predicate — the length rule is the
            # measured safe-everywhere region
            impl = ("flash" if (jax.default_backend() == "tpu"
                                and q.shape[1] >= 2048
                                and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0)
                    else "dense")
        if impl == "flash":
            from distkeras_tpu.ops.flash_attention import flash_attention

            # the Pallas kernel contracts equal head counts; grouped KV
            # heads broadcast up here (training holds the full sequence
            # anyway — GQA's memory win is the decode cache and the ring's
            # ICI traffic, both handled elsewhere)
            k, v = repeat_kv_heads(q, k, v)
            return flash_attention(q, k, v, causal=causal)
        if impl != "dense":
            raise ValueError(f"unknown attention impl {impl!r}: expected 'flash' or 'dense'")
        return dense_attention(q, k, v, causal=causal)
    try:
        lax.axis_size(axis_name)
    except NameError:
        raise ValueError(
            f"sequence axis {axis_name!r} is not bound by the enclosing shard_map "
            f"(bound varying axes: {sorted(jax.typeof(q).vma)}); the model's seq_axis "
            f"must match the mesh axis the sequence is sharded over") from None
    # the schedule is ring attention; impl selects its PER-BLOCK compute
    # (flash kernel vs dense XLA), auto-selected by shard length when None
    return ring_attention(q, k, v, axis_name=axis_name, causal=causal, impl=impl)
