"""Attention ops: dense causal attention + ring attention (sequence parallel).

The reference framework predates transformers and has no long-context
machinery (SURVEY.md §5 "Long-context: absent entirely"); this module is
TPU-native headroom, built first-class per the framework's scaling goals.

Ring attention (Liu et al. 2023 pattern): shard the sequence over a mesh
axis; each device holds a query block and streams key/value blocks around
the ring with ``lax.ppermute``, accumulating softmax online (flash-style
running max / denominator), so attention over a sequence of length L costs
O(L/sp) memory per chip and the KV transfers ride the ICI ring.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True,
                    q_offset: int = 0, k_offset: int = 0) -> jnp.ndarray:
    """Plain softmax attention. Shapes: q [B, Lq, H, D], k/v [B, Lk, H, D].

    ``q_offset``/``k_offset`` are the global positions of the first query /
    key element — needed when the caller holds only a shard of the sequence.
    """
    depth = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    if causal:
        # rows with no visible key (q_offset < k_offset shards) output exactly
        # 0 — softmax of an all-masked row would otherwise emit uniform(V);
        # ring/flash attention both use the zero convention
        any_visible = mask.any(axis=-1)  # [Lq]
        out = jnp.where(any_visible[None, :, None, None], out, 0)
    return out


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, axis_name: str,
                   causal: bool = True) -> jnp.ndarray:
    """Sequence-parallel attention under ``shard_map`` over ``axis_name``.

    Each caller holds the local sequence shard: q/k/v [B, L_local, H, D].
    KV blocks rotate around the ring; the block held at step ``s`` is the
    one that originated on rank ``(my_rank - s) mod sp``. Softmax is
    accumulated online in float32 for stability.
    """
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l_local, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    q32 = q.astype(jnp.float32)

    q_pos = my * l_local + jnp.arange(l_local)

    def step(carry, s):
        m, l_sum, acc, k_blk, v_blk = carry
        src = (my - s) % sp  # global rank the current kv block came from
        k_pos = src * l_local + jnp.arange(l_local)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None, :, :], logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)  # [B,H,Lq]
        new_m = jnp.maximum(m, blk_max)
        # guard: fully-masked rows produce -inf max; keep exp well-defined
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        correction = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - safe_m))
        probs = jnp.exp(logits - safe_m[..., None])  # [B,H,Lq,Lk]
        new_l = l_sum * correction + jnp.sum(probs, axis=-1)
        blk_out = jnp.einsum("bhqk,bkhd->bqhd", probs, v_blk.astype(jnp.float32))
        new_acc = acc * correction.transpose(0, 2, 1)[..., None] + blk_out
        # rotate kv one hop around the ring (rank r -> r+1)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (new_m, new_l, new_acc, k_next, v_next), None

    m0 = jnp.full((b, h, l_local), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, l_local), dtype=jnp.float32)
    acc0 = jnp.zeros((b, l_local, h, d), dtype=jnp.float32)
    # accumulators become device-varying on the first scan step; mark them
    # with q's full varying set (covers extra mesh axes like dp)
    vma = tuple(jax.typeof(q).vma) or (axis_name,)
    m0, l0, acc0 = (lax.pcast(x, vma, to="varying") for x in (m0, l0, acc0))
    (m, l_sum, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v), jnp.arange(sp))
    denom = jnp.maximum(l_sum, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def attention(q, k, v, causal: bool = True, axis_name: Optional[str] = None,
              impl: Optional[str] = None):
    """Dispatch: ring attention when a sequence mesh axis is given, else dense.

    A sequence-parallel model traced outside ``shard_map`` (e.g. parameter
    init, or single-device eval of the same spec) has no bound axis; fall
    back to dense attention — parameters and semantics are identical, only
    the schedule differs.  The fallback applies ONLY when no mesh axes are
    bound at all: inside a shard_map whose axes don't include ``axis_name``,
    falling back would silently attend within each local shard, so that is
    an error instead.

    ``impl``: ``"flash"`` forces the Pallas flash kernel on the dense path,
    ``"dense"`` forces plain XLA softmax attention, ``None`` auto-selects
    flash on TPU for sequences long enough to benefit (the kernel skips
    masked key blocks and never materializes [Lq, Lk]).
    """
    if axis_name is not None and jax.typeof(q).vma:
        # sequence-parallel path: the schedule is ring attention; a forced
        # per-block impl is not honored here, so reject rather than ignore
        if impl is not None:
            raise ValueError(
                f"impl={impl!r} is not supported under sequence parallelism "
                f"(axis {axis_name!r} is bound): the schedule is ring attention")
    if axis_name is not None and not jax.typeof(q).vma:
        axis_name = None  # traced outside any shard_map: dense is exact
    if axis_name is None:
        if impl is None:
            # flash needs Mosaic-legal blocks AND enough total work to beat
            # XLA's fused softmax-attention: measured on v5e (fwd+bwd,
            # 2026-07-30 sweep) flash wins at B*L >= 16k tokens with
            # L >= 2048 (1.2-1.7x) and loses below (0.8x at B=2, L=2048)
            tokens = q.shape[0] * q.shape[1]
            impl = ("flash" if (jax.default_backend() == "tpu"
                                and q.shape[1] >= 2048 and tokens >= 16384
                                and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0)
                    else "dense")
        if impl == "flash":
            from distkeras_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal)
        if impl != "dense":
            raise ValueError(f"unknown attention impl {impl!r}: expected 'flash' or 'dense'")
        return dense_attention(q, k, v, causal=causal)
    try:
        lax.axis_size(axis_name)
    except NameError:
        raise ValueError(
            f"sequence axis {axis_name!r} is not bound by the enclosing shard_map "
            f"(bound varying axes: {sorted(jax.typeof(q).vma)}); the model's seq_axis "
            f"must match the mesh axis the sequence is sharded over") from None
    return ring_attention(q, k, v, axis_name=axis_name, causal=causal)
