"""Fused single-token decode step: one Pallas kernel per transformer block.

Why this exists (measured on v5e, 2026-07-31): the XLA decode step at
batch 1 lowers to ~15 ops per block (LN, qkv, two cache updates, scores,
mask, softmax, pv, proj, residual, LN, up, gelu, down, residual), and a
1-layer/64-dim probe showed the per-token cost scales with that op count
(~0.75us fixed cost per op) rather than matmul size — at 8 layers the
~120-op program spends roughly as much time sequencing ops as it does
moving the ~69MB of weights + KV cache a token actually needs (84us at
819GB/s vs the 89us measured step).  Collapsing each block into ONE
Mosaic kernel removes the per-op overhead floor and leaves the step
bounded by what it must be bounded by: HBM traffic for weights and cache.

Design (single kernel, grid over layers — Mosaic grids run sequentially,
so the hidden-state carry lives in VMEM scratch across grid steps):

- Per-layer weights are stacked to ``[L, ...]`` slabs outside the kernel
  (a one-time, loop-invariant transform that XLA hoists out of the decode
  scan) and streamed per layer through ``BlockSpec`` index maps — Pallas
  double-buffers the fetches, overlapping layer ``l+1``'s weight DMA with
  layer ``l``'s compute.
- The KV cache stays in HBM (``pl.ANY``): the kernel DMAs the layer's
  K/V slabs into VMEM scratch (attention must read them anyway).  The
  NEW K/V rows leave the kernel as ordinary [L, B, HD] outputs and land
  in the cache via one XLA ``dynamic_update_slice`` per cache outside it
  (in place under the decode scan's donation) — Mosaic rejects both a
  dynamic single-row VMEM insert and a sub-tile-aligned HBM DMA write,
  and a blocked-output cache would write the whole slab back per layer
  per token, doubling cache traffic.  The new token's own attention
  contribution is merged analytically as a second online-softmax term,
  so the slab never needs the row at all.
- The K cache is stored TRANSPOSED for this path — [L, B, HD, S] — and
  V row-major [L, B, S, H, D].  This makes both attention contractions
  canonical MXU matmuls with NO [S, HD]-sized elementwise pass and no
  lane<->sublane transposes (Mosaic supports neither a cheap [1, HD] ->
  [HD, 1] reshape nor fast big elementwise f32 passes — the first cut
  of this kernel did five of them and scaled 15x worse per cache row
  than the XLA step):

      scores^T [H, S] = (sel^T ⊙ q_row) [H, HD]  @  k_slab^T [HD, S]
      mix      [H, HD] =          p^T [H, S]     @  v_slab   [S, HD]
      o        [1, HD] = masked row-sum of mix (block-diagonal strip)

  where ``sel^T[h, hd] = (hd // D == h)`` is the 0/1 head selector:
  broadcasting the [1, HD] q row down H sublanes is free, softmax runs
  over lanes, and head count only changes the selector height.

The kernel is decode-phase only (L = 1): prefill keeps the XLA path,
whose big [P, E] matmuls are already MXU-shaped (the K cache is
transposed once after prefill).  Reference parity note: the reference
has no decode path at all (SURVEY.md §2.21 serves independent
``model.predict`` calls); this is TPU-native headroom on the framework's
own serving story.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distkeras_tpu.ops.quantize import QTensor

_NEG_INF = float("-inf")

# the b8 bench working set (two ~6MB KV slabs + double-buffered 6.5MB
# weight blocks + attention temps) sits near 30MB; v5e VMEM fits it
# comfortably but Mosaic's 16MB default does not
_VMEM_LIMIT = 96 * 1024 * 1024


class DecodeWeights(NamedTuple):
    """Per-layer weight slabs stacked on a leading layer axis.

    ``ln`` packs all four norm vectors (ln0 scale/bias, ln1 scale/bias)
    as rows of one [L, 8, E] f32 slab — Mosaic wants the last two block
    dims tileable, and four [L, E] arrays would each carry a sublane-1
    block; padding to 8 rows costs nothing and keeps one fetch."""

    ln: jnp.ndarray     # [L, 8, E] f32
    wqkv: jnp.ndarray   # [L, E, 3*H*D] compute dtype
    wproj: jnp.ndarray  # [L, H*D, E]
    wup: jnp.ndarray    # [L, E, F]
    wdown: jnp.ndarray  # [L, F, E]


def stack_decode_weights(params: Any, num_layers: int,
                         dtype=jnp.bfloat16) -> DecodeWeights:
    """Restack ``block_{i}`` param subtrees into layer-major slabs.

    Inside a jitted generate fn this is loop-invariant w.r.t. the decode
    scan, so XLA materializes the slabs once per call, not per token.
    int8 ``QTensor`` leaves are dequantized here (the fused kernel
    streams weights in the compute dtype; weight-only int8 decode showed
    <3% at batch 1 — see BASELINE.md — so the fused path optimizes the
    dominant costs instead).
    """
    def deq(w):
        return w.dequantize(dtype) if isinstance(w, QTensor) else w.astype(dtype)

    lns, qkvs, projs, ups, downs = [], [], [], [], []
    for i in range(num_layers):
        pb = params[f"block_{i}"]
        e = pb["LayerNorm_0"]["scale"].shape[0]
        ln = jnp.zeros((8, e), jnp.float32)
        ln = ln.at[0].set(pb["LayerNorm_0"]["scale"].astype(jnp.float32))
        ln = ln.at[1].set(pb["LayerNorm_0"]["bias"].astype(jnp.float32))
        ln = ln.at[2].set(pb["LayerNorm_1"]["scale"].astype(jnp.float32))
        ln = ln.at[3].set(pb["LayerNorm_1"]["bias"].astype(jnp.float32))
        lns.append(ln)
        qkvs.append(deq(pb["qkv"]["kernel"]).reshape(e, -1))      # [E, 3HD]
        projs.append(deq(pb["proj"]["kernel"]).reshape(-1, e))    # [HD, E]
        ups.append(deq(pb["up"]["kernel"]))                       # [E, F]
        downs.append(deq(pb["down"]["kernel"]))                   # [F, E]
    return DecodeWeights(jnp.stack(lns), jnp.stack(qkvs), jnp.stack(projs),
                         jnp.stack(ups), jnp.stack(downs))


def round_cache_len(n: int) -> int:
    """The transposed K slab puts the sequence on LANES: multiple of 128."""
    return -(-n // 128) * 128


# what the working set may claim of the 96MB grant, leaving headroom for
# Mosaic's own temporaries and pipelining copies
_VMEM_BUDGET = 72 * 1024 * 1024


def _kernel_vmem_bytes(config: dict, batch: int, cache_len: int) -> int:
    """Rough VMEM working set: both KV slabs + double-buffered weight
    blocks + the [B*H, B*S] f32 score block and its exp/mask copies +
    the per-layer activation slabs (sublane-padded qkv output [B8, 3E]
    and MLP up-projection [B8, F] — near the budget these are what
    pushes a shape past the grant, so omitting them would let
    ``fused_step_supported`` pass a shape that dies at Mosaic compile
    time, the exact failure the gate exists to prevent)."""
    e = config["model_dim"]
    h = config["num_heads"]
    f = config.get("mlp_ratio", 4) * e
    import numpy as np

    dsize = np.dtype(config.get("compute_dtype", jnp.bfloat16)).itemsize
    b8 = -(-batch // 8) * 8  # rows are sublane-padded to 8
    slabs = 2 * batch * cache_len * e * dsize
    weight_block = (e * 3 * e + e * e + e * f + f * e) * dsize * 2
    scores = 3 * (batch * h) * (batch * cache_len) * 4
    # the matmuls producing these run at preferred_element_type=f32, so the
    # live buffer is f32 plus its compute-dtype downcast copy
    acts = (b8 * 3 * e + b8 * f) * (4 + dsize)
    return slabs + weight_block + scores + acts


def fused_step_supported(config: dict, batch: int, cache_len: int) -> bool:
    """Shapes the kernel handles: lane-tiled dims, a lane-tiled cache
    length (see ``round_cache_len``), and a working set the VMEM grant
    can hold (a shape passing the tiling checks but blowing the grant
    would die at Mosaic compile time, not fall back).  Callers use the
    XLA step when this is False."""
    e = config["model_dim"]
    h = config["num_heads"]
    f = config.get("mlp_ratio", 4) * e
    # batch cap: the kernel's [B*H, B*S] f32 score block grows
    # quadratically with batch (6MB at b16/s768); past 16 rows plain
    # batched decode amortizes fine anyway
    kv_heads = config.get("num_kv_heads") or h
    return (e % 128 == 0 and f % 128 == 0 and h <= 128
            and kv_heads == h  # GQA's split q/kv layout: XLA step only (v1)
            # rope rotates q/k per step; the kernel bakes learned-table
            # embedding math only (v1) — auto falls back to the XLA step
            and (config.get("positional") or "learned") == "learned"
            and not config.get("moe_experts")
            and cache_len % 128 == 0 and 1 <= batch <= 16
            and _kernel_vmem_bytes(config, batch, cache_len) <= _VMEM_BUDGET)


# auto-select crossover, measured on v5e (2026-07-31, batch 1, 768-row
# cache, device time, us/step fused vs XLA): 2L/128 9.8 vs 20.6 (2.1x),
# 4L/256 19.8 vs 38.1 (1.9x), 6L/384 50.5 vs 58.0 (1.15x), 8L/512 111 vs
# 89 (0.8x — XLA wins; its step is already overlap/bandwidth-optimal at
# that weight volume).  The kernel's edge is the fixed ~15-op-per-layer
# sequencing cost it removes, which stops mattering once per-layer weight
# streaming dominates — so auto-select keys on total block-weight bytes,
# conservatively inside the measured winning region.
_AUTO_MAX_BLOCK_BYTES = 24 * 1024 * 1024


def fused_step_auto(config: dict, batch: int, cache_len: int) -> bool:
    """Should the fused kernel be auto-selected?  True only in the regime
    where it measured FASTER than the XLA step: batch 1 (the batched
    kernel's lockstep score block loses to XLA's amortization) and a
    small-to-mid model (see crossover table above).  ``step_impl='fused'``
    overrides this for A/B measurement; ``fused_step_supported`` is the
    hard shape gate."""
    e = config["model_dim"]
    # qkv 3e² + proj e² + up/down 2·mlp_ratio·e² per layer, bf16 stream
    # (= 12e² at the measured mlp_ratio-4 crossover configs)
    per_layer = (4 + 2 * config.get("mlp_ratio", 4)) * e * e
    block_bytes = per_layer * config["num_layers"] * 2
    return (batch == 1 and block_bytes <= _AUTO_MAX_BLOCK_BYTES
            and fused_step_supported(config, batch, cache_len))


def resolve_step_impl(config: dict, batch: int, cache_len: int,
                      requested, *, what: str = "step_impl") -> str:
    """The ONE selection policy shared by ``make_generate_fn``,
    ``make_speculative_generate_fn`` (draft side), and the bench's leg
    labelling: ``None`` -> fused iff on TPU and ``fused_step_auto``;
    explicit ``"fused"`` -> hard-validated against
    ``fused_step_supported``; anything else must be ``"xla"``."""
    import jax

    cache_len = round_cache_len(cache_len)
    if requested is None:
        return ("fused" if (jax.default_backend() == "tpu"
                            and fused_step_auto(config, batch, cache_len))
                else "xla")
    if requested == "fused":
        if not fused_step_supported(config, batch, cache_len):
            raise ValueError(
                f"{what}='fused' does not support this config/shape "
                f"(model_dim {config['model_dim']}, batch {batch}, cache "
                f"{cache_len}); see ops.decode_step.fused_step_supported")
        return "fused"
    if requested != "xla":
        raise ValueError(f"unknown {what} {requested!r}; use None, 'fused' "
                         "or 'xla'")
    return "xla"


def _ln(x32, scale, bias):
    """LayerNorm matching models/decode.py::_layer_norm (f32 stats, eps 1e-6)."""
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale + bias


def _decode_kernel(pos_ref, x_ref, ln_ref, wqkv_ref, wproj_ref, wup_ref,
                   wdown_ref, kc_hbm, vc_hbm, x_out, k_rows, v_rows,
                   xc, k_slab, v_slab, sem_k, sem_v, *, batch: int,
                   heads: int, pos_dim: int, s_len: int, dtype):
    """One transformer block over the [B8, E] hidden state at position
    ``pos``; grid dimension 0 is the layer index."""
    l = pl.program_id(0)
    pos = pos_ref[0]
    head_dim = pos_dim
    del pos_dim

    # slab reads first: the LN + qkv matmul below runs under the DMA
    cp_k = pltpu.make_async_copy(kc_hbm.at[l], k_slab, sem_k)
    cp_v = pltpu.make_async_copy(vc_hbm.at[l], v_slab, sem_v)
    cp_k.start()
    cp_v.start()

    @pl.when(l == 0)
    def _seed():
        xc[...] = x_ref[...]

    x = xc[...]  # [B8, E] compute dtype (bf16 residual stream, as XLA path)
    x32 = x.astype(jnp.float32)

    y = _ln(x32, ln_ref[0, 0], ln_ref[0, 1]).astype(dtype)
    qkv = jax.lax.dot_general(y, wqkv_ref[0], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    qkv = qkv.astype(dtype)  # XLA path rounds q/k/v to bf16 before use
    hd = heads * head_dim
    q = qkv[:batch, :hd]
    k_new = qkv[:batch, hd:2 * hd]
    v_new = qkv[:batch, 2 * hd:3 * hd]

    k_rows[...] = k_new[None]
    v_rows[...] = v_new[None]
    cp_k.wait()
    cp_v.wait()

    # --- attention over the slab (batch-interleaved transposed-K scheme) --
    # One scores matmul and one mix matmul for the WHOLE batch: rows are
    # (b, h) pairs, columns (b', s) pairs, and the block-diagonal mask
    # kills the b != b' cross terms.  The B-fold FLOP redundancy is ~2us
    # of MXU time at batch 8; the per-b matmul loop it replaced cost
    # ~8us of issue latency per batch row per layer.
    bh, bs = batch * heads, batch * s_len
    kmat = k_slab[...]                                     # [HD, B*S]
    vmat = v_slab[...]                                     # [B*S, HD]

    row_h = jax.lax.broadcasted_iota(jnp.int32, (bh, hd), 0) % heads
    hd_col = jax.lax.broadcasted_iota(jnp.int32, (bh, hd), 1)
    sel_t = hd_col // head_dim == row_h                    # [BH, HD] 0/1
    sel_f32 = sel_t.astype(jnp.float32)
    scale = 1.0 / head_dim ** 0.5
    # selB[b, r] = (r // heads == b): folds the H rows of batch b back to
    # one output row; selBT is its transpose (built from iota, not
    # transposed — Mosaic transposes are not free) replicating each batch
    # row across its H head-rows
    selB = (jax.lax.broadcasted_iota(jnp.int32, (batch, bh), 1) // heads
            == jax.lax.broadcasted_iota(jnp.int32, (batch, bh), 0))
    selBT = (jax.lax.broadcasted_iota(jnp.int32, (bh, batch), 0) // heads
             == jax.lax.broadcasted_iota(jnp.int32, (bh, batch), 1))

    def rows_per_head(a):                                  # [B, HD] -> [BH, HD]
        if batch == 1:
            return jnp.broadcast_to(a, (bh, hd))
        out = jax.lax.dot_general(selBT.astype(a.dtype), a,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return out.astype(a.dtype)  # 0/1 replication: exact in any dtype

    q_bdt = sel_t.astype(dtype) * rows_per_head(q)         # [BH, HD]
    scores = jax.lax.dot_general(
        q_bdt, kmat, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [BH, BS]
    row_b = jax.lax.broadcasted_iota(jnp.int32, (bh, bs), 0) // heads
    col = jax.lax.broadcasted_iota(jnp.int32, (bh, bs), 1)
    mask = (row_b == col // s_len) & (col % s_len < pos)
    scores = jnp.where(mask, scores, _NEG_INF)

    qk_new = q.astype(jnp.float32) * k_new.astype(jnp.float32)   # [B, HD]
    s_new = jnp.sum(sel_f32 * rows_per_head(qk_new), axis=1,
                    keepdims=True) * scale                 # [BH, 1]

    m = jnp.maximum(jnp.max(scores, axis=1, keepdims=True), s_new)
    p = jnp.exp(scores - m)                                # [BH, BS]
    p_new = jnp.exp(s_new - m)                             # [BH, 1]
    denom = jnp.sum(p, axis=1, keepdims=True) + p_new
    # jax.nn.softmax(f32) then .astype(bf16) in the XLA path: divide
    # first, round to bf16, THEN weight V — same op order here
    p = (p / denom).astype(dtype)
    p_new = (p_new / denom).astype(dtype).astype(jnp.float32)
    mix = jax.lax.dot_general(p, vmat, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)  # [BH, HD]
    selB_f32 = selB.astype(jnp.float32)
    o = jax.lax.dot_general(selB_f32, mix * sel_f32, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)    # [B, HD]
    pn_wide = jax.lax.dot_general(selB_f32, sel_f32 * p_new,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    o = o + pn_wide * v_new.astype(jnp.float32)

    pad_rows = x.shape[0] - batch
    o8 = (o.astype(dtype) if pad_rows == 0 else
          jnp.concatenate([o.astype(dtype), jnp.zeros((pad_rows, hd), dtype)]))
    proj = jax.lax.dot_general(o8, wproj_ref[0], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    x = x + proj.astype(dtype)

    x32 = x.astype(jnp.float32)
    y = _ln(x32, ln_ref[0, 2], ln_ref[0, 3]).astype(dtype)
    up = jax.lax.dot_general(y, wup_ref[0], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    up = jax.nn.gelu(up.astype(dtype))
    down = jax.lax.dot_general(up, wdown_ref[0], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    x = x + down.astype(dtype)

    xc[...] = x
    # write the (valid partial) output every visit: last write wins, and
    # no emitted block ever depends on stale revisited-buffer contents
    x_out[...] = x


@functools.partial(jax.jit, static_argnames=("heads", "interpret"))
def _fused_call(weights: DecodeWeights, x8, k_t, v_all, pos_arr, *,
                heads: int, interpret: bool):
    num_layers, hd, b, s_len = k_t.shape
    # 2D per-layer HBM slices for the kernel's DMAs (Mosaic rejects
    # memref slicing that keeps 1 of an inner dim on 4D tiled refs)
    kc = k_t.reshape(num_layers, hd, b * s_len)
    vc = v_all.reshape(num_layers, b * s_len, hd)
    e = x8.shape[1]
    f = weights.wup.shape[2]
    dtype = x8.dtype
    b8 = x8.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_layers,),
        in_specs=[
            pl.BlockSpec((b8, e), lambda l, p: (0, 0)),
            pl.BlockSpec((1, 8, e), lambda l, p: (l, 0, 0)),
            pl.BlockSpec((1, e, 3 * hd), lambda l, p: (l, 0, 0)),
            pl.BlockSpec((1, hd, e), lambda l, p: (l, 0, 0)),
            pl.BlockSpec((1, e, f), lambda l, p: (l, 0, 0)),
            pl.BlockSpec((1, f, e), lambda l, p: (l, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((b8, e), lambda l, p: (0, 0)),
            pl.BlockSpec((1, b, hd), lambda l, p: (l, 0, 0)),
            pl.BlockSpec((1, b, hd), lambda l, p: (l, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((b8, e), dtype),                  # xc carry
            pltpu.VMEM((hd, b * s_len), k_t.dtype),      # k slab (transposed)
            pltpu.VMEM((b * s_len, hd), vc.dtype),       # v slab
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    head_dim = hd // heads
    kernel = functools.partial(_decode_kernel, batch=b, heads=heads,
                               pos_dim=head_dim, s_len=s_len, dtype=dtype)
    x_out, k_rows, v_rows = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((b8, e), dtype),
                   jax.ShapeDtypeStruct((num_layers, b, hd), k_t.dtype),
                   jax.ShapeDtypeStruct((num_layers, b, hd), vc.dtype)],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(pos_arr, x8, weights.ln, weights.wqkv, weights.wproj, weights.wup,
      weights.wdown, kc, vc)
    # the new rows land via ONE dynamic_update_slice per cache — in place
    # under the decode scan's buffer donation, like any XLA KV cache.
    # K is lane-major: its rows form a [.., HD, B, 1] column at lane ``pos``
    pos = pos_arr[0]
    k_t = jax.lax.dynamic_update_slice(
        k_t, jnp.transpose(k_rows, (0, 2, 1))[..., None], (0, 0, 0, pos))
    v_all = jax.lax.dynamic_update_slice(
        v_all, v_rows.reshape(num_layers, b, 1, *v_all.shape[3:]),
        (0, 0, pos, 0, 0))
    return (x_out, k_t, v_all)


def transpose_k_cache(k_all: jnp.ndarray) -> jnp.ndarray:
    """[L, B, S, H, D] (prefill layout) -> [L, H*D, B, S] (fused-step
    layout: keys lane-major, batch interleaved ahead of the sequence so
    the kernel reads one [HD, B*S] slab); one XLA transpose after
    prefill."""
    num_layers, b, s_len = k_all.shape[:3]
    return jnp.transpose(k_all.reshape(num_layers, b, s_len, -1), (0, 3, 1, 2))


def fused_decode_step(weights: DecodeWeights, x, k_t, v_all, pos, *,
                      heads: int, interpret: bool = False):
    """One fused decode step over all layers.

    ``x`` [B, E] is the embedded token at position ``pos``; ``k_t`` is
    the TRANSPOSED [L, HD, B, S] key cache (``transpose_k_cache``),
    ``v_all`` the [L, B, S, H, D] value cache.  Returns (hidden [B, E]
    before final norm, k_t, v_all) with the new rows landed.
    """
    b, e = x.shape
    b8 = max(8, -(-b // 8) * 8)
    x8 = jnp.zeros((b8, e), x.dtype).at[:b].set(x) if b8 != b else x
    pos_arr = jnp.full((1,), pos, jnp.int32)
    x_out, k_t, v_all = _fused_call(weights, x8, k_t, v_all, pos_arr,
                                    heads=heads, interpret=interpret)
    return x_out[:b], k_t, v_all
