"""Pallas TPU flash attention: the framework's hot-op kernel.

The reference delegates all compute to the Keras backend and ships no
kernels of its own (SURVEY.md §2 "Native components: none"); this module is
the TPU-native replacement for that compute path's attention hot op, written
directly against the MXU/VMEM model:

- O(block) VMEM: the [Lq, Lk] probability matrix is never materialized and
  no full-sequence tensor is ever resident — the kv (resp. q) position is
  an innermost grid dimension, so Pallas streams [block, D] tiles through
  VMEM while float32 scratch accumulators carry the online-softmax state
  (running max / denominator / output) across grid steps.  Sequence length
  is bounded by HBM, not VMEM.
- MXU-shaped: matmuls run in the input dtype (bf16 x bf16 at full MXU rate)
  with ``preferred_element_type=float32`` accumulation; only the softmax
  statistics live in float32.
- Causal skipping: key blocks entirely in the masked future contribute no
  FLOPs — the per-block compute is predicated on the block's global
  position, which also makes sharded callers (ring attention holds only a
  sequence shard) pay only for the keys they can see.

Backward pass is the standard flash recomputation: store per-row logsumexp
in the forward; recompute block probabilities in the backward and
accumulate dQ (grid streams kv blocks) and dK/dV (grid streams q blocks)
in float32 scratch.

Interpret mode (``interpret=True``, auto-enabled off-TPU) runs the same
kernels through the Pallas interpreter so CPU tests exercise identical code.

Layout note: kernels grid over (batch, head, outer block, inner block) on a
[B, H, L, D] layout — Mosaic requires the last two block dims to be
(8, 128)-tiled or equal to the array dims, so the head axis must sit
outside them (same scheme as jax.experimental.pallas.ops.tpu
.flash_attention).  The public entry transposes from the framework's
[B, L, H, D]; per-row softmax stats (logsumexp, delta) are stored with a
trailing 8-lane dim for the same tiling reason.

Fully-masked query rows (possible only when ``q_offset < k_offset``) output
exactly 0 with 0 gradient, matching ``ring_attention``'s convention.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_STAT_LANES = 8  # trailing lanes for per-row stats (min f32 tile lane count
                 # that can equal the array dim; avoids 128x padding waste)

# Mosaic's default scoped-vmem budget is 16M, which the dkv kernel's working
# set at (1024, 1024) blocks overflows by 8K inside full transformer backward
# programs (round-2 block sweep).  24M is the measured sweet spot (v5e,
# 2026-07-30 profiled device-time A/B): enough for the large-block dkv pass,
# while a generous 96M grant made the same kernels ~4-5% SLOWER at 2k/8k —
# Mosaic folds the budget into its pipelining decisions, so grant the
# minimum that fits.
_VMEM_LIMIT = 24 * 1024 * 1024
_COMPILER_PARAMS = pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)


class _Config(NamedTuple):
    """Static kernel configuration (hashable: custom_vjp nondiff argument).

    Three block pairs: forward, dq, and dkv.  The backward normally runs as
    ONE fused kernel (``_bwd_fused_kernel``) using the dkv pair; the dq
    pair only matters on the two-kernel fallback taken when the fused
    kernel's [Lq, D] dq scratch would overflow scoped vmem
    (``_fused_bwd_ok``)."""

    causal: bool
    q_offset: int
    k_offset: int
    block_q: int
    block_k: int
    block_q_dq: int
    block_k_dq: int
    block_q_bwd: int
    block_k_bwd: int
    interpret: bool


def _block_visible(cfg: _Config, qi, kj, bq, bk):
    """True unless key block ``kj`` is entirely in query block ``qi``'s
    masked future (then its FLOPs are predicated away).  Block sizes are
    explicit because forward and backward kernels may use different ones."""
    if not cfg.causal:
        return True
    last_q_pos = cfg.q_offset + (qi + 1) * bq - 1
    first_k_pos = cfg.k_offset + kj * bk
    return last_q_pos >= first_k_pos


def _apply_causal_mask(s, cfg: _Config, qi, kj, bq, bk):
    """Mask ``s`` [bq, bk] where q_pos < k_pos — but only blocks that
    straddle the diagonal pay for the iota+where; blocks fully below it
    (first q row sees the last k column) pass through untouched."""
    def masked(s):
        q_pos = cfg.q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = cfg.k_offset + kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        return jnp.where(q_pos >= k_pos, s, _NEG_INF)

    first_q_pos = cfg.q_offset + qi * bq
    last_k_pos = cfg.k_offset + (kj + 1) * bk - 1
    return jax.lax.cond(first_q_pos >= last_k_pos, lambda s: s, masked, s)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                cfg: _Config, scale: float):
    qi, kj = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = cfg.block_q, cfg.block_k

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_block_visible(cfg, qi, kj, bq, bk))
    def _compute():
        q = q_ref[0, 0]  # [bq, d] — native dtype: bf16 x bf16 at full MXU rate
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if cfg.causal:
            s = _apply_causal_mask(s, cfg, qi, kj, bq, bk)
        m = m_scr[:, 0]
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), _NEG_INF, m - safe_m))
        p = jnp.exp(s - safe_m[:, None])
        pv = jax.lax.dot_general(p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(new_m[:, None], m_scr.shape)
        l_scr[...] = l_scr[...] * corr[:, None] + jnp.broadcast_to(
            jnp.sum(p, axis=-1)[:, None], l_scr.shape)
        acc_scr[...] = acc_scr[...] * corr[:, None] + pv

    @pl.when(kj == nk - 1)
    def _flush():
        m = m_scr[:, 0]
        l_sum = l_scr[:, 0]
        # fully-masked rows (l == 0): output exactly 0, lse 0 (a finite
        # sentinel; the backward recomputes p = exp(-inf - 0) = 0 so grads
        # are exactly 0)
        o_ref[0, 0] = (acc_scr[...] / jnp.maximum(l_sum, 1e-30)[:, None]).astype(o_ref.dtype)
        lse = jnp.where(l_sum > 0.0,
                        jnp.where(jnp.isneginf(m), 0.0, m) + jnp.log(jnp.maximum(l_sum, 1e-30)),
                        0.0)
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[2:])


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    cfg: _Config, scale: float, qi, kj, bq, bk):
    """Shared backward recompute: (p, ds, refs' blocks) for one
    [bq, bk] tile.  p = softmax probabilities rebuilt from the stored
    logsumexp (masked entries exactly 0), ds = p * (dp - delta) in float32.
    Used by all three backward kernels so the score/probability algebra
    lives in one place."""
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0:1]      # [bq, 1]
    delta = delta_ref[0, 0, :, 0:1]  # [bq, 1]
    k_blk = k_ref[0, 0]
    v_blk = v_ref[0, 0]
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if cfg.causal:
        s = _apply_causal_mask(s, cfg, qi, kj, bq, bk)
    p = jnp.exp(s - lse)  # masked/-inf entries -> exactly 0
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return p, ds, q, do, k_blk, v_blk


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, cfg: _Config, scale: float):
    qi, kj = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = cfg.block_q_dq, cfg.block_k_dq

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_block_visible(cfg, qi, kj, bq, bk))
    def _compute():
        _, ds, _, _, k_blk, _ = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, cfg, scale, qi, kj, bq, bk)
        dq_scr[...] += jax.lax.dot_general(ds.astype(k_blk.dtype), k_blk,
                                           (((1,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _flush():
        dq_ref[0, 0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, cfg: _Config, scale: float):
    kj, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    bq, bk = cfg.block_q_bwd, cfg.block_k_bwd

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_block_visible(cfg, qi, kj, bq, bk))
    def _compute():
        p, ds, q, do, _, _ = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, cfg, scale, qi, kj, bq, bk)
        dv_scr[...] += jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _flush():
        dk_ref[0, 0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr, *,
                      cfg: _Config, scale: float):
    """One-pass backward: dK, dV and dQ from a single s/p recomputation.

    The separate dq kernel re-derives the identical [bq, bk] score and
    probability blocks the dkv kernel just computed — at small head dims
    that recompute IS the kernel cost, so fusing the two backward passes
    cuts backward time by ~the dq kernel (measured ~25-30% off the whole
    fwd+bwd attention step on v5e).

    The catch is accumulation order: dK/dV accumulate over the inner qi
    steps (scratch flushed per kv block, as before) while dQ accumulates
    over the OUTER kj steps.  A [Lq, D] float32 scratch holds every dq row
    for the (b, h) pair; row block qi is updated in place via a dynamic
    slice and the dq output block is flushed on the final kj pass.  The
    scratch makes VMEM O(Lq * D) rather than O(block) — ``_backward``
    falls back to the two-kernel path when that does not fit.
    """
    kj, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    bq, bk = cfg.block_q_bwd, cfg.block_k_bwd

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when((kj == 0) & (qi == 0))
    def _init_q():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_block_visible(cfg, qi, kj, bq, bk))
    def _compute():
        p, ds, q, do, k_blk, _ = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, cfg, scale, qi, kj, bq, bk)
        ds = ds.astype(q.dtype)
        dv_scr[...] += jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
        dq_scr[pl.ds(qi * bq, bq), :] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _flush_kv():
        dk_ref[0, 0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)

    # dq row block qi accumulates across the OUTER kj steps, so its output
    # window is revisited once per kj.  Emit the current accumulated prefix
    # on EVERY visit: each window Pallas flushes then holds kernel-written
    # data and the final, ordered revisit carries the complete sum —
    # correctness rests on last-write-wins, not on revisited output
    # windows preserving stale buffer contents (unstated semantics under
    # double-buffering).  The extra [bq, d] VMEM store per step is noise
    # next to the three matmuls above.
    dq_ref[0, 0] = (dq_scr[pl.ds(qi * bq, bq), :] * scale).astype(dq_ref.dtype)


def _out_struct(shape, dtype, *like):
    """ShapeDtypeStruct whose ``vma`` (varying-mesh-axes set) is the union
    of the inputs' — required for pallas_call outputs under ``shard_map``
    with vma checking (e.g. the dp-sharded LM step); plain jit traces have
    no vma and take the unannotated branch."""
    vma = frozenset().union(*(getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
                              for x in like))
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _forward(q, k, v, cfg: _Config):
    """q [B, H, Lq, D], k/v [B, H, Lk, D] -> (o like q, lse [B, H, Lq, 8])."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bq, bk = cfg.block_q, cfg.block_k
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_fwd_kernel, cfg=cfg, scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(b, h, lq // bq, lk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, _STAT_LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            _out_struct((b, h, lq, d), q.dtype, q, k, v),
            _out_struct((b, h, lq, _STAT_LANES), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _STAT_LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, _STAT_LANES), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),            # output accumulator
        ],
        interpret=cfg.interpret,
        compiler_params=_COMPILER_PARAMS,
    )(q, k, v)


# Fused-backward eligibility (v5e scoped-vmem measurements, 2026-07-30).
# The fused kernel's [Lq, D] float32 dq scratch plus its block working set
# must fit the scoped-vmem budget; measured boundaries at D=64:
#   (1024, 1024) blocks fit when BOTH the dq scratch and the streamed kv
#     length stay small (through Lq=Lk=16k), and are 2-3% faster than
#     (512, 1024) everywhere they fit; OOM when Lk reaches 32k;
#   (512, 1024) blocks fit through Lq=16k (dq scratch 4.2M) at ANY Lk
#     (the 32k leg runs them via q-chunking), OOM at unchunked Lq=32k;
#   (512, 512) blocks fit through Lq=32k (dq scratch 8.4M);
#   above that, fall back to the two-kernel backward with wide blocks.
# SINGLE-BLOCK tier (round-5, D=128 re-sweep): when the k block spans the
# WHOLE sequence (reachable from auto-select when Lq, Lk <= 2048 — the
# square Lq = Lk case is the measured one; cross-length shapes like
# Lq 2048 / Lk 1024 take the same single-k-block structure) the fused
# backward in one grid step beats (1024, 1024) despite skipping no causal
# blocks — the
# same fewer-passes-beats-fewer-FLOPs tradeoff the forward measured: 1.43
# vs 1.57 ms/step on the 2k hd128 attention leg.  Its [bq, bk] f32
# score/dp + bf16 p tiles (~10 B/element) outgrow the standard 24M grant,
# so ``_bwd_compiler_params`` sizes the grant per call (48M measured flat
# vs 56/64M).  At 8k the same wide blocks LOSE (5.20 vs 4.92: q-chunks
# re-stream k/v and forgo the 44% causal-skip), hence the lk == bk_kv
# containment rather than a general wide tier.
_FUSED_WIDE_CAP = 5 * 1024 * 1024       # dq / lk-stream cap for 1024-wide blocks
_FUSED_DQ_SCRATCH_CAP = 12 * 1024 * 1024  # dq scratch cap for (<=512, <=512)
_BWD_WS_BYTES_PER_ELEM = 10             # f32 s + f32 dp + bf16 p per score
_BWD_WIDE_WS_CAP = 44 * 1024 * 1024     # blocks through (2048, 2048)


def _fused_bwd_ok(lq: int, d: int, bq_kv: int, bk_kv: int, lk: int) -> bool:
    dq_bytes = lq * d * 4
    if bk_kv == lk and 1024 < max(bq_kv, bk_kv) <= 2048:
        # single-k-block wide tier: one (or few) grid passes, sized grant
        return (dq_bytes <= _FUSED_DQ_SCRATCH_CAP
                and bq_kv * bk_kv * _BWD_WS_BYTES_PER_ELEM <= _BWD_WIDE_WS_CAP)
    if bk_kv > 1024:
        return False
    if bq_kv > 1024:
        return False
    if bq_kv > 512:
        return dq_bytes <= _FUSED_WIDE_CAP and lk * d * 4 <= _FUSED_WIDE_CAP
    if bk_kv <= 512:
        return dq_bytes <= _FUSED_DQ_SCRATCH_CAP
    return dq_bytes <= _FUSED_WIDE_CAP


def _bwd_compiler_params(bq_kv: int, bk_kv: int) -> pltpu.CompilerParams:
    """Scoped-vmem grant for a backward call, sized to its score-tile
    working set: the standard minimum-that-fits 24M grant through
    (1024, 1024) blocks; the wide single-block tier measured fastest at
    48M (v5e 2026-07-31: 48M == 56M == 64M within noise, all faster than
    any 24M-compatible blocking).  >= so the boundary pair (2048, 1024)
    — reachable cross-length, e.g. Lq 2048 vs Lk 1024 — gets the sized
    grant its exactly-20M score tiles need rather than the 24M grant
    that only fits the 10M working set of (1024, 1024)."""
    if bq_kv * bk_kv * _BWD_WS_BYTES_PER_ELEM >= 20 * 1024 * 1024:
        return pltpu.CompilerParams(vmem_limit_bytes=48 * 1024 * 1024)
    return _COMPILER_PARAMS


def _fused_backward_call(q, k, v, do, lse, delta, cfg: _Config, scale: float):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bq_kv, bk_kv = cfg.block_q_bwd, cfg.block_k_bwd
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, cfg=cfg, scale=scale),
        grid=(b, h, lk // bk_kv, lq // bq_kv),
        in_specs=[
            pl.BlockSpec((1, 1, bq_kv, d), lambda b, h, j, i: (b, h, i, 0)),   # q
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # k
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # v
            pl.BlockSpec((1, 1, bq_kv, d), lambda b, h, j, i: (b, h, i, 0)),   # do
            pl.BlockSpec((1, 1, bq_kv, _STAT_LANES), lambda b, h, j, i: (b, h, i, 0)),  # lse
            pl.BlockSpec((1, 1, bq_kv, _STAT_LANES), lambda b, h, j, i: (b, h, i, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # dk
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # dv
            pl.BlockSpec((1, 1, bq_kv, d), lambda b, h, j, i: (b, h, i, 0)),   # dq
        ],
        out_shape=[
            _out_struct((b, h, lk, d), k.dtype, q, k, v, do),
            _out_struct((b, h, lk, d), v.dtype, q, k, v, do),
            _out_struct((b, h, lq, d), q.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_kv, d), jnp.float32),
            pltpu.VMEM((bk_kv, d), jnp.float32),
            pltpu.VMEM((lq, d), jnp.float32),
        ],
        interpret=cfg.interpret,
        compiler_params=_bwd_compiler_params(bq_kv, bk_kv),
    )(q, k, v, do, lse, delta)


_FUSED_MAX_CHUNKS = 16


def _fused_q_chunks(lq: int, d: int, bq_kv: int, bk_kv: int, lk: int):
    """Number of equal q-range chunks that makes the fused backward's
    [chunk, D] dq scratch fit scoped vmem (1 = single call, None = cannot
    chunk: fall back to the two-kernel backward).  Chunks re-stream k/v, so
    cap the count — beyond ~16 the repeated kv DMA erodes the win."""
    for n in range(1, _FUSED_MAX_CHUNKS + 1):
        if lq % n:
            continue
        chunk = lq // n
        if chunk % bq_kv == 0 and _fused_bwd_ok(chunk, d, bq_kv, bk_kv, lk):
            return n
    return None


def _backward(q, k, v, o, lse, do, cfg: _Config, dlse=None):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bq, bk = cfg.block_q_dq, cfg.block_k_dq
    bq_kv, bk_kv = cfg.block_q_bwd, cfg.block_k_bwd
    scale = 1.0 / (d ** 0.5)
    # delta[b, h, i] = sum_d dO * O — the softmax-jacobian row term; tiny
    # elementwise reduce, XLA fuses it, no kernel needed.  When the caller
    # also differentiates the lse OUTPUT (flash_attention_with_lse), its
    # cotangent folds into the same kernels: dL/ds = p * (dp - delta + dlse)
    # — i.e. the kernels just see delta' = delta - dlse
    delta = jnp.einsum("bhld,bhld->bhl", do.astype(jnp.float32), o.astype(jnp.float32))
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], (b, h, lq, _STAT_LANES))

    n_chunks = _fused_q_chunks(lq, d, bq_kv, bk_kv, lk)
    if n_chunks == 1:
        dk, dv, dq = _fused_backward_call(q, k, v, do, lse, delta, cfg, scale)
        return dq, dk, dv
    if n_chunks is not None:
        # chunk the q range so each fused call's dq scratch fits scoped
        # vmem: dq concatenates over chunks, dk/dv sum partial results
        # (kv blocks invisible to a chunk flush zeros, so the sum is exact;
        # each chunk's q_offset keeps the causal predication global)
        chunk = lq // n_chunks
        dk = dv = None
        dqs = []
        for c in range(n_chunks):
            sl = lambda x: jax.lax.slice_in_dim(x, c * chunk, (c + 1) * chunk, axis=2)
            cfg_c = cfg._replace(q_offset=cfg.q_offset + c * chunk)
            dk_c, dv_c, dq_c = _fused_backward_call(
                sl(q), k, v, sl(do), sl(lse), sl(delta), cfg_c, scale)
            # accumulate partials in f32: summing bf16 chunk outputs would
            # round at every add, a precision cliff vs the unchunked path
            dk = dk_c.astype(jnp.float32) if dk is None else dk + dk_c
            dv = dv_c.astype(jnp.float32) if dv is None else dv + dv_c
            dqs.append(dq_c)
        return (jnp.concatenate(dqs, axis=2), dk.astype(k.dtype), dv.astype(v.dtype))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, cfg=cfg, scale=scale),
        grid=(b, h, lq // bq, lk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),   # q
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),   # k
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),   # v
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),   # do
            pl.BlockSpec((1, 1, bq, _STAT_LANES), lambda b, h, i, j: (b, h, i, 0)),  # lse
            pl.BlockSpec((1, 1, bq, _STAT_LANES), lambda b, h, i, j: (b, h, i, 0)),  # delta
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=_out_struct((b, h, lq, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=cfg.interpret,
        # size the grant to THIS kernel's score tile too (ADVICE round 5):
        # when the fused path is rejected with full-length forward-inherited
        # blocks (large head_dim, Lq=Lk<=2048), the dq working set can
        # outgrow the fixed 24M grant and fail Mosaic compilation
        compiler_params=_bwd_compiler_params(bq, bk),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, cfg=cfg, scale=scale),
        grid=(b, h, lk // bk_kv, lq // bq_kv),
        in_specs=[
            pl.BlockSpec((1, 1, bq_kv, d), lambda b, h, j, i: (b, h, i, 0)),   # q
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # k
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),   # v
            pl.BlockSpec((1, 1, bq_kv, d), lambda b, h, j, i: (b, h, i, 0)),   # do
            pl.BlockSpec((1, 1, bq_kv, _STAT_LANES), lambda b, h, j, i: (b, h, i, 0)),  # lse
            pl.BlockSpec((1, 1, bq_kv, _STAT_LANES), lambda b, h, j, i: (b, h, i, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk_kv, d), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            _out_struct((b, h, lk, d), k.dtype, q, k, v, do),
            _out_struct((b, h, lk, d), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_kv, d), jnp.float32),
            pltpu.VMEM((bk_kv, d), jnp.float32),
        ],
        interpret=cfg.interpret,
        compiler_params=_bwd_compiler_params(bq_kv, bk_kv),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, cfg: _Config):
    o, _ = _forward(q, k, v, cfg)
    return o


def _flash_fwd(q, k, v, cfg: _Config):
    o, lse = _forward(q, k, v, cfg)
    return o, (q, k, v, o, lse)


def _flash_bwd(cfg: _Config, res, do):
    q, k, v, o, lse = res
    return _backward(q, k, v, o, lse, do, cfg)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_lse(q, k, v, cfg: _Config):
    o, lse = _forward(q, k, v, cfg)
    return o, lse[..., 0]


def _flash_lse_fwd(q, k, v, cfg: _Config):
    o, lse = _forward(q, k, v, cfg)
    return (o, lse[..., 0]), (q, k, v, o, lse)


def _flash_lse_bwd(cfg: _Config, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _backward(q, k, v, o, lse, do, cfg, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _pick_block(block: int, length: int) -> int:
    block = min(block, length)
    while length % block:
        block //= 2
    return max(block, 1)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True, q_offset: int = 0, k_offset: int = 0,
                    block_q: Optional[int] = None, block_k: Optional[int] = None,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over [B, L, H, D] tensors (same layout/semantics as
    ``ops.attention.dense_attention``, including the shard offsets).

    Kernel structure and block defaults (v5e device-time sweeps,
    2026-07-30): the forward uses one full-length block when the [Lq, Lk]
    score tile fits scoped vmem and (1024, 1024) above that; the backward
    normally runs as ONE fused kernel producing dq, dk and dv from a
    single score/probability recompute (25-30% faster than the classic
    two-kernel backward), preferring (512, 1024) blocks and chunking the
    q range when its [Lq, D] f32 dq scratch outgrows scoped vmem
    (``_fused_q_chunks``); the two-kernel path remains as the fallback for
    shapes that cannot chunk.  Small blocks lose badly (128 runs at 0.4x
    dense).

    Explicit knobs: ``block_q``/``block_k`` govern the forward kernel;
    absent bwd overrides the backward AUTO-SELECTS fused-compatible blocks
    (capped at 1024/512 per ``_fused_bwd_ok``) and only inherits the
    forward pair verbatim on the non-fused fallback tier — so a >1024
    forward sweep does NOT reach the backward.  Explicit
    ``block_q_bwd``/``block_k_bwd`` pin the backward kernels exactly
    (including forcing it out of the fused path if too large to fit).
    ``_pick_block`` shrinks every block to fit short sequences
    automatically.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so the
    identical kernel code runs (slowly) in CPU tests.
    """
    cfg = _make_config(q, k, causal, q_offset, k_offset, block_q, block_k,
                       block_q_bwd, block_k_bwd, interpret)
    # [B, L, H, D] -> [B, H, L, D] for the kernels; the transposes sit outside
    # the custom_vjp so their adjoints are handled by XLA
    o = _flash(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2), cfg)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_with_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                             causal: bool = True, q_offset: int = 0,
                             k_offset: int = 0,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             block_q_bwd: Optional[int] = None,
                             block_k_bwd: Optional[int] = None,
                             interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp of the scaled scores: ``(o [B, L, H, D], lse [B, H, L]
    float32)``.

    The pair is exactly what blockwise/ring composition needs — partial
    attentions over kv blocks merge as ``out = sum_s o_s * exp(lse_s - M)
    / sum_s exp(lse_s - M)`` — and BOTH outputs are differentiable: the
    lse cotangent folds into the same backward kernels as a delta shift
    (see ``_backward``), so ``ops.attention.ring_attention`` gets exact
    gradients through the merge.  Fully-masked rows report lse 0 (finite
    sentinel) and o exactly 0, matching ``flash_attention``.
    """
    cfg = _make_config(q, k, causal, q_offset, k_offset, block_q, block_k,
                       block_q_bwd, block_k_bwd, interpret)
    o, lse = _flash_lse(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), cfg)
    return jnp.swapaxes(o, 1, 2), lse


def _make_config(q, k, causal, q_offset, k_offset, block_q, block_k,
                 block_q_bwd, block_k_bwd, interpret) -> _Config:
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lq, lk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    # forward defaults (v5e device-time sweep, 2026-07-30, fwd+bwd with all
    # grads live): one full-length block when the whole [Lq, Lk] score tile
    # fits scoped vmem (14% faster than (512, 1024) at 2k — no online
    # correction passes, no grid overhead), (1024, 1024) above that (5%
    # faster than (512, 1024) at 8k; [2048, 2048] f32 scores OOM at 8k+)
    if block_q is None:
        block_q = lq if (lq <= 2048 and lk <= 2048) else 1024
    if block_k is None:
        block_k = lk if (lq <= 2048 and lk <= 2048) else 1024
    if block_q_bwd is None and block_k_bwd is None:
        # backward defaults aim for the FUSED single-pass backward kernel
        # (one s/p recompute instead of two — measured 25-30% off the whole
        # fwd+bwd step on v5e): first the single-block wide tier (only
        # reachable when the forward already runs full-length blocks, i.e.
        # Lq = Lk <= 2048 — see the _fused_bwd_ok tier note), then
        # (1024, 1024), degrading to (512, 1024), (512, 512) and finally
        # the two-kernel path with forward-inherited blocks as Lq * D
        # grows (see _fused_bwd_ok)
        if _fused_q_chunks(lq, d, min(block_q, 2048), min(block_k, 2048), lk):
            dq_q = dkv_q = min(block_q, 2048)
            dq_k = dkv_k = min(block_k, 2048)
        elif _fused_q_chunks(lq, d, min(block_q, 1024), min(block_k, 1024), lk):
            dq_q = dkv_q = min(block_q, 1024)
            dq_k = dkv_k = min(block_k, 1024)
        elif _fused_q_chunks(lq, d, min(block_q, 512), min(block_k, 1024), lk):
            dq_q = dkv_q = min(block_q, 512)
            dq_k = dkv_k = min(block_k, 1024)
        elif _fused_q_chunks(lq, d, min(block_q, 512), min(block_k, 512), lk):
            dq_q = dkv_q = min(block_q, 512)
            dq_k = dkv_k = min(block_k, 512)
        else:
            dq_q = dkv_q = block_q
            dq_k = dkv_k = block_k
    else:
        dq_q = dkv_q = block_q_bwd if block_q_bwd is not None else block_q
        dq_k = dkv_k = block_k_bwd if block_k_bwd is not None else block_k
    bq, bk = _pick_block(block_q, lq), _pick_block(block_k, lk)
    bq_dq, bk_dq = _pick_block(dq_q, lq), _pick_block(dq_k, lk)
    bq_kv, bk_kv = _pick_block(dkv_q, lq), _pick_block(dkv_k, lk)
    for name, blk, length in (("block_q", bq, lq), ("block_k", bk, lk),
                              ("block_q_dq", bq_dq, lq), ("block_k_dq", bk_dq, lk),
                              ("block_q_bwd", bq_kv, lq), ("block_k_bwd", bk_kv, lk)):
        # Mosaic tiling: the sublane block dim must be 8-divisible or span
        # the whole array dim (interpret mode is lenient, but keep semantics
        # identical so CPU tests catch what TPU would reject)
        if blk % 8 != 0 and blk != length:
            raise ValueError(
                f"no Mosaic-legal {name} for sequence length {length}: "
                f"largest fitting divisor is {blk}, which is neither "
                f"8-divisible nor the full length; pad the sequence or use "
                f"impl='dense'")
    return _Config(causal=bool(causal), q_offset=int(q_offset), k_offset=int(k_offset),
                   block_q=bq, block_k=bk, block_q_dq=bq_dq, block_k_dq=bk_dq,
                   block_q_bwd=bq_kv, block_k_bwd=bk_kv,
                   interpret=bool(interpret))
