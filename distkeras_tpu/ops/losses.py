"""Loss registry with Keras-style string names.

Reference parity: the reference passed Keras loss names (strings) into
``Trainer(model, loss='categorical_crossentropy', ...)`` and compiled them
into the worker's model (``workers.py :: Worker.prepare_model``).  Here each
name maps to a pure ``loss(logits_or_preds, labels) -> scalar`` function
that jit-compiles and differentiates cleanly on TPU.

All losses reduce with a mean over the batch so gradient magnitudes are
batch-size invariant (required for the window/commit algebra of the
distributed trainers to match the reference's per-batch semantics).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


# logits-size ceiling for the UNchunked CE path: below this the [rows, V]
# f32 logits (plus cotangent) fit HBM comfortably and the dense form beats
# the chunked lax.map — measured on the 2k hd128 train leg (v5e device
# time, 2026-07-31): dense 31.8ms/step vs 32.7 chunked-2048 (the map's
# sequential DUS accumulation plus the checkpoint's extra forward matmul
# cost MORE than the extra HBM traffic of materializing 537MB of logits).
# Above the ceiling (e.g. the 32k leg's 1GB logits) chunking still wins —
# it exists for memory, and there it also measures faster.
_DENSE_CE_BYTES = 640 * 1024 * 1024
_DEFAULT_CHUNK_ROWS = 2048  # chunk size target when the policy must chunk


def _pick_chunks(rows: int, vocab: int, target_rows: Optional[int]) -> int:
    """Chunk count with the largest chunk size that divides ``rows`` and
    stays <= ``target_rows``.  One dense chunk when the full [rows, V] f32
    logits stay under ``_DENSE_CE_BYTES`` (measured faster — see above;
    the ceiling applies only on the DEFAULT policy ``target_rows=None`` —
    an explicit ``chunk_rows`` is a caller's memory bound and is honored
    strictly) or when ``rows`` factorizes awkwardly (e.g. prime ``rows``,
    where the only fitting divisor would mean near-per-row chunks and a
    long sequential ``lax.map``) — materializing the logits once beats
    serializing thousands of tiny matmuls."""
    if target_rows is None:
        if rows * vocab * 4 <= _DENSE_CE_BYTES:
            return 1
        target_rows = _DEFAULT_CHUNK_ROWS
    if rows <= target_rows:
        return 1
    for n in range(2, rows + 1):
        if rows % n == 0 and rows // n <= target_rows:
            if rows // n >= max(8, target_rows // 8):
                return n
            break  # divisors only get smaller from here
    return 1


def unembed_cross_entropy(hidden: jnp.ndarray, table: jnp.ndarray,
                          targets: jnp.ndarray, chunk_rows: Optional[int] = None,
                          compute_dtype: Optional[jnp.dtype] = jnp.bfloat16) -> jnp.ndarray:
    """Fused unembed + softmax CE whose logits stay bounded: chunked when
    they would be large, dense when materializing them once is faster.

    ``hidden`` [B, L, E] (final-norm output), ``table`` [V, E] (the tied
    embedding matrix), ``targets`` [B, L] int.  Returns per-position CE
    [B, L] in float32.  ``chunk_rows=None`` (default) picks the measured
    policy below; an EXPLICIT ``chunk_rows`` is treated as a hard memory
    bound — the dense fast path is never taken over it.

    Two wins over ``head() -> optax CE`` on TPU:

    - the unembed matmul runs in ``compute_dtype`` (default bfloat16 — full
      MXU rate) with float32 accumulation via ``preferred_element_type``,
      instead of the float32 x float32 matmul ``embed.attend`` issues;
    - when the [B*L, V] float32 logits would exceed ``_DENSE_CE_BYTES``
      they are computed ``chunk_rows`` rows at a time inside a ``lax.map``
      whose body is ``jax.checkpoint``'d, so the backward recomputes each
      chunk instead of keeping ~1 GB of logits (+ another in the
      cotangent) live across the whole backward.  Peak logit memory drops
      from O(B*L*V) to O(chunk_rows * V).  Below the ceiling the dense
      single-matmul form runs (measured faster; see ``_pick_chunks``).

    ``compute_dtype=None`` keeps the inputs' dtype (exact-parity testing).
    """
    b, l, e = hidden.shape
    rows = b * l
    h2 = hidden.reshape(rows, e)
    t2 = targets.reshape(rows).astype(jnp.int32)
    if compute_dtype is not None:
        h2 = h2.astype(compute_dtype)
        table = table.astype(compute_dtype)

    def chunk_ce(hc, tc):
        logits = lax.dot_general(hc, table, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [rows_c, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return lse - tgt

    n_chunks = _pick_chunks(rows, table.shape[0], chunk_rows)
    if n_chunks == 1:
        ce = chunk_ce(h2, t2)
    else:
        body = jax.checkpoint(chunk_ce, prevent_cse=False)
        ce = lax.map(lambda args: body(*args),
                     (h2.reshape(n_chunks, rows // n_chunks, e),
                      t2.reshape(n_chunks, rows // n_chunks)))
    return ce.reshape(b, l)


def lm_token_cross_entropy(module, params, tokens: jnp.ndarray, targets: jnp.ndarray,
                           pos_offset=0, chunk_rows: Optional[int] = None,
                           compute_dtype: Optional[jnp.dtype] = jnp.bfloat16) -> jnp.ndarray:
    """Per-position next-token CE [B, L] for a tied-embedding LM.

    The single home of the fused-loss wiring contract: ``module`` must
    expose a ``hidden`` method (forward up to and including the final norm,
    no unembed) and keep its tied unembedding table at
    ``params['embed']['embedding']`` — i.e. ``models.transformer
    .TransformerLM``.  Used by ``parallel/lm.py``, the bench, and the
    parity tests so the pairing lives in exactly one place.
    """
    h = module.apply({"params": params}, tokens, pos_offset=pos_offset,
                     method="hidden")
    return unembed_cross_entropy(h, params["embed"]["embedding"],
                                 targets.astype(jnp.int32),
                                 chunk_rows=chunk_rows, compute_dtype=compute_dtype)


def categorical_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax CE with one-hot labels (labels shape [..., num_classes])."""
    return optax.softmax_cross_entropy(logits, labels).mean()


def sparse_categorical_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax CE with integer labels (labels shape [...])."""
    labels = labels.astype(jnp.int32)
    if labels.ndim == logits.ndim:  # tolerate a trailing singleton label dim
        labels = labels.squeeze(-1)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def binary_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Sigmoid CE on logits (numerically stable; do NOT pre-sigmoid)."""
    return optax.sigmoid_binary_cross_entropy(logits, labels.astype(logits.dtype)).mean()


def mean_squared_error(preds: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.square(preds - targets.astype(preds.dtype)))


def mean_absolute_error(preds: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.abs(preds - targets.astype(preds.dtype)))


_LOSSES: Dict[str, LossFn] = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
}


def get_loss(name_or_fn) -> LossFn:
    """Resolve a Keras-style loss name (or pass a callable through)."""
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _LOSSES[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown loss {name_or_fn!r}; known: {sorted(_LOSSES)}") from None


def register_loss(name: str, fn: LossFn) -> None:
    _LOSSES[name] = fn
