"""Loss registry with Keras-style string names.

Reference parity: the reference passed Keras loss names (strings) into
``Trainer(model, loss='categorical_crossentropy', ...)`` and compiled them
into the worker's model (``workers.py :: Worker.prepare_model``).  Here each
name maps to a pure ``loss(logits_or_preds, labels) -> scalar`` function
that jit-compiles and differentiates cleanly on TPU.

All losses reduce with a mean over the batch so gradient magnitudes are
batch-size invariant (required for the window/commit algebra of the
distributed trainers to match the reference's per-batch semantics).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp
import optax

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def categorical_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax CE with one-hot labels (labels shape [..., num_classes])."""
    return optax.softmax_cross_entropy(logits, labels).mean()


def sparse_categorical_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax CE with integer labels (labels shape [...])."""
    labels = labels.astype(jnp.int32)
    if labels.ndim == logits.ndim:  # tolerate a trailing singleton label dim
        labels = labels.squeeze(-1)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def binary_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Sigmoid CE on logits (numerically stable; do NOT pre-sigmoid)."""
    return optax.sigmoid_binary_cross_entropy(logits, labels.astype(logits.dtype)).mean()


def mean_squared_error(preds: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.square(preds - targets.astype(preds.dtype)))


def mean_absolute_error(preds: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.abs(preds - targets.astype(preds.dtype)))


_LOSSES: Dict[str, LossFn] = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
}


def get_loss(name_or_fn) -> LossFn:
    """Resolve a Keras-style loss name (or pass a callable through)."""
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _LOSSES[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown loss {name_or_fn!r}; known: {sorted(_LOSSES)}") from None


def register_loss(name: str, fn: LossFn) -> None:
    _LOSSES[name] = fn
