"""Optimizer registry with Keras-style names, backed by optax.

Reference parity: the reference passed a Keras optimizer (string name or
object) as the *worker optimizer* into every trainer; the parameter server
applied raw deltas with no optimizer of its own.  The same split holds
here: these optax transforms drive the *local* (per-replica) SGD steps,
while the center/commit updates in ``distkeras_tpu.algorithms`` are plain
arithmetic, exactly like the reference PS.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

# learning_rate below may be a float OR an optax schedule (step -> lr);
# optax optimizers accept both natively, so trainers get LR schedules for
# free by passing get_schedule(...) as learning_rate
ScalarOrSchedule = Union[float, Callable]


def get_optimizer(spec: Union[str, optax.GradientTransformation],
                  learning_rate: ScalarOrSchedule = 0.01,
                  momentum: Optional[float] = None) -> optax.GradientTransformation:
    """Resolve a Keras-style optimizer name into an optax transform.

    ``spec`` may already be an ``optax.GradientTransformation`` (returned
    unchanged), or one of: ``sgd``, ``momentum``, ``nesterov``, ``adam``,
    ``adamw``, ``adamax``, ``nadam``, ``adagrad``, ``rmsprop``,
    ``adadelta``, ``lamb``, ``lars``, ``lion``.
    """
    if isinstance(spec, optax.GradientTransformation):
        return spec
    name = spec.lower()
    # None means "use this optimizer's conventional default"; an explicit
    # momentum=0.0 must be honored, so no falsy-zero shortcuts here
    mom = 0.9 if momentum is None else momentum
    if name == "sgd":
        return optax.sgd(learning_rate)
    if name == "momentum":
        return optax.sgd(learning_rate, momentum=mom)
    if name == "nesterov":
        return optax.sgd(learning_rate, momentum=mom, nesterov=True)
    simple = {"adam": optax.adam, "adamw": optax.adamw, "adamax": optax.adamax,
              "nadam": optax.nadam, "adagrad": optax.adagrad,
              "rmsprop": optax.rmsprop, "adadelta": optax.adadelta,
              "lamb": optax.lamb, "lars": optax.lars, "lion": optax.lion}
    if name in simple:
        return simple[name](learning_rate)
    raise ValueError(f"unknown optimizer {spec!r}; known: sgd, momentum, "
                     f"nesterov, {', '.join(sorted(simple))}")


def get_schedule(name: str, learning_rate: float, decay_steps: int, *,
                 warmup_steps: int = 0, end_value: float = 0.0,
                 decay_rate: float = 0.96) -> Callable:
    """Build an optax learning-rate schedule by Keras-ish name.

    ``cosine`` | ``linear`` | ``exponential`` | ``constant`` — each
    optionally preceded by ``warmup_steps`` of linear warmup from 0.
    Pass the result as any trainer's ``learning_rate=``; ``decay_steps``
    counts optimizer updates (batches), not epochs.

    Note: AEASGD/EAMSGD additionally need their scalar elastic coupling
    (alpha = rho * lr); give those trainers a scalar ``learning_rate`` and
    put the schedule inside an optax ``worker_optimizer`` object instead.
    """
    n = name.lower()
    if n == "cosine":
        sched = optax.cosine_decay_schedule(learning_rate, decay_steps,
                                            alpha=end_value / learning_rate
                                            if learning_rate else 0.0)
    elif n == "linear":
        sched = optax.linear_schedule(learning_rate, end_value, decay_steps)
    elif n == "exponential":
        sched = optax.exponential_decay(learning_rate, decay_steps, decay_rate,
                                        end_value=end_value or None)
    elif n == "constant":
        sched = optax.constant_schedule(learning_rate)
    else:
        raise ValueError(f"unknown schedule {name!r}; known: cosine, linear, "
                         "exponential, constant")
    if warmup_steps:
        warm = optax.linear_schedule(0.0, learning_rate, warmup_steps)
        sched = optax.join_schedules([warm, sched], [warmup_steps])
    return sched
