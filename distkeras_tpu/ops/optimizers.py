"""Optimizer registry with Keras-style names, backed by optax.

Reference parity: the reference passed a Keras optimizer (string name or
object) as the *worker optimizer* into every trainer; the parameter server
applied raw deltas with no optimizer of its own.  The same split holds
here: these optax transforms drive the *local* (per-replica) SGD steps,
while the center/commit updates in ``distkeras_tpu.algorithms`` are plain
arithmetic, exactly like the reference PS.
"""

from __future__ import annotations

from typing import Optional, Union

import optax


def get_optimizer(spec: Union[str, optax.GradientTransformation], learning_rate: float = 0.01,
                  momentum: Optional[float] = None) -> optax.GradientTransformation:
    """Resolve a Keras-style optimizer name into an optax transform.

    ``spec`` may already be an ``optax.GradientTransformation`` (returned
    unchanged), or one of: ``sgd``, ``momentum``, ``nesterov``, ``adam``,
    ``adamw``, ``adagrad``, ``rmsprop``, ``adadelta``.
    """
    if isinstance(spec, optax.GradientTransformation):
        return spec
    name = spec.lower()
    # None means "use this optimizer's conventional default"; an explicit
    # momentum=0.0 must be honored, so no falsy-zero shortcuts here
    mom = 0.9 if momentum is None else momentum
    if name == "sgd":
        return optax.sgd(learning_rate)
    if name == "momentum":
        return optax.sgd(learning_rate, momentum=mom)
    if name == "nesterov":
        return optax.sgd(learning_rate, momentum=mom, nesterov=True)
    if name == "adam":
        return optax.adam(learning_rate)
    if name == "adamw":
        return optax.adamw(learning_rate)
    if name == "adagrad":
        return optax.adagrad(learning_rate)
    if name == "rmsprop":
        return optax.rmsprop(learning_rate)
    if name == "adadelta":
        return optax.adadelta(learning_rate)
    raise ValueError(f"unknown optimizer {spec!r}")
