"""Weight-only int8 quantization for inference.

No reference counterpart (the reference serves full-precision Keras models;
SURVEY §2.15) — this is TPU-native headroom for the serving path: matmul
weights are stored in HBM as int8 with a float32 scale per output channel
and dequantized to the compute dtype inside the compiled program, where XLA
fuses the ``q.astype(dtype) * scale`` into the consumer.  Inference at
batch sizes below the MXU's arithmetic-intensity knee is HBM-bound on
weight reads, so halving (vs bf16) or quartering (vs f32) the weight bytes
moves the bound directly.

Scheme: symmetric per-channel. For a kernel ``w`` of any rank, the LAST
axis is the output-channel axis (flax convention: Dense [in, out], Conv
[kh, kw, cin, cout], DenseGeneral qkv [e, 3, h, dh] — reduced over all
axes but the last):

    scale[c] = max(|w[..., c]|) / 127
    q[..., c] = round(w[..., c] / scale[c])  in [-127, 127]

Leaves are quantized only when they are matmul-shaped (ndim >= 2, named
``kernel`` or ``embedding``) and large enough to matter
(``min_size`` elements); biases, norms scales, and tiny tensors stay in
their original dtype — they are a rounding error of the HBM traffic and
quantizing them costs accuracy for nothing.

Usage:
    qp = quantize_params(model.params)            # pytree with QTensor leaves
    params = dequantize_params(qp)                # inside jit: fused dequant
    ModelPredictor(model, quantize=True)          # transparent serving path
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class QTensor(NamedTuple):
    """int8 values + per-output-channel float32 scale (broadcastable)."""

    q: jnp.ndarray       # int8, same shape as the original weight
    scale: jnp.ndarray   # float32, shape (1, ..., 1, channels)

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _is_qtensor(x) -> bool:
    return isinstance(x, QTensor)


def quantize_leaf(w: jnp.ndarray) -> QTensor:
    """Symmetric per-channel int8 over the last (output-channel) axis."""
    w = jnp.asarray(w, jnp.float32)
    axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def _should_quantize(path, leaf, min_size: int) -> bool:
    names = {getattr(p, "key", getattr(p, "name", None)) for p in path}
    is_weight = bool(names & {"kernel", "embedding"})
    return (is_weight and getattr(leaf, "ndim", 0) >= 2
            and leaf.size >= min_size)


def quantize_params(params: Any, min_size: int = 4096) -> Any:
    """Quantize the matmul weights of a param tree; other leaves pass
    through unchanged.  Returns a tree with ``QTensor`` leaves."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: quantize_leaf(leaf)
        if _should_quantize(path, leaf, min_size) else leaf, params)


def dequantize_params(qparams: Any, dtype=jnp.float32) -> Any:
    """Rebuild a dense param tree (jit-safe: inside a compiled program the
    dequant multiply fuses into each weight's consumer)."""
    return jax.tree.map(
        lambda l: l.dequantize(dtype) if _is_qtensor(l) else l,
        qparams, is_leaf=_is_qtensor)


def quantization_error(params: Any, qparams: Any) -> float:
    """Max relative per-tensor L2 error across quantized leaves (sanity
    metric: int8 per-channel is typically < 1%)."""
    errs = []

    def visit(orig, q):
        if _is_qtensor(q):
            w = np.asarray(orig, np.float64)
            d = np.asarray(q.dequantize(jnp.float32), np.float64)
            denom = np.linalg.norm(w) or 1.0
            errs.append(np.linalg.norm(w - d) / denom)

    # tree.map flattens against params' structure and extracts the matching
    # qparams subtree per leaf, so QTensors arrive whole as `q`
    jax.tree.map(visit, params, qparams)
    return float(max(errs)) if errs else 0.0


def param_nbytes(tree: Any) -> int:
    """Total stored bytes of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree.leaves(tree, is_leaf=_is_qtensor):
        if _is_qtensor(leaf):
            total += leaf.q.size * 1 + leaf.scale.size * 4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
