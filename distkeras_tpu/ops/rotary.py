"""Rotary position embeddings (RoPE, Su et al. 2021).

No reference counterpart (the reference predates transformers) — the
modern positional default for the flagship LM, next to the learned table:
instead of adding a position vector to the residual stream, each
query/key head vector is ROTATED by an angle proportional to its absolute
position, so the attention score <R(p_q)q, R(p_k)k> depends only on the
relative offset p_q - p_k.  TPU-friendly by construction: pure elementwise
cos/sin math that XLA fuses into the projection epilogues, no table in
HBM, and nothing length-bound — the same weights serve any sequence
length (``max_seq_len`` remains only a cache-sizing bound for decoding).

Convention: NeoX split-half — the head dim splits into two halves that
rotate as (x1, x2) -> (x1 cos - x2 sin, x2 cos + x1 sin), with
frequencies base^(-2i/D).  Rotation runs in float32 (angle precision at
large positions) and casts back to the input dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_rotate(x: jnp.ndarray, positions: jnp.ndarray,
                base: float = 10000.0) -> jnp.ndarray:
    """Rotate ``x`` [B, L, H, D] by absolute ``positions`` [L].

    Works for any head count (queries and grouped GQA keys alike) and any
    even D.  Position 0 is the identity rotation, so un-offset prefixes
    are unchanged and cached K rows (stored rotated) stay valid forever —
    rotation depends only on the row's own absolute position.
    """
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    half = d // 2
    freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freq[None, :]   # [L, half]
    cos = jnp.cos(ang)[None, :, None, :]                           # [1, L, 1, half]
    sin = jnp.sin(ang)[None, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1).astype(x.dtype)
