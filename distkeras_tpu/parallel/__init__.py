"""Parallelism layer: device meshes, collective update rules, window engine."""

from distkeras_tpu.parallel.mesh import create_mesh  # noqa: F401
from distkeras_tpu.parallel.algorithms import (  # noqa: F401
    Algorithm,
    AdagAlgorithm,
    DownpourAlgorithm,
    ElasticAlgorithm,
    DynSGDAlgorithm,
    NoCommitAlgorithm,
)
from distkeras_tpu.parallel.engine import ReplicaState, WindowEngine  # noqa: F401
