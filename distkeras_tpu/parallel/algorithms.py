"""Distributed-optimization update rules as pure collective functions.

Reference parity (SURVEY.md §2.5–2.9, §3.1, §3.5): each reference
algorithm was a (worker, parameter-server) pair exchanging weight deltas
over TCP — ``commit`` applied a delta to the center under a mutex, ``pull``
fetched fresh center weights.  TPU-native re-expression: every replica runs
``communication_window`` local minibatch steps, then the algorithm's
*commit rule* runs as one XLA collective over the ``replica`` mesh axis.
The hub-and-spoke socket round-trip collapses into a ``psum`` on ICI.

Asynchrony note (the SURVEY §7 "hard part"): TPU collectives are
synchronous, so the async protocols are realized as their *deterministic
synchronous serializations* — every replica commits once per window, and
staleness (DynSGD) is modeled by a fixed round-robin commit order within
the window (replica r sees r prior commits, staleness = r).  This keeps
the reference's update algebra bit-for-bit testable (see
tests/test_algorithms.py) while removing the GIL-serialized mutex hub.

Each rule is a pure function ``(center, local, extra) -> (center, local,
extra)`` evaluated under ``shard_map``; ``center`` is mesh-invariant
(replicated), ``local``/``extra`` are per-replica.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax


def _tree_psum(tree: Any, axis_name: str) -> Any:
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


class Algorithm:
    """Commit-rule interface. Subclasses are stateless; per-replica state
    beyond the weights goes in the ``extra`` pytree."""

    name: str = "base"

    def init_extra(self, params: Any) -> Dict[str, Any]:
        return {}

    def window_commit(self, center: Any, local: Any, extra: Dict[str, Any],
                      axis_name: str) -> tuple:
        raise NotImplementedError


class AdagAlgorithm(Algorithm):
    """ADAG — Asynchronous Distributed Adaptive Gradients (arXiv:1611.04581).

    Reference: ``ADAGParameterServer.handle_commit`` scaled each incoming
    windowed delta by 1/num_workers before adding it to the center
    (staleness-compensating normalization).  Synchronous form: the center
    advances by the *replica-mean* accumulated delta:

        center' = center + (1/R) * sum_r (local_r - center)
        local'  = center'            (the post-commit pull)
    """

    name = "adag"

    def window_commit(self, center, local, extra, axis_name):
        num = lax.psum(1, axis_name)
        delta = jax.tree.map(lambda l, c: l - c, local, center)
        mean_delta = jax.tree.map(lambda d: lax.psum(d, axis_name) / num, delta)
        new_center = jax.tree.map(lambda c, d: c + d, center, mean_delta)
        return new_center, new_center, extra


class DownpourAlgorithm(Algorithm):
    """DOWNPOUR (Dean et al. 2012).

    Reference: workers accumulate raw gradient updates for
    ``communication_window`` batches and commit the summed delta; the PS
    (``DeltaParameterServer``) adds deltas *unscaled*.  Synchronous form:

        center' = center + sum_r (local_r - center)
        local'  = center'
    """

    name = "downpour"

    def window_commit(self, center, local, extra, axis_name):
        delta = jax.tree.map(lambda l, c: l - c, local, center)
        sum_delta = _tree_psum(delta, axis_name)
        new_center = jax.tree.map(lambda c, d: c + d, center, sum_delta)
        return new_center, new_center, extra


class ElasticAlgorithm(Algorithm):
    """AEASGD / EAMSGD — (momentum) elastic averaging SGD (arXiv:1412.6651).

    Reference worker window step (``AEASGDWorker.train``):

        elastic_diff = alpha * (local - center)   # alpha = rho * lr
        local  -= elastic_diff                    # spring pulls local inward
        commit(elastic_diff)                      # PS: center += elastic_diff

    Synchronous form: the center collects every replica's elastic force in
    one psum. Locals stay divergent — the exploration property of EASGD.
    EAMSGD differs only in the *local* optimizer (momentum/Nesterov), which
    lives in the engine's optax state, so both share this commit rule.
    """

    name = "elastic"

    def __init__(self, rho: float, learning_rate: float):
        self.alpha = float(rho) * float(learning_rate)

    def window_commit(self, center, local, extra, axis_name):
        ediff = jax.tree.map(lambda l, c: self.alpha * (l - c), local, center)
        new_local = jax.tree.map(lambda l, e: l - e, local, ediff)
        sum_ediff = _tree_psum(ediff, axis_name)
        new_center = jax.tree.map(lambda c, e: c + e, center, sum_ediff)
        return new_center, new_local, extra


class DynSGDAlgorithm(Algorithm):
    """DynSGD — staleness-aware dynamic learning rate (arXiv:1611.04581).

    Reference: ``DynSGDParameterServer.handle_commit`` kept a global update
    clock and scaled each delta by ``1/(staleness+1)`` where staleness =
    commits applied since that worker's pull.

    This sync form is the exact serialization of one specific async
    schedule — *all replicas pull at the window start, train, then commit
    in rank order; everyone re-pulls after the full window*:

    - replica r's committed delta is ``local_r - center`` against the
      center it PULLED (the async worker's delta is relative to its pull
      point, NOT the center at commit time — reference §3.1);
    - committing r-th means r commits landed since r's pull, so the hub
      scales by ``1/(r+1)``;

        c_{r+1} = c_r + (local_r - c_0) / (r + 1)
      ⇒ center' = c_0 + sum_r (local_r - c_0) / (r + 1)

    which is the psum below.  Note rank r is *permanently* scaled by
    1/(r+1) under this schedule — real async runs randomize commit order,
    this serialization fixes it for determinism.  The equivalence against
    the async hub under the same schedule is proven by
    ``tests/test_algorithms.py :: test_dynsgd_sync_matches_async_hub``.
    """

    name = "dynsgd"

    def window_commit(self, center, local, extra, axis_name):
        rank = lax.axis_index(axis_name)
        scale = 1.0 / (rank.astype(jnp.float32) + 1.0)
        scaled = jax.tree.map(lambda l, c: (l - c) * scale, local, center)
        sum_scaled = _tree_psum(scaled, axis_name)
        new_center = jax.tree.map(lambda c, d: c + d, center, sum_scaled)
        return new_center, new_center, extra


class NoCommitAlgorithm(Algorithm):
    """No communication — replicas train independently for the whole run.

    Backs ``AveragingTrainer`` (average locals once at the end) and
    ``EnsembleTrainer`` (return all locals), reference §2.2/2.3.
    """

    name = "nocommit"

    def window_commit(self, center, local, extra, axis_name):
        return center, local, extra
