"""The window engine: compiled replacement for the Trainer/Worker/PS loop.

Reference call stack being replaced (SURVEY.md §3.1): driver starts a PS
thread, ships pickled workers to Spark executors, each worker loops
``model.train_on_batch`` and every ``communication_window`` batches does a
socket ``commit``/``pull`` round-trip to the driver.

TPU-native shape: ONE jitted function per epoch —

    shard_map over the 'replica' mesh axis of:
        lax.scan over windows of:
            lax.scan over the window's minibatches:  local optax step
            algorithm.window_commit(...):            psum collective

The whole epoch is a single XLA program: no Python in the hot loop, no
host round-trips, the commit is an ICI allreduce fused into the step.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import observability as obs
from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.parallel.algorithms import Algorithm


@struct.dataclass
class ReplicaState:
    """Global training state. ``local``/``opt_state``/``extra`` carry a
    leading replica axis (sharded over the mesh); ``center`` is replicated —
    it is the PS's "center variable" of the reference, now mesh-invariant."""

    center: Any
    local: Any
    opt_state: Any
    extra: Any
    step: jnp.ndarray


def _ensure_varying(x, axis_name: str):
    """Mark ``x`` as varying over ``axis_name`` unless it already is."""
    if axis_name in jax.typeof(x).vma:
        return x
    return lax.pcast(x, (axis_name,), to="varying")


def make_minibatch_step(apply_fn: Callable, loss: Callable,
                        optimizer: optax.GradientTransformation,
                        with_rng: bool = False) -> Callable:
    """One ``train_on_batch`` equivalent: value_and_grad + optax update.

    ``with_rng=True``: ``apply_fn`` is a train-mode forward taking a PRNG
    key (``ModelSpec.train_apply_fn``) and each scanned batch is
    ``(x, y, key)`` — the key rides the batch stream, NOT the carry, so
    state layouts (and checkpoint formats) are identical either way.
    """
    if with_rng:
        def loss_of(params, batch):
            return loss(apply_fn(params, batch[0], batch[2]), batch[1])
    else:
        def loss_of(params, batch):
            return loss(apply_fn(params, batch[0]), batch[1])

    def step(carry, batch):
        params, opt_state = carry
        loss_val, grads = jax.value_and_grad(loss_of)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss_val

    return step


def scan_epoch_fn(apply_fn: Callable, loss: Callable,
                  optimizer: optax.GradientTransformation,
                  with_rng: bool = False) -> Callable:
    """Single-device compiled epoch: lax.scan over [num_batches, bs, ...].

    Backs ``SingleTrainer`` — the reference's minimal path (SURVEY §3.2)
    with the per-row partition iterator replaced by one device transfer
    and one XLA program per epoch.  ``with_rng``: see
    :func:`make_minibatch_step`; the epoch then takes per-batch keys
    [num_batches, 2] as a fourth array.
    """
    mini = make_minibatch_step(apply_fn, loss, optimizer, with_rng=with_rng)

    if with_rng:
        def epoch(params, opt_state, xs, ys, keys):
            (params, opt_state), losses = lax.scan(
                mini, (params, opt_state), (xs, ys, keys))
            return params, opt_state, losses
    else:
        def epoch(params, opt_state, xs, ys):
            (params, opt_state), losses = lax.scan(mini, (params, opt_state), (xs, ys))
            return params, opt_state, losses

    return jax.jit(epoch, donate_argnums=(0, 1))


class WindowEngine:
    """Builds and runs the sharded window-training program for one
    (model spec, loss, optimizer, algorithm, mesh) combination."""

    def __init__(self, spec: ModelSpec, loss: Callable,
                 optimizer: optax.GradientTransformation, algorithm: Algorithm,
                 mesh: Mesh, axis_name: str = "replica", window: int = 1):
        spec.reject_silent_aux("WindowEngine")
        self.spec = spec
        self.loss = loss
        self.optimizer = optimizer
        self.algorithm = algorithm
        self.mesh = mesh
        self.axis_name = axis_name
        self.window = int(window)
        self.num_replicas = mesh.shape[axis_name]
        # dropout-bearing specs train through the rng-taking forward; the
        # per-batch keys ride the scanned data stream (state layout — and
        # therefore checkpoints — identical either way)
        self.needs_rng = spec.needs_rng
        self._apply = spec.train_apply_fn() if self.needs_rng else spec.apply_fn()
        self._epoch_fns: Dict[int, Callable] = {1: self._build_epoch_fn()}

    # -- state ----------------------------------------------------------------
    def _state_specs(self) -> ReplicaState:
        return ReplicaState(
            center=P(),
            local=P(self.axis_name),
            opt_state=P(self.axis_name),
            extra=P(self.axis_name),
            step=P(),
        )

    def _state_shardings(self) -> ReplicaState:
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._state_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )

    def init_state(self, model: Model, divergent_seeds: Optional[Sequence[int]] = None) -> ReplicaState:
        """Replicate the model into per-replica locals + a shared center.

        ``divergent_seeds`` gives each replica its own re-initialization
        (EnsembleTrainer's decorrelation; reference ``uniform_weights``).
        """
        r = self.num_replicas
        center = jax.tree.map(np.asarray, model.params)
        if divergent_seeds is not None:
            if len(divergent_seeds) != r:
                raise ValueError(f"need {r} seeds, got {len(divergent_seeds)}")
            locals_list = [
                jax.tree.map(np.asarray, self.spec.init_params(seed=s)) for s in divergent_seeds
            ]
        else:
            locals_list = [center] * r
        local = jax.tree.map(lambda *xs: np.stack(xs), *locals_list)
        opt0 = self.optimizer.init(model.params)
        opt_np = jax.tree.map(np.asarray, opt0)
        opt_state = jax.tree.map(lambda x: np.stack([x] * r), opt_np)
        extra0 = self.algorithm.init_extra(model.params)
        extra = jax.tree.map(lambda x: np.stack([np.asarray(x)] * r), extra0)
        state = ReplicaState(center=center, local=local, opt_state=opt_state,
                             extra=extra, step=np.zeros((), np.int32))
        return self.shard_state(state)

    def shard_state(self, state: ReplicaState) -> ReplicaState:
        """Place a (host or restored-from-checkpoint) state onto the mesh
        with this engine's shardings.

        Multi-process (``jax.distributed`` initialized, mesh spanning
        hosts): every process holds the same full host-side state and
        contributes just its addressable shards via
        ``make_array_from_callback`` — ``device_put`` cannot place onto
        non-addressable devices."""
        shardings = self._state_shardings()
        if jax.process_count() == 1:
            return jax.device_put(state, shardings)

        def put(subtree, sharding):
            # one sharding per ReplicaState FIELD (device_put broadcasts
            # prefix trees itself; make_array_from_callback does not)
            def leaf(l):
                host = np.asarray(l)
                return jax.make_array_from_callback(
                    host.shape, sharding, lambda idx, h=host: h[idx])

            return jax.tree.map(leaf, subtree)

        return ReplicaState(
            center=put(state.center, shardings.center),
            local=put(state.local, shardings.local),
            opt_state=put(state.opt_state, shardings.opt_state),
            extra=put(state.extra, shardings.extra),
            step=put(state.step, shardings.step),
        )

    # -- compiled epoch --------------------------------------------------------
    def _build_epoch_fn(self, reps: int = 1) -> Callable:
        """``reps > 1`` compiles ``reps`` passes over the same data into
        ONE program (outer lax.scan) — the steady-state measurement shape:
        per-dispatch host/relay overhead amortizes across every epoch
        instead of dominating each one (the round-2 baseline matrix
        measured ~100ms relay RPCs, not the chip)."""
        algo = self.algorithm
        axis = self.axis_name
        needs_rng = self.needs_rng
        mini = make_minibatch_step(self._apply, self.loss, self.optimizer,
                                   with_rng=needs_rng)

        def shard_fn(state: ReplicaState, xs, ys, keys):
            # per-shard views: strip the leading (sharded) replica axis
            local = jax.tree.map(lambda a: a[0], state.local)
            opt_state = jax.tree.map(lambda a: a[0], state.opt_state)
            extra = jax.tree.map(lambda a: a[0], state.extra)
            center = state.center

            def window_step(carry, window_batches):
                center, local, opt_state, extra = carry
                if needs_rng:
                    wx, wy, wk = window_batches
                    # same base key per batch everywhere, diverged per
                    # replica so the masks differ across workers
                    ridx = lax.axis_index(axis)
                    wk = jax.vmap(lambda kk: jax.random.fold_in(kk, ridx))(wk)
                    batches = (wx, wy, wk)
                else:
                    wx, wy = window_batches
                    batches = (wx, wy)
                (local, opt_state), losses = lax.scan(mini, (local, opt_state), batches)
                center, local, extra = algo.window_commit(center, local, extra, axis)
                # commit rules that reset local to the (mesh-invariant) center
                # change the carry's varying-axes type; cast it back
                local = jax.tree.map(lambda x: _ensure_varying(x, axis), local)
                extra = jax.tree.map(lambda x: _ensure_varying(x, axis), extra)
                mean_loss = lax.pmean(jnp.mean(losses), axis)
                return (center, local, opt_state, extra), mean_loss

            data = (xs, ys, keys) if needs_rng else (xs, ys)
            if reps == 1:
                (center, local, opt_state, extra), window_losses = lax.scan(
                    window_step, (center, local, opt_state, extra), data)
            else:
                def one_pass(carry, _):
                    carry, losses = lax.scan(window_step, carry, data)
                    return carry, losses

                (center, local, opt_state, extra), window_losses = lax.scan(
                    one_pass, (center, local, opt_state, extra), None, length=reps)
                window_losses = window_losses[-1]  # last pass's per-window losses
            num_steps = xs.shape[0] * xs.shape[1] * reps
            new_state = ReplicaState(
                center=center,
                local=jax.tree.map(lambda a: a[None], local),
                opt_state=jax.tree.map(lambda a: a[None], opt_state),
                extra=jax.tree.map(lambda a: a[None], extra),
                step=state.step + jnp.int32(num_steps),
            )
            return new_state, window_losses

        specs = self._state_specs()
        data_spec = P(None, None, axis)
        sharded = jax.shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(specs, data_spec, data_spec, P()),  # keys replicated
            out_specs=(specs, P()),
        )
        return jax.jit(sharded, donate_argnums=(0,))

    def data_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, None, self.axis_name))

    def steady_state_rate(self, state: ReplicaState, xs: np.ndarray, ys: np.ndarray,
                          reps: int = 4, repeat: int = 3) -> float:
        """Measured samples/sec/chip with ``reps`` epochs over ``xs``/``ys``
        inside ONE compiled program — the number that reflects the chip,
        not the per-dispatch relay overhead.  The engine's training state
        is copied per run (the epoch program donates its input), so the
        caller's ``state`` stays usable.  Median of ``repeat`` runs."""
        import time as _time

        self.spec.reject_rng_spec("steady_state_rate")
        fn = self._epoch_fns.get(reps)
        if fn is None:
            fn = self._build_epoch_fn(reps)
            self._epoch_fns[reps] = fn
        xs_d, ys_d = self._place_data(xs, ys)  # multi-process safe
        keys = self._place_keys(np.zeros(xs.shape[:2] + (2,), np.uint32))
        samples = reps * xs.shape[0] * xs.shape[1] * xs.shape[2]

        def fresh():
            return jax.tree.map(jnp.array, state)

        _, losses = fn(fresh(), xs_d, ys_d, keys)
        np.asarray(losses)  # compile + completion barrier (relayed platforms)
        rates = []
        for _ in range(repeat):
            s = fresh()
            t0 = _time.perf_counter()
            _, losses = fn(s, xs_d, ys_d, keys)
            np.asarray(losses)
            rates.append(samples / (_time.perf_counter() - t0))
        return sorted(rates)[len(rates) // 2] / self.num_replicas

    def run_epoch(self, state: ReplicaState, xs: np.ndarray, ys: np.ndarray,
                  keys: Optional[np.ndarray] = None):
        """xs/ys: [num_windows, window, global_batch, ...] host arrays;
        ``keys`` [num_windows, window, 2] uint32 per-batch dropout keys
        (required iff the spec ``needs_rng``).

        Returns (new_state, per-window mean losses as numpy).

        Telemetry (when ``distkeras_tpu.observability`` is enabled):
        dispatch-to-completion time per compiled epoch-chunk program
        (``engine_epoch_seconds`` — the ``np.asarray`` below blocks, so
        the interval IS the program's effective duration incl. dispatch),
        achieved throughput (``engine_samples_per_sec``) and the step
        counter ``engine_steps_total``.
        """
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        with obs.span("engine.run_epoch", windows=int(np.shape(xs)[0]),
                      replicas=self.num_replicas):
            xs_d, ys_d = self._place_data(xs, ys)
            if keys is None:
                # any constant is a valid (unused) threefry key when the spec
                # has no rng need; a real run with needs_rng must pass keys
                if self.needs_rng:
                    raise ValueError("this engine's spec needs per-batch dropout "
                                     "keys; pass keys=[num_windows, window, 2]")
                keys = np.zeros(xs.shape[:2] + (2,), np.uint32)
            keys_d = self._place_keys(np.asarray(keys))
            state, losses = self._epoch_fns[1](state, xs_d, ys_d, keys_d)
            losses = np.asarray(losses)
        if telemetry:
            dt = time.perf_counter() - t0
            num_windows, window, global_batch = (int(d) for d in np.shape(xs)[:3])
            # identity as labels (ARCHITECTURE.md convention): a process
            # with several engines (bench legs, elastic rebuilds) must not
            # overwrite one unlabeled gauge or merge differently-shaped
            # programs into one histogram
            ident = {"model": self.spec.name,
                     "replicas": str(self.num_replicas)}
            obs.histogram("engine_epoch_seconds", **ident).observe(dt)
            obs.counter("engine_steps_total", **ident).inc(num_windows * window)
            obs.gauge("engine_samples_per_sec", **ident).set(
                num_windows * window * global_batch / max(dt, 1e-9))
        return state, losses

    def _place_keys(self, keys: np.ndarray):
        """Replicated placement for the per-batch key stream — a
        process-local array cannot enter a program spanning processes."""
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P()), keys)
        return jnp.asarray(keys)

    def place_data(self, xs, ys):
        """Host chunk -> mesh-sharded device arrays (asynchronous issue;
        public so trainers can double-buffer via ``prefetch_to_device``);
        in a multi-process run every process passes the same GLOBAL chunk
        and contributes the batch columns its devices own (exact parity
        with the single-process replica->rows assignment, which a
        contiguous dataset-level shard would not give).  Already-placed
        ``jax.Array`` inputs pass through untouched, so ``run_epoch``
        accepts either form."""
        if isinstance(xs, jax.Array) and isinstance(ys, jax.Array):
            return xs, ys
        sharding = self.data_sharding()
        if jax.process_count() > 1:
            lo, hi = self._local_batch_range(xs.shape[2])
            return (jax.make_array_from_process_local_data(sharding, xs[:, :, lo:hi]),
                    jax.make_array_from_process_local_data(sharding, ys[:, :, lo:hi]))
        return jax.device_put(xs, sharding), jax.device_put(ys, sharding)

    _place_data = place_data  # backward-compatible alias

    def _local_batch_range(self, global_batch: int):
        """Global-batch column range owned by this process's devices (the
        replica axis shards the batch dim in mesh-device order)."""
        devs = list(self.mesh.devices.ravel())
        if global_batch % len(devs):
            # single-process device_put raises on this; fail identically
            # instead of silently dropping the trailing columns
            raise ValueError(
                f"global batch {global_batch} is not divisible by the "
                f"{len(devs)}-device mesh; pad or resize the batch")
        per = global_batch // len(devs)
        mine = [i for i, d in enumerate(devs)
                if d.process_index == jax.process_index()]
        if not mine:
            raise RuntimeError("this process owns no devices of the engine mesh")
        if mine != list(range(mine[0], mine[-1] + 1)):
            raise NotImplementedError(
                f"non-contiguous local device placement {mine} in the mesh; "
                "build the mesh from jax.devices() order")
        return mine[0] * per, (mine[-1] + 1) * per

    # -- results ---------------------------------------------------------------
    def center_model(self, state: ReplicaState) -> Model:
        """The trained center — reference ``parameter_server.get_model()``."""
        return Model(spec=self.spec, params=jax.tree.map(lambda x: jnp.asarray(x), state.center))

    def _gather_rows(self, subtree):
        """Compiled one-replica-row gather: a [R, ...]-leading sharded
        pytree -> R replicated row pytrees, one collective per row.

        Row-at-a-time keeps the PEAK extra device memory at O(one model
        copy) instead of replicating the full O(model x replicas) stack
        into every device's HBM — a state that only fits sharded must not
        OOM at exactly the checkpoint/ensemble moment the gather exists
        for.  SPMD caveat: this dispatches collectives, so in a
        multi-process run EVERY process must call it with the same
        state."""
        fn = getattr(self, "_row_gather_fn", None)
        if fn is None:
            fn = jax.jit(
                lambda t, i: jax.tree.map(lambda a: jnp.take(a, i, axis=0), t),
                out_shardings=NamedSharding(self.mesh, P()))
            self._row_gather_fn = fn  # fresh lambdas would defeat the jit cache
        return [fn(subtree, jnp.int32(i)) for i in range(self.num_replicas)]

    def gather_state(self, state: ReplicaState, to_host: bool = True) -> Optional[ReplicaState]:
        """Full HOST copy of the training state, gathered row-by-row.

        The sharded fields (``local``/``opt_state``/``extra``) are pulled
        one replica row per collective (see ``_gather_rows``); ``center``
        and ``step`` are already replicated and copy straight out.  This
        is what makes checkpointing and ``local_models`` work when
        replicas live on other hosts.

        ``to_host=False`` runs ONLY the collectives (every process must
        participate in them) and returns ``None`` without materializing
        anything in host RAM — the non-writer processes of a checkpoint
        save use this so an N-host run doesn't copy N-1 redundant full
        states per epoch."""
        rows = {name: self._gather_rows(getattr(state, name))
                for name in ("local", "opt_state", "extra")}
        if not to_host:
            return None
        stacked = {
            name: jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                               *field_rows)
            for name, field_rows in rows.items()
        }
        return ReplicaState(
            center=jax.tree.map(np.asarray, state.center),
            local=stacked["local"],
            opt_state=stacked["opt_state"],
            extra=stacked["extra"],
            step=np.asarray(state.step),
        )

    def local_models(self, state: ReplicaState) -> List[Model]:
        """All per-replica models (EnsembleTrainer's return value).

        Multi-process meshes gather the ``local`` field row-by-row (just
        the weights — not the 2-3x larger optimizer slots), so every
        process returns the identical full ensemble."""
        if jax.process_count() > 1:
            rows = self._gather_rows(state.local)
            return [Model(spec=self.spec,
                          params=jax.tree.map(jnp.asarray, row)) for row in rows]
        local_np = jax.tree.map(np.asarray, state.local)
        models = []
        for i in range(self.num_replicas):
            params = jax.tree.map(lambda a: jnp.asarray(a[i]), local_np)
            models.append(Model(spec=self.spec, params=params))
        return models

    def averaged_model(self, state: ReplicaState) -> Model:
        """Arithmetic mean of locals (AveragingTrainer, reference §2.2).

        The mean runs as a compiled reduction with a REPLICATED output, so
        it also works when the replicas live on other hosts."""
        mean_fn = getattr(self, "_mean_fn", None)
        if mean_fn is None:
            mean_fn = jax.jit(
                lambda local: jax.tree.map(lambda a: jnp.mean(a, axis=0), local),
                out_shardings=NamedSharding(self.mesh, P()))
            self._mean_fn = mean_fn  # fresh lambdas would defeat the jit cache
        return Model(spec=self.spec, params=mean_fn(state.local))
