"""Sequence-parallel LM training: dp × sp shard_map step with ring attention.

No reference counterpart (the reference predates transformers; SURVEY §5
"long-context: absent") — this is the TPU-native long-context path: batch
sharded over the ``dp`` mesh axis, sequence sharded over ``sp`` with ring
attention streaming KV blocks over ICI (``ops/attention.py``), gradients
pmean'd over both axes, parameters replicated.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec


def make_lm_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                       mesh: Mesh, dp_axis: str = "dp", sp_axis: str = "sp") -> Callable:
    """Build a jitted (params, opt_state, tokens, targets) -> (params,
    opt_state, loss) step. ``spec`` must be a transformer_lm whose config
    sets ``seq_axis=sp_axis``; tokens/targets are [B, L] with B sharded
    over dp and L sharded over sp (targets pre-shifted on host).
    """
    if spec.config.get("seq_axis") != sp_axis:
        raise ValueError(
            f"spec.config['seq_axis'] = {spec.config.get('seq_axis')!r} must equal "
            f"sp_axis = {sp_axis!r} or ring attention would not ride this mesh axis")
    module = spec.build()

    def local_loss(params, tokens, targets, offset):
        logits = module.apply({"params": params}, tokens, pos_offset=offset)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets.astype(jnp.int32))
        # mask the GLOBAL final position: its target is shift_targets'
        # padding, not a real next token.  Global position = offset + local
        # index; only the last sp shard holds the padded column.
        l_local = tokens.shape[1]
        global_len = l_local * lax.axis_size(sp_axis)
        pos = offset + jnp.arange(l_local)
        weights = (pos < global_len - 1).astype(jnp.float32)[None, :]
        wsum = jnp.sum(ce * weights)
        wcount = jnp.sum(weights) * tokens.shape[0]
        return wsum, wcount

    def shard_fn(params, opt_state, tokens, targets):
        offset = lax.axis_index(sp_axis) * tokens.shape[1]

        # Differentiate the GLOBAL (pmean'd) loss and use the result as-is.
        # ``params`` enter the shard as mesh-invariant (P()); their use in
        # varying computation is an implicit broadcast whose transpose is a
        # psum, so ``jax.grad`` already returns the cross-shard-summed
        # gradient of whatever scalar it was given.  Hand it the *global*
        # loss (psum-normalized masked CE) and the result is exactly dG/dparams —
        # adding a manual pmean/psum afterwards double-counts by the mesh
        # size.  This also routes sequence-crossing paths (ring attention
        # streams KV over sp) correctly via the collective adjoints.
        def global_loss(p):
            wsum, wcount = local_loss(p, tokens, targets, offset)
            # wcount depends only on the sp position -> varying over sp but
            # not dp; psum requires a uniform varying set, so widen it
            both = (dp_axis, sp_axis)
            missing = tuple(a for a in both if a not in jax.typeof(wcount).vma)
            if missing:
                wcount = lax.pcast(wcount, missing, to="varying")
            return lax.psum(wsum, both) / lax.psum(wcount, both)

        loss, grads = jax.value_and_grad(global_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    data_spec = P(dp_axis, sp_axis)
    sharded = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def lm_data_shardings(mesh: Mesh, dp_axis: str = "dp", sp_axis: str = "sp"):
    return NamedSharding(mesh, P(dp_axis, sp_axis))


def shift_targets(tokens) -> Any:
    """Host-side next-token targets: targets[t] = tokens[t+1], last = pad(0).

    Done on the host because the shift crosses sp shard boundaries; the
    cost is one roll over an int array per batch.  The padded final position
    is excluded from the training loss by ``make_lm_train_step``'s mask.
    """
    import numpy as np

    targets = np.roll(np.asarray(tokens), -1, axis=-1)
    targets[..., -1] = 0
    return targets
