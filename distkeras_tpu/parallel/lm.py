"""LM training: dp × sp × tp shard_map step (ring attention + Megatron TP).

No reference counterpart (the reference predates transformers; SURVEY §5
"long-context: absent") — this is the TPU-native long-context path:

- batch sharded over ``dp``;
- sequence sharded over ``sp`` with ring attention streaming KV blocks over
  ICI (``ops/attention.py``);
- heads / FFN sharded over ``tp`` (Megatron column/row split) with the two
  per-block psums inside the model (``models/transformer.py``);
- gradients of replicated params arrive via collective adjoints, gradients
  of tp-sharded params stay local to their shard.

Any of the axes may be absent from the mesh (or size 1): the same step
builder covers pure-dp, dp×sp, dp×tp and the full 3-D mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec, build_module
from distkeras_tpu.ops.losses import lm_token_cross_entropy


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for k in path:
        key = getattr(k, "key", None)
        if key is None:
            key = getattr(k, "name", None)
        if key is not None:
            names.append(str(key))
    return tuple(names)


def _tp_leaf_spec(path, leaf, tp_axis: Optional[str]) -> P:
    """Megatron placement rule, keyed on the flax param path.

    Matches both the raw param tree and optimizer-state trees (whose paths
    carry the same ``block_i/<layer>/kernel`` suffix); everything else —
    layernorms, embeddings, scalar optimizer counters — is replicated.
    """
    if tp_axis is None:
        return P()
    names = _path_names(path)
    ndim = len(getattr(leaf, "shape", ()))
    if "kernel" in names:
        if "qkv" in names and ndim == 4:
            return P(None, None, tp_axis, None)
        # GQA split layout: q [E, H, Dh] and kv [E, 2, Hkv, Dh] are both
        # column-parallel over their head axis (num_kv_heads % tp_size is
        # validated by TransformerBlock)
        if "q" in names and ndim == 3:
            return P(None, tp_axis, None)
        if "kv" in names and ndim == 4:
            return P(None, None, tp_axis, None)
        if "proj" in names and ndim == 3:
            return P(tp_axis, None, None)
        if "up" in names and ndim == 2:
            return P(None, tp_axis)
        if "down" in names and ndim == 2:
            return P(tp_axis, None)
    return P()


def lm_param_specs(params: Any, tp_axis: Optional[str]) -> Any:
    """PartitionSpec pytree for a TransformerLM param (or optimizer-state,
    or gradient) tree under Megatron tensor parallelism."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _tp_leaf_spec(path, leaf, tp_axis), params)


def lm_opt_specs(optimizer: optax.GradientTransformation, params: Any,
                 tp_axis: Optional[str]) -> Any:
    opt_shapes = jax.eval_shape(optimizer.init, params)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _tp_leaf_spec(path, leaf, tp_axis), opt_shapes)


def lm_state_shardings(mesh: Mesh, optimizer: optax.GradientTransformation,
                       params: Any, tp_axis: Optional[str] = None):
    """(param shardings, opt-state shardings) for placing host state on the
    mesh — feed to ``jax.device_put`` before the first step."""
    pspecs = lm_param_specs(params, tp_axis)
    ospecs = lm_opt_specs(optimizer, params, tp_axis)
    to_sharding = lambda spec: NamedSharding(mesh, spec)
    return (jax.tree.map(to_sharding, pspecs, is_leaf=lambda x: isinstance(x, P)),
            jax.tree.map(to_sharding, ospecs, is_leaf=lambda x: isinstance(x, P)))


def make_lm_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                       mesh: Mesh, dp_axis: str = "dp", sp_axis: Optional[str] = "sp",
                       tp_axis: Optional[str] = None) -> Callable:
    """Build a jitted (params, opt_state, tokens, targets) -> (params,
    opt_state, loss) step over the mesh.

    ``spec`` is the FULL-model spec (init produces the full param tree);
    when ``tp_axis`` names a mesh axis, the step internally applies a module
    configured for the local shard sizes (``tp_size = mesh.shape[tp_axis]``)
    and expects params placed with ``lm_state_shardings``.  ``sp_axis=None``
    (or absent from the mesh) disables sequence parallelism; the spec's
    ``seq_axis`` must agree.
    """
    spec.reject_silent_aux("make_lm_train_step")
    sp_active = sp_axis is not None and sp_axis in mesh.shape and mesh.shape[sp_axis] > 1
    if sp_active and spec.config.get("seq_axis") != sp_axis:
        raise ValueError(
            f"spec.config['seq_axis'] = {spec.config.get('seq_axis')!r} must equal "
            f"sp_axis = {sp_axis!r} or ring attention would not ride this mesh axis")
    tp_size = mesh.shape[tp_axis] if (tp_axis is not None and tp_axis in mesh.shape) else 1
    if tp_axis is not None and tp_axis not in mesh.shape:
        raise ValueError(f"tp_axis {tp_axis!r} is not a mesh axis of {mesh}")
    cfg = dict(spec.config)
    cfg.update(tp_axis=tp_axis if tp_size > 1 else None, tp_size=tp_size)
    module = build_module(spec.name, cfg)
    loss_axes = (dp_axis, sp_axis) if sp_active else (dp_axis,)

    def local_loss(params, tokens, targets, offset):
        # fused unembed+CE: the [B, L, V] f32 logits tensor is never
        # materialized and the unembed matmul runs at bf16 MXU rate
        # (ops/losses.py) — the embed table is replicated under tp, so the
        # fused path is tp-invariant like head()
        ce = lm_token_cross_entropy(module, params, tokens, targets,
                                    pos_offset=offset)
        # mask the GLOBAL final position: its target is shift_targets'
        # padding, not a real next token.  Global position = offset + local
        # index; only the last sp shard holds the padded column.
        l_local = tokens.shape[1]
        global_len = l_local * (lax.axis_size(sp_axis) if sp_active else 1)
        pos = offset + jnp.arange(l_local)
        weights = (pos < global_len - 1).astype(jnp.float32)[None, :]
        wsum = jnp.sum(ce * weights)
        wcount = jnp.sum(weights) * tokens.shape[0]
        return wsum, wcount

    def shard_fn(params, opt_state, tokens, targets):
        offset = (lax.axis_index(sp_axis) * tokens.shape[1]) if sp_active else 0

        # Differentiate the GLOBAL (psum'd) loss and use the result as-is.
        # Replicated params enter mesh-invariant (P()); their use in varying
        # computation is an implicit broadcast whose transpose is a psum, so
        # ``jax.grad`` of the global loss returns the cross-shard-summed
        # gradient directly — adding a manual pmean/psum would double-count.
        # tp-sharded params enter tp-varying; their grads stay local to the
        # shard (Megatron semantics).  The loss itself is tp-INVARIANT —
        # the in-model psums already merged the partial sums — so it is
        # reduced over (dp, sp) only.
        def global_loss(p):
            wsum, wcount = local_loss(p, tokens, targets, offset)
            # wsum derives from the (dp/sp-sharded) data so it already varies
            # over every loss axis; wcount depends only on the sp position and
            # genuinely lacks dp — widen it for the uniform-vma psum
            missing = tuple(a for a in loss_axes if a not in jax.typeof(wcount).vma)
            if missing:
                wcount = lax.pcast(wcount, missing, to="varying")
            return lax.psum(wsum, loss_axes) / lax.psum(wcount, loss_axes)

        loss, grads = jax.value_and_grad(global_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    param_template = jax.eval_shape(lambda: spec.init_params(seed=0))
    pspecs = lm_param_specs(param_template, tp_axis if tp_size > 1 else None)
    ospecs = lm_opt_specs(optimizer, param_template, tp_axis if tp_size > 1 else None)
    data_spec = P(dp_axis, sp_axis) if sp_active else P(dp_axis)
    sharded = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def lm_data_shardings(mesh: Mesh, dp_axis: str = "dp", sp_axis: Optional[str] = "sp"):
    # same activation predicate as make_lm_train_step (size-1 sp is inactive)
    if sp_axis is not None and sp_axis in mesh.shape and mesh.shape[sp_axis] > 1:
        return NamedSharding(mesh, P(dp_axis, sp_axis))
    return NamedSharding(mesh, P(dp_axis))


def shift_targets(tokens) -> Any:
    """Host-side next-token targets: targets[t] = tokens[t+1], last = pad(0).

    Done on the host because the shift crosses sp shard boundaries; the
    cost is one roll over an int array per batch.  The padded final position
    is excluded from the training loss by ``make_lm_train_step``'s mask.
    """
    import numpy as np

    targets = np.roll(np.asarray(tokens), -1, axis=-1)
    targets[..., -1] = 0
    return targets
