"""Device-mesh helpers.

The reference's "cluster" was Spark executors + a TCP hub on the driver
(SURVEY.md §2.14).  Here the cluster is a ``jax.sharding.Mesh``: the
``replica`` axis carries data parallelism (one replica = one reference
"worker"), and richer meshes (dp × tp × sp) serve the TPU-native models.
Collectives ride ICI within a slice; across hosts, join processes with
``runtime/launcher.py :: initialize_multihost`` first — ``jax.devices()``
then spans every host and these helpers build the same mesh over DCN
(exercised by ``tests/test_multihost.py`` with 2 real processes).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def create_mesh(num_devices: Optional[int] = None, axis_name: str = "replica") -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (data parallelism)."""
    devices = jax.devices()
    if num_devices is None:
        num_devices = len(devices)
    if num_devices > len(devices):
        raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
    return Mesh(np.array(devices[:num_devices]), (axis_name,))


def create_nd_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str]) -> Mesh:
    """N-D mesh, e.g. ``create_nd_mesh((2, 2, 2), ('dp', 'tp', 'sp'))``."""
    n = int(np.prod(axis_sizes))
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(f"mesh of {n} devices requested, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))
