"""Expert parallelism: Switch-style mixture-of-experts over an ``ep`` axis.

No reference counterpart (data-parallel only, SURVEY §2.13) — this
completes the framework's parallelism suite (dp/sp/tp/pp/ep).  The design
is the standard TPU MoE shape (Switch Transformer / Mesh-TF lineage),
built for the MXU and ICI:

- **Top-k routing with static capacity** (``router_top_k``: 1 = Switch,
  2 = GShard-style gating with renormalized pair weights and rank
  priority — every token's first choice seats before any second
  choice).  Each expert accepts at most ``capacity`` tokens per shard
  (the rest fall through on the residual path).
- **Two dispatch implementations, one seating rule**
  (``dispatch_impl``): ``"dense"`` builds the classic [T, E, C] one-hot
  dispatch/combine tensors and einsums through them — no gathers, no
  dynamic shapes, everything MXU-tiled, but the einsums cost
  ``4·T·E·C·D`` matmul FLOPs of pure routing plumbing per layer (41% of
  ALL matmul work at the round-5 bench shape).  ``"sorted"`` computes
  the SAME seating (expert id + queue position per assignment) and then
  moves rows by index: a static-shape scatter builds the slot->token
  map, one gather fills the [E, C, D] slot tensor, one gather + a
  k-term weighted sum combines — zero dispatch matmuls, O((kT + EC)·D)
  memory traffic, still static shapes for XLA.  Both paths produce
  bit-identical outputs (parity-tested); ``"auto"`` picks dense only
  below a small-shape threshold where a single fused einsum beats
  gather launch overhead (see :func:`resolve_dispatch_impl`).
- **Experts live sharded over ``ep``.**  Dispatch is two
  ``lax.all_to_all``s over the mesh axis: token slots [E, C, D] travel to
  the shard owning their expert, come back as expert outputs — the
  all-to-all rides ICI, exactly like the sequence-parallel ring.
- **Router determinism.**  Routing depends only on (params, tokens), so
  ep=1 and ep=N produce bit-comparable results for the same inputs — the
  parity property the tests pin down.

The load-balancing auxiliary loss is the Switch one:
``E * sum_e f_e * p_e`` (token fraction times mean router prob).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import observability as obs
from distkeras_tpu.models.base import ModelSpec, register_model

import flax.linen as nn

# auto dispatch threshold: below this many [T, E, C] one-hot elements the
# dense einsum pair is a single fused MXU kernel over <= 1 MB of f32 and
# beats the sorted path's scatter+gather launch overhead; above it the
# dense tax grows as 4·T·E·C·D matmul FLOPs (41% of ALL matmul work at
# the round-5 bench shape T=2048, E=8, C=512) while sorted stays
# O((kT + EC)·D) bytes moved.  The bench's dense-vs-sorted A/B legs
# record the real crossover so drift after an XLA change trips visibly.
_DENSE_DISPATCH_MAX_TEC = 1 << 18


def resolve_dispatch_impl(impl: str, t: int, e: int, c: int) -> str:
    """Resolve ``dispatch_impl`` ("dense" | "sorted" | "auto") for a
    routing shape: tokens ``t``, experts ``e``, per-expert capacity ``c``.

    ``auto`` keys on the dense one-hot tensor size ``t*e*c`` — the
    quantity whose growth makes the dense einsums' 2·T MACs per slot
    element intolerable — with the threshold documented above."""
    if impl in ("dense", "sorted"):
        return impl
    if impl != "auto":
        raise ValueError(f"dispatch_impl must be 'dense', 'sorted' or "
                         f"'auto', got {impl!r}")
    return "dense" if t * e * c <= _DENSE_DISPATCH_MAX_TEC else "sorted"


def dispatch_matmul_flops(t: int, e: int, c: int, d: int, impl: str) -> int:
    """FORWARD matmul FLOPs one MoE layer spends on dispatch + combine.

    Dense: the [T,E,C] one-hot einsums cost ``2·T·E·C·D`` on each side.
    Sorted: zero — rows move by gather/scatter, not contraction.  The
    single source of truth for the bench's ``dispatch_flops_pct`` and
    the sown per-layer stat (multiply by 3 for fwd+bwd accounting)."""
    if impl == "sorted":
        return 0
    if impl != "dense":
        raise ValueError(f"impl must be 'dense' or 'sorted', got {impl!r}")
    return 4 * t * e * c * d


class MoEMLP(nn.Module):
    """Router + E experts (each a 2-layer gelu MLP), top-k dispatch.

    Call with tokens [T, D] -> (out [T, D], aux_loss scalar).  ``ep_axis``
    set (and bound by an enclosing shard_map) runs expert-parallel: this
    shard computes routing for its T tokens, all_to_all's token slots so
    each shard runs only its E_local = E/ep experts, and reverses the
    exchange.  Unbound (init / single device): all experts local, same
    math, no collectives.

    Expert-parameter sharding follows the TP pattern (models/transformer.py):
    init always builds the FULL tree (``ep_size=1`` semantics, w_up
    [E, D, F]); the train step device_puts w_up/w_down with a leading-axis
    ``P(ep)`` sharding and applies a module configured with ``ep_size=ep``,
    whose declared param shapes are the LOCAL slabs [E/ep, D, F] — each
    device holds (and optimizes) only its own experts' weights.  The
    router stays replicated: routing needs all E logits.
    """

    num_experts: int
    model_dim: int
    hidden_dim: int
    capacity: int  # per-expert slots PER SHARD
    ep_axis: Optional[str] = None
    ep_size: int = 1
    router_top_k: int = 1  # 1 = Switch; 2 = GShard-style top-2 gating
    dispatch_impl: str = "auto"  # "dense" | "sorted" | "auto" — see
                                 # resolve_dispatch_impl; same seating
                                 # either way (bit-parity tested)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        t, d = x.shape
        if d != self.model_dim:
            raise ValueError(f"tokens have dim {d}, module declares model_dim={self.model_dim}")
        e, c, f = self.num_experts, self.capacity, self.hidden_dim
        k_r = self.router_top_k
        if k_r not in (1, 2):
            raise ValueError(f"router_top_k must be 1 or 2, got {k_r}")
        if k_r > e:
            raise ValueError(f"router_top_k {k_r} exceeds num_experts {e}")
        if e % self.ep_size:
            raise ValueError(f"num_experts {e} not divisible by ep_size {self.ep_size}")
        impl = resolve_dispatch_impl(self.dispatch_impl, t, e, c)
        e_local = e // self.ep_size
        router = self.param("router", nn.initializers.normal(0.02), (d, e))
        w_up_l = self.param("w_up", nn.initializers.lecun_normal(), (e_local, d, f))
        w_down_l = self.param("w_down", nn.initializers.lecun_normal(), (e_local, f, d))

        xc = x.astype(self.compute_dtype)
        # -- routing (float32 for a stable softmax/top-k) ----------------------
        scores = jax.nn.softmax((x.astype(jnp.float32) @ router.astype(jnp.float32)),
                                axis=-1)  # [T, E]
        # gate weights: Switch (k=1) uses the raw top prob; top-2 uses the
        # GShard form — the pair's probs renormalized to sum to 1
        gate_probs, choice = lax.top_k(scores, k_r)            # [T, k]
        if k_r > 1:
            gate_probs = gate_probs / jnp.sum(gate_probs, axis=-1, keepdims=True)
        onehots = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [T, k, E]
        # queue positions with RANK priority (GShard): every token's first
        # choice is seated before any token's second choice, so adding a
        # second choice never evicts someone's first.  The rank-major
        # [k*T, E] cumsum implements exactly that order; beyond-capacity
        # assignments drop (residual path, standard Switch behavior).
        # This seating is shared by BOTH dispatch impls — parity by
        # construction, the einsum-vs-gather choice only moves the rows
        oh_rank = jnp.swapaxes(onehots, 0, 1)                   # [k, T, E], rank-major
        rank_major = oh_rank.reshape(k_r * t, e)                # [k*T, E]
        pos_flat = jnp.cumsum(rank_major, axis=0) * rank_major - 1.0
        pos_rank = jnp.sum(pos_flat.reshape(k_r, t, e) * oh_rank,
                           axis=-1).astype(jnp.int32)           # [k, T]
        keep = pos_rank < c
        gates_rank = jnp.swapaxes(gate_probs, 0, 1)             # [k, T]

        # Switch load-balance aux: E * sum_e (fraction routed) * (mean prob)
        # — computed on FIRST choices for both k (the standard Switch form;
        # GShard's variant likewise uses the top-1 assignment fraction)
        frac = jnp.mean(onehots[:, 0], axis=0)
        mean_prob = jnp.mean(scores, axis=0)
        aux = e * jnp.sum(frac * mean_prob)

        # router observability (surfaced by the train steps into their
        # stats output): what fraction of routed assignments fell off the
        # capacity cliff, how hot the hottest expert ran relative to its
        # capacity, and what share of this layer's matmul FLOPs the
        # RESOLVED dispatch impl spends on routing plumbing (analytic,
        # layer-local: dispatch over dispatch + experts + router).
        # Scalars, so the sow costs nothing
        assigned = jnp.sum(rank_major, axis=0)                  # [E]
        self.sow("router_stats", "dropped_fraction",
                 1.0 - jnp.sum(keep.astype(jnp.float32)) / (k_r * t))
        self.sow("router_stats", "max_expert_load",
                 jnp.max(assigned) / c)
        disp_fl = dispatch_matmul_flops(t, e, c, d, impl)
        layer_fl = 4 * e * c * d * f + 2 * t * d * e  # experts + router, fwd
        # NOTE the denominator: LAYER-local (dispatch + experts + router —
        # the module cannot see attention/unembed), so under dense
        # dispatch this reads HIGHER than the bench's same-named
        # model-wide field (~50% vs 41% at the r05 bench shape); both are
        # exactly 0 on the sorted path, which is the number that matters
        self.sow("router_stats", "dispatch_flops_pct",
                 jnp.float32(100.0 * disp_fl / (disp_fl + layer_fl)))

        # -- dispatch to experts ----------------------------------------------
        if impl == "dense":
            slot = jax.nn.one_hot(jnp.where(keep, pos_rank, -1), c,
                                  dtype=jnp.float32)            # [k, T, C]; dropped -> 0
            per_rank = oh_rank[:, :, :, None] * slot[:, :, None, :]
            dispatch = jnp.sum(per_rank, axis=0)                # [T, E, C]
            slots = jnp.einsum("tec,td->ecd",
                               dispatch.astype(self.compute_dtype), xc)
        else:
            # sorted: each kept (rank, token) assignment owns a unique flat
            # slot expert*C + queue_pos (queue positions are unique per
            # expert across the rank-major order); dropped assignments park
            # on a dummy slot E*C that is sliced away.  Scatter the TOKEN
            # INDEX per slot (ints — no gradient surface), then one gather
            # fills the slot tensor; unoccupied slots multiply to zero so
            # the expert compute sees exactly the dense path's operand
            choice_rank = jnp.swapaxes(choice, 0, 1)            # [k, T]
            dest = jnp.where(keep, choice_rank * c + pos_rank, e * c)
            flat_dest = dest.reshape(-1)                        # [k*T]
            src_tok = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :],
                                       (k_r, t)).reshape(-1)
            slot_tok = jnp.zeros((e * c + 1,), jnp.int32).at[flat_dest].set(src_tok)
            occupied = jnp.zeros((e * c + 1,), self.compute_dtype
                                 ).at[flat_dest].set(1)
            slots = (jnp.take(xc, slot_tok[:e * c], axis=0)
                     * occupied[:e * c, None]).reshape(e, c, d)
        ep = 1
        if self.ep_axis is not None and self.ep_axis in jax.typeof(x).vma:
            ep = lax.axis_size(self.ep_axis)
            if ep != self.ep_size:
                raise ValueError(f"mesh axis {self.ep_axis!r} has size {ep}, module "
                                 f"was configured with ep_size={self.ep_size}")
        if ep > 1:
            # tiled all_to_all: [E, C, D] -> [E_local, ep*C, D] — shard s
            # keeps its E_local experts' slot block from EVERY peer (the
            # expert dim splits, the slot dim concatenates); rides ICI
            slots = lax.all_to_all(slots, self.ep_axis, split_axis=0, concat_axis=1,
                                   tiled=True)

        h = jnp.einsum("ecd,edf->ecf", slots, w_up_l.astype(self.compute_dtype))
        h = nn.gelu(h)
        out_slots = jnp.einsum("ecf,efd->ecd", h, w_down_l.astype(self.compute_dtype))

        if ep > 1:
            # reverse exchange: [E_local, ep*C, D] -> [E, C, D]
            out_slots = lax.all_to_all(out_slots, self.ep_axis, split_axis=1,
                                       concat_axis=0, tiled=True)

        if impl == "dense":
            combine = jnp.sum(per_rank * gates_rank[:, :, None, None], axis=0)
            out = jnp.einsum("tec,ecd->td",
                             combine.astype(self.compute_dtype), out_slots)
        else:
            # gather each assignment's expert output back by its flat slot
            # (dropped -> the appended zero row), then gate-weight and sum
            # over the k ranks with the same precision as the dense
            # combine (compute-dtype operands, dot accumulation, one
            # downcast)
            padded = jnp.concatenate(
                [out_slots.reshape(e * c, d),
                 jnp.zeros((1, d), out_slots.dtype)], axis=0)
            y_tok = jnp.take(padded, dest, axis=0)              # [k, T, D]
            gates_c = gates_rank.astype(self.compute_dtype)     # [k, T]
            # the k-term sum as a contraction (not an explicit mul+add):
            # XLA lowers it through the same dot/FMA machinery as the
            # dense combine einsum, which is what keeps the two paths
            # bit-identical rather than 1-ulp apart under top-2
            out = jnp.einsum("kt,ktd->td", gates_c, y_tok)
        return out.astype(x.dtype), aux


@register_model("moe_mlp_classifier")
class MoEClassifier(nn.Module):
    """Small MoE classifier: embed -> MoE layer (+residual) -> head.

    The minimal end-to-end carrier for expert parallelism (the MoE analogue
    of the reference's MLP example family).
    """

    input_dim: int = 32
    model_dim: int = 64
    num_experts: int = 4
    hidden_dim: int = 128
    capacity: int = 64
    num_outputs: int = 10
    ep_axis: Optional[str] = None
    ep_size: int = 1
    router_top_k: int = 1
    dispatch_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Dense(self.model_dim, name="embed")(x)
        moe_out, aux = MoEMLP(num_experts=self.num_experts, model_dim=self.model_dim,
                              hidden_dim=self.hidden_dim, capacity=self.capacity,
                              ep_axis=self.ep_axis, ep_size=self.ep_size,
                              router_top_k=self.router_top_k,
                              dispatch_impl=self.dispatch_impl, name="moe")(h)
        h = h + moe_out
        self.sow("aux_loss", "load_balance", aux)
        return nn.Dense(self.num_outputs, name="head")(h)


def moe_classifier_spec(input_dim: int = 32, num_experts: int = 4, capacity: int = 64,
                        num_outputs: int = 10, ep_axis: Optional[str] = None,
                        router_top_k: int = 1,
                        dispatch_impl: str = "auto") -> ModelSpec:
    return ModelSpec(
        name="moe_mlp_classifier",
        config={"input_dim": input_dim, "num_experts": num_experts,
                "capacity": capacity, "num_outputs": num_outputs, "ep_axis": ep_axis,
                "router_top_k": router_top_k, "dispatch_impl": dispatch_impl},
        input_shape=(input_dim,),
    )


def _moe_param_specs(params: Any, ep_axis: str):
    """w_up/w_down leaves shard over ep on the leading (expert) axis; the
    router and every non-MoE leaf stay replicated."""

    def spec_for(path, _leaf):
        names = {getattr(k, "key", None) for k in path}
        return P(ep_axis) if names & {"w_up", "w_down"} else P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _collect_router_stats(tree) -> Dict[str, list]:
    """Walk a sown ``router_stats`` collection — nested {module_path:
    {stat_name: (values...)}} dicts, one entry per MoE layer — and group
    the leaf values by STAT NAME across layers."""
    stats: Dict[str, list] = {}

    def visit(node):
        for key, val in dict(node).items():
            if hasattr(val, "items"):
                visit(val)
            else:
                vals = val if isinstance(val, (tuple, list)) else (val,)
                stats.setdefault(key, []).extend(vals)

    visit(tree)
    return stats


def _make_moe_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                   mesh: Mesh, dp_axis: str, ep_axis: str, aux_weight: float,
                   num_experts: int, per_example_loss: Callable) -> Callable:
    """Shared (dp x ep) step machinery: batch sharded over both axes,
    expert weights sharded over ep, aux losses collected from every sown
    ``aux_loss`` leaf, gradients synced per-leaf down to each param's
    sharding."""
    from distkeras_tpu.models.base import build_module

    ep = mesh.shape[ep_axis]
    if num_experts % ep:
        raise ValueError(f"num_experts {num_experts} not divisible by "
                         f"ep mesh axis size {ep}")
    module_local = build_module(spec.name, dict(spec.config, ep_axis=ep_axis, ep_size=ep))

    def shard_fn(params, opt_state, x, y):
        def loss_fn(p):
            logits, variables = module_local.apply(
                {"params": p}, x, mutable=["aux_loss", "router_stats"])
            ce = per_example_loss(logits, y)
            aux_leaves = jax.tree.leaves(variables.get("aux_loss", {}))
            aux = sum(aux_leaves) / len(aux_leaves) if aux_leaves else 0.0
            loss = ce + aux_weight * aux
            n = lax.psum(1, (dp_axis, ep_axis))
            return lax.psum(loss, (dp_axis, ep_axis)) / n, variables

        (loss, variables), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # router observability: every sown counter, averaged over layers
        # and shards (each shard routes its own tokens) — returned so the
        # caller's training loop can watch drops/overflow without a second
        # forward.  stats names follow the sow names in MoEMLP
        n = lax.psum(1, (dp_axis, ep_axis))
        stats = {
            name: lax.psum(sum(vals) / len(vals), (dp_axis, ep_axis)) / n
            for name, vals in _collect_router_stats(
                variables.get("router_stats", {})).items()
        }
        # sync each grad leaf down to its param's sharding: replicated
        # params need the cross-shard psum; expert slabs keep their ep
        # variance but still sum over dp (the same slab serves every dp row)
        grads = jax.tree.map(
            lambda g, p: lax.psum(g, extra) if (extra := tuple(
                a for a in jax.typeof(g).vma if a not in jax.typeof(p).vma)) else g,
            grads, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, stats

    def wrapped(params, opt_state, x, y):
        # specs resolved at trace time from the actual tree structures
        pspecs = _moe_param_specs(params, ep_axis)
        ospecs = _moe_param_specs(opt_state, ep_axis)
        data_spec = P((dp_axis, ep_axis))  # batch split over all devices
        sharded = jax.shard_map(shard_fn, mesh=mesh,
                                in_specs=(pspecs, ospecs, data_spec, data_spec),
                                out_specs=(pspecs, ospecs, P(), P()))
        return sharded(params, opt_state, x, y)

    jitted = jax.jit(wrapped, donate_argnums=(0, 1))

    def step_with_telemetry(params, opt_state, x, y):
        out = jitted(params, opt_state, x, y)
        if obs.enabled():
            # the stats the router always computed and the train loops
            # used to discard: surfaced as gauges.  float() blocks on the
            # step — only paid when telemetry is on
            stats = out[3]
            for stat_name in ("dropped_fraction", "max_expert_load",
                              "dispatch_flops_pct"):
                if stat_name in stats:
                    obs.gauge(f"moe_{stat_name}").set(float(stats[stat_name]))
            obs.counter("moe_steps_total").inc()
        return out

    return step_with_telemetry


def make_moe_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                        mesh: Mesh, dp_axis: str = "dp", ep_axis: str = "ep",
                        aux_weight: float = 0.01) -> Callable:
    """Jitted ``(params, opt_state, x, y) -> (params, opt_state, loss,
    router_stats)`` over a (dp, ep) mesh for classifier-shaped models:
    ``y`` one-hot.  Expert weights sharded over ep (place state with
    ``moe_state_shardings``), everything else replicated.  ``router_stats``
    is a dict of scalars averaged over MoE layers and shards —
    ``dropped_fraction`` (routed assignments lost to the capacity cliff),
    ``max_expert_load`` (hottest expert's assignments / capacity) and
    ``dispatch_flops_pct`` (share of the MoE LAYER's matmul FLOPs —
    dispatch + experts + router — spent on routing plumbing; exactly 0
    for sorted.  The bench's same-named field divides by the whole
    MODEL's FLOPs incl. attention and unembed, so its dense numbers run
    lower) — for the training loop's metrics.
    """
    return _make_moe_step(
        spec, optimizer, mesh, dp_axis, ep_axis, aux_weight,
        num_experts=spec.config["num_experts"],
        per_example_loss=lambda logits, y: optax.softmax_cross_entropy(
            logits.astype(jnp.float32), y).mean())


def make_moe_lm_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                           mesh: Mesh, dp_axis: str = "dp", ep_axis: str = "ep",
                           aux_weight: float = 0.01) -> Callable:
    """(dp x ep) training step for a MoE TransformerLM (``moe_experts`` set
    in the spec): tokens/targets [B, L] int32 with B sharded over both
    axes, Switch FFN experts sharded over ep, per-block load-balance aux
    losses averaged into the objective.  Returns ``(params, opt_state,
    loss, router_stats)`` — see :func:`make_moe_train_step` for the stats
    dict.  v1 scope: MoE composes with dp/ep here (tp/sp belong to the
    dense lm step in parallel/lm.py).
    """
    return _make_moe_step(
        spec, optimizer, mesh, dp_axis, ep_axis, aux_weight,
        num_experts=spec.config["moe_experts"],
        per_example_loss=lambda logits, tgt: optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt.astype(jnp.int32))[:, :-1].mean())


def moe_state_shardings(mesh: Mesh, optimizer: optax.GradientTransformation,
                        params: Any, ep_axis: str = "ep"):
    """(param shardings, opt-state shardings) for ``device_put`` before the
    step: expert slabs over ep, the rest replicated (mirrors
    ``lm_state_shardings`` for the tp path)."""
    pspecs = _moe_param_specs(params, ep_axis)
    ospecs = _moe_param_specs(jax.eval_shape(optimizer.init, params), ep_axis)
    to_sh = lambda s: NamedSharding(mesh, s)
    return (jax.tree.map(to_sh, pspecs, is_leaf=lambda x: isinstance(x, P)),
            jax.tree.map(to_sh, ospecs, is_leaf=lambda x: isinstance(x, P)))


def moe_data_sharding(mesh: Mesh, dp_axis: str = "dp", ep_axis: str = "ep"):
    return NamedSharding(mesh, P((dp_axis, ep_axis)))
