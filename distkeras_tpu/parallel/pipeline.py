"""Pipeline parallelism: microbatch schedules over a ``pp`` axis.

No reference counterpart (the reference is data-parallel only, SURVEY.md
§2.13) — TPU-native headroom.  Two schedules share one substrate (the
rotation: each rank applies its resident stage of ``num_layers / pp``
transformer blocks to its current buffer, then ``lax.ppermute``s
activations one hop):

1. **GPipe** — all-forward-then-all-backward.  Rank 0 feeds a fresh
   microbatch each tick; the last rank collects finished microbatches;
   ``M + pp - 1`` ticks drain ``M``.  The backward schedule is NOT
   hand-written: differentiating through the tick scan reverses every
   ppermute (collective adjoints), which IS the backward pipeline.
   ``jax.checkpoint`` around the stage keeps per-tick residuals
   O(microbatch), but the scan's residuals grow O(M) overall.
2. **1F1B** (``schedule="1f1b"``) — hand-scheduled: each cycle runs one
   forward AND one backward unit per rank, cotangents hop up a reverse
   ppermute ring, and backward units re-derive their stage vjp from a
   ``2*pp - 1``-slot input ring — resident activations O(pp) regardless
   of M.  Same gradients (parity-tested), same 2(pp-1)-unit bubble.

Layout: block params are stacked to [num_layers, ...] and sharded over pp
on the leading axis (each rank holds its stage's slab); embedding/unembed/
final-norm params are replicated — only rank 0's embedding output enters
the pipeline, so its gradient routes exclusively through rank 0's path.

Composes with data parallelism over a (dp, pp) mesh; tensor/sequence axes
compose at the block level and are left out of the v1 pipeline step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec, build_module
from distkeras_tpu.models.transformer import TransformerBlock


def split_block_params(params: Dict[str, Any]) -> Tuple[Dict[str, Any], Any]:
    """Full TransformerLM params -> (outer params, blocks stacked on axis 0).

    ``outer`` keeps the embedding / positional / final-norm leaves under
    their original names; ``blocks`` stacks ``block_0..block_{n-1}`` (all
    structurally identical) into one pytree with a leading layer axis.
    """
    names = sorted((k for k in params if k.startswith("block_")),
                   key=lambda k: int(k.split("_")[1]))
    if not names:
        raise ValueError("params contain no block_i subtrees; not a TransformerLM tree")
    blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *[params[k] for k in names])
    outer = {k: v for k, v in params.items() if not k.startswith("block_")}
    return outer, blocks


def merge_block_params(outer: Dict[str, Any], blocks: Any) -> Dict[str, Any]:
    """Inverse of ``split_block_params`` (for checkpointing / serialization)."""
    num_layers = jax.tree.leaves(blocks)[0].shape[0]
    params = dict(outer)
    for i in range(num_layers):
        params[f"block_{i}"] = jax.tree.map(lambda a, i=i: a[i], blocks)
    return params


def pp_param_specs(outer: Dict[str, Any], blocks: Any, pp_axis: str):
    outer_specs = jax.tree.map(lambda _: P(), outer)
    block_specs = jax.tree.map(lambda _: P(pp_axis), blocks)
    return outer_specs, block_specs


def head_recompute_factor(pp: int, num_microbatches: int) -> float:
    """1F1B's head (+CE) evaluations per step relative to GPipe's.

    GPipe evaluates the final-norm + unembed + softmax-CE once per
    microbatch (M total).  Since the head moved inside a ``lax.cond``
    gated on (last rank AND valid backward unit), 1F1B evaluates it
    exactly M times too — factor **1.0**.  The round-5 schedule's
    ``jnp.where`` form computed-then-masked the head on every rank every
    cycle, ``pp * (1 + 2(pp-1)/M)`` times GPipe's unembed FLOPs — the
    measured reason it lost to GPipe at every M (1081 vs 596 ms at M=2).
    The function stays so the bench ``pipeline`` leg keeps recording the
    factor next to the measurement: a schedule change that reintroduces
    head recompute must move this number, not a docstring."""
    if pp < 1 or num_microbatches < 1:
        raise ValueError(f"pp and num_microbatches must be >= 1, got "
                         f"{pp}, {num_microbatches}")
    return 1.0


def make_pp_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                       mesh: Mesh, num_microbatches: int,
                       dp_axis: str = "dp", pp_axis: str = "pp",
                       schedule: str = "gpipe") -> Callable:
    """Build a jitted ((outer, blocks), opt_state, tokens, targets) ->
    ((outer, blocks), opt_state, loss) pipeline-parallel training step.

    ``tokens``/``targets`` are [B, L] with B sharded over dp (and B a
    multiple of ``num_microbatches`` per dp shard); block params must be
    placed with ``pp_state_shardings``.

    ``schedule``:

    - ``"gpipe"`` — all-forward-then-all-backward; the backward pipeline
      comes free from differentiating the tick scan (collective
      adjoints).  Activation residuals grow with the number of
      microbatches M: O(M) stage boundaries live across the backward.
    - ``"1f1b"`` — hand-scheduled one-forward-one-backward: each cycle
      every rank runs one forward unit AND one backward unit (the
      backward re-derives its stage vjp from a stored stage INPUT), so
      at most ``2*pp - 1`` microbatch activations are ever resident —
      O(pp), independent of M.  The gradient math is identical (parity
      tested); the BUBBLE is also identical (2(pp-1) idle units either
      way — non-interleaved 1F1B trades nothing for its memory bound).
      Pick it when M must grow (long sequences / small microbatches)
      and GPipe's O(M) residuals would not fit HBM.

      **Head cost (fixed in round 6):** ``unit_scalar`` runs the
      final-norm + unembed matmul and the vocab-wide softmax-CE inside a
      ``lax.cond`` whose predicate is (last rank AND valid backward
      unit) — XLA conditionals execute one branch per device at
      runtime, so only the last rank's M valid units ever pay the
      vocab-sized matmul; every other rank (and fill/drain cycles) runs
      the cheap cotangent chain term.  ``head_recompute_factor`` is
      therefore 1.0 — the same head FLOPs as GPipe.  (The round-5 form
      computed the head on every rank every cycle and masked it with
      ``jnp.where`` — ``pp * (1 + 2(pp-1)/M)`` times GPipe's unembed
      FLOPs, the measured reason 1F1B lost to GPipe at every M.)
      ``bench.py``'s ``pipeline`` leg records the measured
      gpipe-vs-1f1b step time next to the analytic factor so a
      regression trips as a number, not a docstring drift.
    """
    if spec.config.get("moe_experts"):
        raise ValueError("MoE FFN does not compose with pipeline parallelism "
                         "(v1); use make_moe_lm_train_step or a dense spec")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
    pp = mesh.shape[pp_axis]
    num_layers = spec.config["num_layers"]
    if num_layers % pp:
        raise ValueError(f"num_layers {num_layers} not divisible by pp {pp}")
    layers_per_stage = num_layers // pp
    cfg = spec.config
    cdtype = cfg.get("compute_dtype", jnp.bfloat16)
    block = TransformerBlock(
        model_dim=cfg["model_dim"], num_heads=cfg["num_heads"],
        num_kv_heads=cfg.get("num_kv_heads"),
        mlp_ratio=cfg.get("mlp_ratio", 4), seq_axis=None,
        positional=cfg.get("positional") or "learned",
        attn_impl=cfg.get("attn_impl"), compute_dtype=cdtype)
    module = build_module(spec.name, dict(cfg, seq_axis=None))

    @jax.checkpoint
    def stage_apply(stage_params, x):
        """Apply this rank's ``layers_per_stage`` blocks (scan over the slab)."""

        def one(x, layer_params):
            return block.apply({"params": layer_params}, x), None

        x, _ = lax.scan(one, x, stage_params)
        return x

    def vary(z):
        """Promote to varying over (dp, pp) — both schedules' buffers need
        the full vma before mixing with per-shard data."""
        missing = tuple(a for a in (dp_axis, pp_axis)
                        if a not in jax.typeof(z).vma)
        return lax.pcast(z, missing, to="varying") if missing else z

    down_perm = [(i, (i + 1) % pp) for i in range(pp)]
    up_perm = [(i, (i - 1) % pp) for i in range(pp)]

    def shard_fn_1f1b(params, opt_state, tokens, targets):
        """One-forward-one-backward: cycle c runs the forward of
        microbatch ``c - rank`` and the backward of microbatch
        ``c - 2(pp-1) + rank`` on every rank, with activations hopping
        down (ppermute) and cotangents hopping up each cycle.

        No autodiff crosses the cycle scan: backward units recompute
        their stage vjp from the stage INPUT stored in a ``2*pp - 1``
        slot ring (an input stored at cycle ``b + r`` is consumed at
        ``b + 2(pp-1) - r``, span <= 2(pp-1) < ring), and parameter
        gradients accumulate explicitly.  The last rank's backward unit
        folds the head + CE vjp into the same grad call via a
        ``lax.cond``-selected scalar (the cond's vjp is the cond of the
        branch vjps, so non-head units contribute exactly the cotangent
        chain and zero head gradient — and, unlike the round-5
        ``jnp.where`` form, never EXECUTE the vocab-sized head matmul).

        Resident activations really are O(pp): the embedding runs PER
        CYCLE on the current microbatch's tokens (the full-epoch token
        ids are the only O(M) array — int32, model_dim-times smaller
        than activations), and rank 0's embedding cotangent folds into
        the gradient accumulator in the same cycle via an inline vjp
        instead of being collected into an O(M) buffer.

        Params enter the cycle computation pcast to (dp, pp)-VARYING, so
        every unit grad is shard-local (no per-cycle implicit psum from
        the unvarying->varying adjoint); the single demotion to each
        param's sharding happens once after the scan — where the psum
        over pp neatly SUMS the outer tree's two owners (rank 0's
        embedding part, the last rank's head part).
        """
        outer, blocks = params
        my = lax.axis_index(pp_axis)
        is_last = my == pp - 1
        b, l = tokens.shape
        m = num_microbatches
        mb = b // m
        e = cfg["model_dim"]
        edtype = jnp.dtype(cdtype)
        tok_mb = vary(tokens.reshape(m, mb, l))
        tgt_mb = vary(targets.reshape(m, mb, l))
        outer_v = jax.tree.map(vary, outer)
        blocks_v = jax.tree.map(vary, blocks)

        def embed(outer_, tok_1mb):
            return module.apply({"params": outer_}, tok_1mb,
                                method="embed_tokens")

        def unit_scalar(blocks_, outer_, x_in, cot_in, tgt_1mb, head_flag):
            """``head_flag`` = (last rank AND valid backward unit): the
            vocab-sized head + CE runs inside a ``lax.cond`` branch, so
            every other rank (and the last rank's fill/drain cycles)
            executes only the cheap chain term at RUNTIME — XLA
            conditionals evaluate one branch per device, which is how a
            per-rank branch lives inside one SPMD program without every
            rank paying the unembed matmul (the round-5 ``jnp.where``
            form computed-then-masked it: pp ranks x every cycle of
            vocab-sized waste, the reason 1F1B lost to GPipe at every
            measured M).  Autodiff through cond yields the cond of the
            branch vjps, so non-head units contribute exactly the
            cotangent chain and zero head gradient, as before."""
            y = stage_apply(blocks_, x_in)

            def ce_term(y_):
                logits = module.apply({"params": outer_}, y_, method="head")
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), tgt_1mb.astype(jnp.int32))
                return jnp.sum(ce[:, :-1])

            def chain_term(y_):
                return jnp.sum((y_ * cot_in).astype(jnp.float32))

            return lax.cond(head_flag, ce_term, chain_term, y)

        unit_grad = jax.value_and_grad(unit_scalar, argnums=(0, 1, 2))

        ring = 2 * pp - 1
        cycles = m + 2 * (pp - 1)
        zeros_f32 = lambda tree: jax.tree.map(
            lambda a: vary(jnp.zeros(a.shape, jnp.float32)), tree)
        carry0 = (
            vary(jnp.zeros((mb, l, e), edtype)),               # fwd_buf
            vary(jnp.zeros((mb, l, e), edtype)),               # cot_buf
            vary(jnp.zeros((ring, mb, l, e), edtype)),         # act ring
            zeros_f32(blocks),                                 # grad accum
            zeros_f32(outer),                                  # outer grad accum
            vary(jnp.zeros((), jnp.float32)),                  # loss accum
        )

        def cycle(carry, c):
            fwd_buf, cot_buf, acts, g_blocks, g_outer, loss = carry
            # ---- forward unit: microbatch c - my -------------------------
            feed = embed(outer_v, lax.dynamic_index_in_dim(
                tok_mb, jnp.clip(c, 0, m - 1), 0, keepdims=False))
            x_in_f = jnp.where(my == 0, feed.astype(edtype), fwd_buf)
            y_f = stage_apply(blocks_v, x_in_f)
            acts = lax.dynamic_update_index_in_dim(acts, x_in_f, c % ring, 0)
            # ---- backward unit: microbatch c - 2(pp-1) + my --------------
            b_idx = c - 2 * (pp - 1) + my
            b_valid = jnp.logical_and(b_idx >= 0, b_idx < m)
            stored_at = b_idx + my  # its forward cycle on this rank
            x_in_b = lax.dynamic_index_in_dim(
                acts, jnp.clip(stored_at, 0, cycles) % ring, 0, keepdims=False)
            tgt_b = lax.dynamic_index_in_dim(tgt_mb, jnp.clip(b_idx, 0, m - 1),
                                             0, keepdims=False)
            # head branch only where it counts: the last rank's VALID
            # units (b_valid also gates it so fill/drain cycles skip the
            # unembed too — the head now runs exactly M times per step,
            # matching GPipe's count)
            val, (gb, go, gx) = unit_grad(blocks_v, outer_v, x_in_b, cot_buf,
                                          tgt_b,
                                          jnp.logical_and(is_last, b_valid))
            mask = b_valid.astype(jnp.float32)
            # rank 0's input cotangent is the embedding cotangent for mb b:
            # fold it into the outer grads NOW (inline vjp over one
            # microbatch) instead of collecting an O(M) cotangent buffer
            tok_b = lax.dynamic_index_in_dim(tok_mb, jnp.clip(b_idx, 0, m - 1),
                                             0, keepdims=False)
            keep0 = jnp.logical_and(b_valid, my == 0)
            ggx = jnp.where(keep0, gx, jnp.zeros_like(gx))
            _, vjp_embed = jax.vjp(lambda o: embed(o, tok_b), outer_v)
            (ge,) = vjp_embed(ggx.astype(feed.dtype))
            g_blocks = jax.tree.map(lambda acc, g: acc + mask * g, g_blocks, gb)
            g_outer = jax.tree.map(
                lambda acc, g1, g2: acc + mask * g1 + g2.astype(jnp.float32),
                g_outer, go, ge)
            loss = loss + jnp.where(jnp.logical_and(b_valid, is_last), val, 0.0)
            # ---- communication: activations down, cotangents up ----------
            fwd_buf = lax.ppermute(y_f, pp_axis, down_perm)
            cot_buf = lax.ppermute(gx.astype(edtype), pp_axis, up_perm)
            return (fwd_buf, cot_buf, acts, g_blocks, g_outer, loss), None

        (carry_out, _) = lax.scan(cycle, carry0, jnp.arange(cycles))
        _, _, _, g_blocks, g_outer_acc, loss_sum = carry_out

        # normalization matching the GPipe loss: global token count over dp
        wcount = lax.pcast(jnp.float32(b * (l - 1)), (dp_axis,), to="varying")
        denom = lax.psum(wcount, (dp_axis,))
        # grads accumulated SHARD-LOCALLY (params entered varying): one
        # explicit demotion to each param's sharding.  blocks are
        # pp-sharded dp-replicated -> sum over dp only; the outer tree's
        # two contributions live on different ranks (embedding on rank 0,
        # head on the last rank, zero elsewhere by masking), so the psum
        # over pp both combines them and replicates the result
        g_blocks = jax.tree.map(lambda g: lax.psum(g, (dp_axis,)) / denom,
                                g_blocks)
        g_outer = jax.tree.map(
            lambda g: lax.psum(g, (dp_axis, pp_axis)) / denom, g_outer_acc)
        loss = lax.psum(loss_sum, (dp_axis, pp_axis)) / denom

        grads = (g_outer, g_blocks)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def shard_fn(params, opt_state, tokens, targets):
        outer, blocks = params
        my = lax.axis_index(pp_axis)

        def global_loss(p):
            outer, blocks = p
            # stage slab arrives as [layers_per_stage, ...] (leading pp axis
            # stripped by shard_map); embedding is computed identically on
            # every rank but only rank 0's copy enters the pipeline
            b, l = tokens.shape
            mb = b // num_microbatches
            toks_mb = tokens.reshape(num_microbatches, mb, l)

            # Embed/head run outside the pipeline via TransformerLM's own
            # bound methods, so they share one source of truth (and the
            # exact param leaves) with the single-device __call__ path.
            # The block params are absent from `outer`, which is fine:
            # embed_tokens/head never touch them.
            x_emb = module.apply({"params": outer}, toks_mb.reshape(b, l),
                                 method="embed_tokens")
            x_emb = vary(x_emb.reshape(num_microbatches, mb, l, -1))
            e = x_emb.shape[-1]
            ticks = num_microbatches + pp - 1
            buf0 = vary(jnp.zeros((mb, l, e), x_emb.dtype))
            outs0 = vary(jnp.zeros_like(x_emb))

            def tick(carry, t):
                buf, outs = carry
                feed = lax.dynamic_index_in_dim(
                    x_emb, jnp.clip(t, 0, num_microbatches - 1), 0, keepdims=False)
                x_in = jnp.where(my == 0, feed, buf)
                # idle ranks/ticks compute on garbage; results are never
                # collected (GPipe bubble) — predication would not save
                # wall-clock on a SPMD schedule
                y = stage_apply(blocks, x_in)
                done_idx = t - (pp - 1)
                valid = jnp.logical_and(my == pp - 1, done_idx >= 0)
                new_outs = lax.dynamic_update_index_in_dim(
                    outs, y, jnp.clip(done_idx, 0, num_microbatches - 1), 0)
                outs = jnp.where(valid, new_outs, outs)
                buf = lax.ppermute(y, pp_axis, down_perm)
                return (buf, outs), None

            (buf, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
            # finished activations live on the last rank only; mask + psum
            # replicates them (making the rest of the loss pp-invariant)
            outs = lax.psum(jnp.where(my == pp - 1, outs, 0.0), pp_axis)

            logits = module.apply({"params": outer}, outs.reshape(b, l, e),
                                  method="head")
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), targets.astype(jnp.int32))
            wsum = jnp.sum(ce[:, :-1])
            wcount = jnp.float32(b * (l - 1))
            wcount = lax.pcast(wcount, (dp_axis,), to="varying")
            return lax.psum(wsum, (dp_axis,)) / lax.psum(wcount, (dp_axis,))

        loss, grads = jax.value_and_grad(global_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    outer_t, blocks_t = jax.eval_shape(
        lambda: split_block_params(spec.init_params(seed=0)))
    pspecs = pp_param_specs(outer_t, blocks_t, pp_axis)
    ospecs = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _opt_leaf_spec(path, pp_axis),
        jax.eval_shape(optimizer.init, (outer_t, blocks_t)))
    data_spec = P(dp_axis)
    sharded = jax.shard_map(
        shard_fn_1f1b if schedule == "1f1b" else shard_fn,
        mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def _opt_leaf_spec(path, pp_axis: str) -> P:
    """Optimizer-state leaves mirroring the (outer, blocks) params tuple.

    Optax states nest that tuple under namedtuple/tuple wrappers whose keys
    are also SequenceKeys, so walk from the leaf upward: the innermost
    SequenceKey (the params-tuple position, since everything below it is
    the flax dict tree) decides — index 1 is the pp-sharded block slab.
    Pure-scalar leaves (step counters) sit directly under state tuples and
    resolve to index 0 -> replicated, which is correct for them too.
    """
    for k in reversed(path):
        idx = getattr(k, "idx", None)
        if idx == 1:
            return P(pp_axis)
        if idx is not None:
            return P()
    return P()


def pp_state_shardings(mesh: Mesh, optimizer: optax.GradientTransformation,
                       outer: Dict[str, Any], blocks: Any,
                       pp_axis: str = "pp"):
    pspecs = pp_param_specs(outer, blocks, pp_axis)
    ospecs = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _opt_leaf_spec(path, pp_axis),
        jax.eval_shape(optimizer.init, (outer, blocks)))
    to_sh = lambda s: NamedSharding(mesh, s)
    return (jax.tree.map(to_sh, pspecs, is_leaf=lambda x: isinstance(x, P)),
            jax.tree.map(to_sh, ospecs, is_leaf=lambda x: isinstance(x, P)))
