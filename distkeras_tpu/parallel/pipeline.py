"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

No reference counterpart (the reference is data-parallel only, SURVEY.md
§2.13) — TPU-native headroom.  The design leans on two XLA facts:

1. A pipeline is just a rotation: each rank applies its resident stage
   (``num_layers / pp`` transformer blocks) to its current buffer, then
   ``lax.ppermute``s the activations one hop to the next rank.  Rank 0
   feeds a fresh microbatch each tick; the last rank collects finished
   microbatches.  ``M + pp - 1`` ticks drain ``M`` microbatches.
2. The backward schedule is NOT hand-written: differentiating through the
   tick scan reverses every ppermute (collective adjoints), which IS the
   backward pipeline.  ``jax.checkpoint`` around the stage keeps the
   per-tick residuals O(microbatch), the standard remat trade.

Layout: block params are stacked to [num_layers, ...] and sharded over pp
on the leading axis (each rank holds its stage's slab); embedding/unembed/
final-norm params are replicated — only rank 0's embedding output enters
the pipeline, so its gradient routes exclusively through rank 0's path.

Composes with data parallelism over a (dp, pp) mesh; tensor/sequence axes
compose at the block level and are left out of the v1 pipeline step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec, build_module
from distkeras_tpu.models.transformer import TransformerBlock


def split_block_params(params: Dict[str, Any]) -> Tuple[Dict[str, Any], Any]:
    """Full TransformerLM params -> (outer params, blocks stacked on axis 0).

    ``outer`` keeps the embedding / positional / final-norm leaves under
    their original names; ``blocks`` stacks ``block_0..block_{n-1}`` (all
    structurally identical) into one pytree with a leading layer axis.
    """
    names = sorted((k for k in params if k.startswith("block_")),
                   key=lambda k: int(k.split("_")[1]))
    if not names:
        raise ValueError("params contain no block_i subtrees; not a TransformerLM tree")
    blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *[params[k] for k in names])
    outer = {k: v for k, v in params.items() if not k.startswith("block_")}
    return outer, blocks


def merge_block_params(outer: Dict[str, Any], blocks: Any) -> Dict[str, Any]:
    """Inverse of ``split_block_params`` (for checkpointing / serialization)."""
    num_layers = jax.tree.leaves(blocks)[0].shape[0]
    params = dict(outer)
    for i in range(num_layers):
        params[f"block_{i}"] = jax.tree.map(lambda a, i=i: a[i], blocks)
    return params


def pp_param_specs(outer: Dict[str, Any], blocks: Any, pp_axis: str):
    outer_specs = jax.tree.map(lambda _: P(), outer)
    block_specs = jax.tree.map(lambda _: P(pp_axis), blocks)
    return outer_specs, block_specs


def make_pp_train_step(spec: ModelSpec, optimizer: optax.GradientTransformation,
                       mesh: Mesh, num_microbatches: int,
                       dp_axis: str = "dp", pp_axis: str = "pp") -> Callable:
    """Build a jitted ((outer, blocks), opt_state, tokens, targets) ->
    ((outer, blocks), opt_state, loss) pipeline-parallel training step.

    ``tokens``/``targets`` are [B, L] with B sharded over dp (and B a
    multiple of ``num_microbatches`` per dp shard); block params must be
    placed with ``pp_state_shardings``.
    """
    if spec.config.get("moe_experts"):
        raise ValueError("MoE FFN does not compose with pipeline parallelism "
                         "(v1); use make_moe_lm_train_step or a dense spec")
    pp = mesh.shape[pp_axis]
    num_layers = spec.config["num_layers"]
    if num_layers % pp:
        raise ValueError(f"num_layers {num_layers} not divisible by pp {pp}")
    layers_per_stage = num_layers // pp
    cfg = spec.config
    cdtype = cfg.get("compute_dtype", jnp.bfloat16)
    block = TransformerBlock(
        model_dim=cfg["model_dim"], num_heads=cfg["num_heads"],
        mlp_ratio=cfg.get("mlp_ratio", 4), seq_axis=None,
        attn_impl=cfg.get("attn_impl"), compute_dtype=cdtype)
    module = build_module(spec.name, dict(cfg, seq_axis=None))

    @jax.checkpoint
    def stage_apply(stage_params, x):
        """Apply this rank's ``layers_per_stage`` blocks (scan over the slab)."""

        def one(x, layer_params):
            return block.apply({"params": layer_params}, x), None

        x, _ = lax.scan(one, x, stage_params)
        return x

    def shard_fn(params, opt_state, tokens, targets):
        outer, blocks = params
        my = lax.axis_index(pp_axis)

        def global_loss(p):
            outer, blocks = p
            # stage slab arrives as [layers_per_stage, ...] (leading pp axis
            # stripped by shard_map); embedding is computed identically on
            # every rank but only rank 0's copy enters the pipeline
            b, l = tokens.shape
            mb = b // num_microbatches
            toks_mb = tokens.reshape(num_microbatches, mb, l)

            # Embed/head run outside the pipeline via TransformerLM's own
            # bound methods, so they share one source of truth (and the
            # exact param leaves) with the single-device __call__ path.
            # The block params are absent from `outer`, which is fine:
            # embed_tokens/head never touch them.
            x_emb = module.apply({"params": outer}, toks_mb.reshape(b, l),
                                 method="embed_tokens")
            x_emb = x_emb.reshape(num_microbatches, mb, l, -1)

            def vary(z):
                missing = tuple(a for a in (dp_axis, pp_axis)
                                if a not in jax.typeof(z).vma)
                return lax.pcast(z, missing, to="varying") if missing else z

            x_emb = vary(x_emb)
            e = x_emb.shape[-1]
            ticks = num_microbatches + pp - 1
            buf0 = vary(jnp.zeros((mb, l, e), x_emb.dtype))
            outs0 = vary(jnp.zeros_like(x_emb))

            def tick(carry, t):
                buf, outs = carry
                feed = lax.dynamic_index_in_dim(
                    x_emb, jnp.clip(t, 0, num_microbatches - 1), 0, keepdims=False)
                x_in = jnp.where(my == 0, feed, buf)
                # idle ranks/ticks compute on garbage; results are never
                # collected (GPipe bubble) — predication would not save
                # wall-clock on a SPMD schedule
                y = stage_apply(blocks, x_in)
                done_idx = t - (pp - 1)
                valid = jnp.logical_and(my == pp - 1, done_idx >= 0)
                new_outs = lax.dynamic_update_index_in_dim(
                    outs, y, jnp.clip(done_idx, 0, num_microbatches - 1), 0)
                outs = jnp.where(valid, new_outs, outs)
                perm = [(i, (i + 1) % pp) for i in range(pp)]
                buf = lax.ppermute(y, pp_axis, perm)
                return (buf, outs), None

            (buf, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
            # finished activations live on the last rank only; mask + psum
            # replicates them (making the rest of the loss pp-invariant)
            outs = lax.psum(jnp.where(my == pp - 1, outs, 0.0), pp_axis)

            logits = module.apply({"params": outer}, outs.reshape(b, l, e),
                                  method="head")
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), targets.astype(jnp.int32))
            wsum = jnp.sum(ce[:, :-1])
            wcount = jnp.float32(b * (l - 1))
            wcount = lax.pcast(wcount, (dp_axis,), to="varying")
            return lax.psum(wsum, (dp_axis,)) / lax.psum(wcount, (dp_axis,))

        loss, grads = jax.value_and_grad(global_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    outer_t, blocks_t = jax.eval_shape(
        lambda: split_block_params(spec.init_params(seed=0)))
    pspecs = pp_param_specs(outer_t, blocks_t, pp_axis)
    ospecs = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _opt_leaf_spec(path, pp_axis),
        jax.eval_shape(optimizer.init, (outer_t, blocks_t)))
    data_spec = P(dp_axis)
    sharded = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def _opt_leaf_spec(path, pp_axis: str) -> P:
    """Optimizer-state leaves mirroring the (outer, blocks) params tuple.

    Optax states nest that tuple under namedtuple/tuple wrappers whose keys
    are also SequenceKeys, so walk from the leaf upward: the innermost
    SequenceKey (the params-tuple position, since everything below it is
    the flax dict tree) decides — index 1 is the pp-sharded block slab.
    Pure-scalar leaves (step counters) sit directly under state tuples and
    resolve to index 0 -> replicated, which is correct for them too.
    """
    for k in reversed(path):
        idx = getattr(k, "idx", None)
        if idx == 1:
            return P(pp_axis)
        if idx is not None:
            return P()
    return P()


def pp_state_shardings(mesh: Mesh, optimizer: optax.GradientTransformation,
                       outer: Dict[str, Any], blocks: Any,
                       pp_axis: str = "pp"):
    pspecs = pp_param_specs(outer, blocks, pp_axis)
    ospecs = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _opt_leaf_spec(path, pp_axis),
        jax.eval_shape(optimizer.init, (outer, blocks)))
    to_sh = lambda s: NamedSharding(mesh, s)
    return (jax.tree.map(to_sh, pspecs, is_leaf=lambda x: isinstance(x, P)),
            jax.tree.map(to_sh, ospecs, is_leaf=lambda x: isinstance(x, P)))
