"""ZeRO-1 sharded optimizer state over the data-parallel axis.

Absent from the reference (SURVEY §2.13 lists ZeRO/FSDP-style sharding as
beyond-parity headroom) — on TPU it is the natural next step once data
parallelism exists: optimizer state is the largest training tensor after
the params (2x params for Adam), and replicating it across every replica
wastes exactly (N-1)/N of that HBM.

TPU-native formulation (the collectives ride ICI):

- params stay REPLICATED (this is ZeRO stage 1, not FSDP);
- the whole parameter pytree is raveled into one flat vector, padded to a
  multiple of the axis size, and each replica owns one contiguous shard
  of optimizer state (``1/N`` of Adam's moments);
- per step: each replica computes full gradients on its batch shard, a
  single ``psum_scatter`` both averages them AND hands each replica only
  its gradient shard (half the bytes of a full allreduce), the optimizer
  update runs on the local shard, and one ``all_gather`` rebuilds the
  replicated updated params.

Exactness: every optax transform used here (sgd, momentum, adam, ...) is
ELEMENTWISE over parameters, so updating disjoint shards on different
replicas is bit-identical to the replicated update — pinned by the
parity test against the plain DP step.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.base import ModelSpec


def _state_specs(optimizer: optax.GradientTransformation, shard_size: int,
                 axis: str) -> Any:
    """Per-leaf specs for the sharded optimizer state: vector leaves (adam
    moments etc.) shard over ``axis``; 0-d leaves (step counters) are
    identical on every replica and stay replicated."""
    shape = jax.eval_shape(optimizer.init, jnp.zeros((shard_size,), jnp.float32))
    return jax.tree.map(lambda l: P(axis) if l.ndim else P(), shape)


def make_zero_train_step(spec: ModelSpec, loss: Callable,
                         optimizer: optax.GradientTransformation, mesh: Mesh,
                         axis: str = "replica") -> Callable:
    """Build ``(params, opt_shard, x, y) -> (params, opt_shard, loss)``.

    ``params`` replicated; ``opt_shard`` is this step's sharded optimizer
    state — create it with :func:`zero_init_state`, place it with
    :func:`zero_state_sharding`.  ``x``/``y`` batch-sharded over ``axis``.

    .. warning:: ``optimizer`` must be ELEMENTWISE over parameters (sgd,
       momentum, adam, adamw, rmsprop ...).  Transforms that couple
       parameters globally — ``clip_by_global_norm``, LARS/LAMB trust
       ratios — would compute their statistic over only the local 1/N
       shard inside ``shard_map`` and silently diverge from replicated
       DP.  Apply such transforms to the full gradient BEFORE this step
       (or use the replicated trainers).
    """
    spec.reject_silent_aux("make_zero_train_step")
    spec.reject_rng_spec("make_zero_train_step")
    apply_fn = spec.apply_fn()
    n = mesh.shape[axis]
    template = jax.eval_shape(lambda: spec.init_params(seed=0))
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(template))
    padded = -(-total // n) * n
    shard_size = padded // n

    def shard_fn(params, opt_shard, x, y):
        flat0, unravel = ravel_pytree(params)

        def loss_fn(p):
            return loss(apply_fn(p, x), y)

        step_loss, grads = jax.value_and_grad(lambda p: loss_fn(p))(params)
        gflat, _ = ravel_pytree(grads)
        gflat = jnp.pad(gflat, (0, padded - total))
        # one collective: mean-reduce AND scatter — each replica receives
        # only its shard of the averaged gradient (allreduce would move 2x)
        gshard = lax.psum_scatter(gflat, axis, scatter_dimension=0, tiled=True) / n

        my = lax.axis_index(axis)
        pflat = jnp.pad(flat0, (0, padded - total))
        pshard = lax.dynamic_slice_in_dim(pflat, my * shard_size, shard_size)
        updates, opt_shard = optimizer.update(gshard, opt_shard, pshard)
        new_pshard = optax.apply_updates(pshard, updates)

        # rebuild replicated params: each replica contributes its updated
        # shard at its offset, psum concatenates AND yields the invariant
        # type the replicated out_spec needs (all_gather's result stays
        # device-varying under the vma system)
        contrib = lax.dynamic_update_slice_in_dim(
            jnp.zeros((padded,), new_pshard.dtype), new_pshard, my * shard_size, 0)
        new_flat = lax.psum(contrib, axis)[:total]
        new_params = unravel(new_flat)
        mean_loss = lax.psum(step_loss, axis) / n
        return new_params, opt_shard, mean_loss

    ospecs = _state_specs(optimizer, shard_size, axis)
    sharded = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), ospecs, P(axis), P(axis)),
        out_specs=(P(), ospecs, P()))
    return jax.jit(sharded, donate_argnums=(0, 1))


def zero_init_state(params: Any, optimizer: optax.GradientTransformation,
                    mesh: Mesh, axis: str = "replica") -> Any:
    """Sharded optimizer state: each replica holds only its shard of the
    vector leaves (1/N of the replicated state's memory).

    For the elementwise transforms this module supports, init over the
    padded flat params equals the concatenation of per-shard inits — so we
    jit the init with sharded OUT shardings and XLA allocates the state
    already distributed (the full replicated state, which for Adam is the
    2x-params tensor ZeRO exists to avoid, never materializes anywhere).
    """
    n = mesh.shape[axis]
    flat, _ = ravel_pytree(params)
    total = int(flat.size)
    padded = -(-total // n) * n
    shardings = zero_state_sharding(optimizer, params, mesh, axis)
    init = jax.jit(lambda f: optimizer.init(jnp.pad(f, (0, padded - total))),
                   out_shardings=shardings)
    return init(flat)


def zero_state_sharding(optimizer: optax.GradientTransformation, params: Any,
                        mesh: Mesh, axis: str = "replica"):
    """Per-leaf shardings for the opt-state pytree from zero_init_state."""
    n = mesh.shape[axis]
    flat, _ = ravel_pytree(params)
    shard_size = -(-int(flat.size) // n)
    specs = _state_specs(optimizer, shard_size, axis)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda v: isinstance(v, P))


def zero_data_sharding(mesh: Mesh, axis: str = "replica"):
    return NamedSharding(mesh, P(axis))
