"""Platform pinning — a LEAF module (imports nothing from this package).

Kept import-light on purpose: callers (tests/conftest.py, the driver's
multichip dry run, examples) must be able to pin the CPU platform before
any other module gets a chance to touch a JAX backend.  The package
``__init__`` is lazy (PEP 562) so ``from distkeras_tpu.platform import
pin_cpu_devices`` executes only this file.
"""

from __future__ import annotations

import os


def pin_cpu_devices(n: int) -> None:
    """Pin this process to an ``n``-device virtual CPU platform.

    The one shared copy of the CPU-simulation recipe (tests, examples, and
    the driver's multichip dry run all use it).  Two traps it handles:

    - The axon TPU sitecustomize forces ``jax_platforms='axon,cpu'`` via
      jax.config at interpreter start, so the ``JAX_PLATFORMS`` env var is
      ignored — only ``jax.config.update`` wins.  Touching the default
      backend first can hang on a held TPU, so CPU must be pinned before
      the first ``jax.devices()`` call.
    - ``--xla_force_host_platform_device_count`` is read once at CPU client
      creation; if a backend already exists (wrong platform or too few
      devices) the only fix is ``clear_backends()`` + ``jax_num_cpu_devices``
      (which takes precedence over the XLA flag).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if len(devs) < n or devs[0].platform != "cpu":
        from jax.extend.backend import clear_backends

        clear_backends()
        jax.config.update("jax_num_cpu_devices", n)
        devs = jax.devices()
    if len(devs) < n or devs[0].platform != "cpu":
        raise RuntimeError(f"could not materialize {n} CPU devices; have {devs}")
