"""Predictors (reference parity: ``distkeras/predictors.py``).

Reference: ``ModelPredictor.predict(dataframe)`` shipped a deserialized
Keras model to every partition and appended a raw prediction-vector column
via ``mapPartitions`` (SURVEY §3.3).  TPU-native: one jit'd apply function,
batched over the whole column on-device — optionally sharded over the data
axis of a mesh for multi-chip inference.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.base import Model


class Predictor:
    def __init__(self, model: Model, features_col: str = "features", output_col: str = "prediction"):
        self.model = model
        self.features_col = features_col
        self.output_col = output_col

    def predict(self, dataset: Dataset) -> Dataset:  # pragma: no cover - interface
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Appends ``output_col`` with the model's raw output vector per row."""

    def __init__(self, model: Model, features_col: str = "features", output_col: str = "prediction",
                 batch_size: int = 1024, mesh: Optional[Mesh] = None, data_axis: str = "replica",
                 quantize: bool = False, quantize_min_size: int = 4096):
        super().__init__(model, features_col, output_col)
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.data_axis = data_axis
        apply = model.spec.apply_fn()
        # unquantized serving reads model.params live at predict() time (a
        # predictor built once keeps serving a retrained model's weights);
        # quantize=True necessarily snapshots at construction
        self._params = None
        if quantize:
            # weight-only int8 (ops/quantize.py): HBM stores int8 + scales;
            # the in-graph dequant fuses into each weight's consumer, so
            # weight-read-bound inference sees ~4x less traffic vs f32.
            # quantize_min_size: smallest weight (elements) worth quantizing
            from distkeras_tpu.ops.quantize import dequantize_params, quantize_params

            self._params = quantize_params(model.params, min_size=quantize_min_size)
            inner = apply
            apply = lambda qp, x: inner(dequantize_params(qp), x)
        if mesh is not None:
            data_sharding = NamedSharding(mesh, P(data_axis))
            self._apply = jax.jit(apply, in_shardings=(NamedSharding(mesh, P()), data_sharding))
            self._shard = mesh.shape[data_axis]
        else:
            self._apply = jax.jit(apply)
            self._shard = 1

    def predict(self, dataset: Dataset) -> Dataset:
        x = dataset[self.features_col]
        n = len(x)
        # one static chunk shape for every call: batch_size rounded up to a
        # multiple of the mesh size (the sharded dim must divide evenly), and
        # short/final chunks padded up to it so jit sees a single shape
        bs = -(-self.batch_size // self._shard) * self._shard
        chunks = []
        for i in range(0, n, bs):
            chunk = x[i : i + bs]
            valid = len(chunk)
            if valid < bs:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], bs - valid, axis=0)], axis=0)
            params = self.model.params if self._params is None else self._params
            out = np.asarray(self._apply(params, jnp.asarray(chunk)))
            chunks.append(out[:valid])
        preds = np.concatenate(chunks, axis=0) if chunks else np.zeros((0,))
        return dataset.with_column(self.output_col, preds)
