"""Host-side runtime: networking, parameter-server hub, async trainers.

This package is the re-design of the reference's L3 communication layer
(``distkeras/networking.py`` + ``distkeras/parameter_servers.py``,
SURVEY.md §2.11–2.12) for deployments where the *synchronous on-chip*
re-expression of the algorithms (``distkeras_tpu.parallel``) is not
enough — genuine asynchrony across host processes over DCN, and the
Punchcard-style job-submission plane.

Two interchangeable parameter-server hubs speak one wire protocol:

- :mod:`distkeras_tpu.runtime.parameter_server` — pure-Python hub
  (thread per connection, like the reference — but pickle-free).
- :mod:`distkeras_tpu.runtime.native` — the same hub in C++
  (``native/ps_server.cpp``), loaded via ctypes: commits apply without
  the GIL, so concurrent workers do not serialize on the interpreter.
"""

from distkeras_tpu.runtime.faults import (  # noqa: F401
    ChaosProxy,
    Fault,
    FaultPlan,
    HubKillPlan,
    InjectedWorkerFault,
    ShardedChaosProxy,
    WorkerKillPlan,
)
from distkeras_tpu.runtime.networking import (  # noqa: F401
    FlatFrameCodec,
    ProtocolError,
    configure_socket,
    connect,
    determine_host_address,
    recv_frame,
    recv_frame_into,
    recv_json,
    recv_tensors,
    send_frame,
    send_json,
    send_tensors,
)
from distkeras_tpu.runtime.parameter_server import (  # noqa: F401
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    HubSnapshotter,
    InprocPSClient,
    PSClient,
    ReplicationFeed,
    ShardedParameterServer,
    ShardedPSClient,
    ShardPlan,
    SnapshotSetCoordinator,
    SocketParameterServer,
    StripeLostError,
    shard_plan,
)
